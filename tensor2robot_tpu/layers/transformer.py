"""Transformer blocks over the attention hot op.

Beyond the reference layer library (its temporal models top out at
SNAIL/TCN scale, layers/snail.py; SURVEY §5 long-context row): a standard
pre-norm transformer whose attention routes through ops/flash_attention —
single-device attention on the XLA einsum path below _FLASH_AUTO_SEQ
and the Pallas flash kernel above it (O(S^2) logits vs O(S) tiles; see
MultiHeadAttention.use_flash for the measured rationale), and
sequence-parallel attention when constructed with a mesh whose
`sequence` axis is >1 — the ring (parallel/ring_attention.py) by
default, or Ulysses all-to-all (parallel/ulysses_attention.py) via
`sequence_parallel_mode="ulysses"`; the mesh paths share the same
einsum-first dispatch policy (flash opt-in). Sequence length lives in
the specs, so the same model trains short episodes on one chip and long
contexts on a CP mesh without code changes.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tensor2robot_tpu.ops import flash_attention as flash_lib
from tensor2robot_tpu.parallel import mesh as mesh_lib

# Single-device auto-dispatch crossover: below this sequence length the
# XLA einsum path wins on measured speed; at/above it the einsum path's
# [S, S] logits (b8/h8 f32 at S=4096: ~4 GiB) OOM territory where the
# flash kernel's O(S) tiles still fit. The constant is shared with the
# sequence-parallel paths (same policy on the per-device attended
# length): ops/flash_attention.FLASH_AUTO_SEQ.
_FLASH_AUTO_SEQ = flash_lib.FLASH_AUTO_SEQ


class MultiHeadAttention(nn.Module):
    """Self-attention over [batch, seq, features].

    mesh: when given with a sequence axis > 1, attention runs
    sequence-parallel (the ring by default; `sequence_parallel_mode=
    "ulysses"` selects the all-to-all strategy); otherwise single-device
    attention via plain XLA (default) or the Pallas flash kernel
    (use_flash=True).
    """

    num_heads: int
    head_dim: int
    causal: bool = True
    mesh: Optional[object] = None
    # Attention kernel policy, tri-state:
    #   None (default) — auto. Single-device attention takes the XLA
    #     einsum path below _FLASH_AUTO_SEQ, measured FASTER than the
    #     Pallas flash kernel on the available chip (BENCH_FLASH_r03
    #     microbench: flash fwd 1.33 TFLOPS at b4/s2048/h8/d128 bf16,
    #     ~0.7% of peak; docs/PERFORMANCE.md); at seq >=
    #     _FLASH_AUTO_SEQ it switches to the flash kernel because the
    #     einsum path's [S, S] logits are O(S^2) HBM and OOM where
    #     flash's O(S) tiles still fit (the r4 A/B's expected einsum
    #     OOM at S=4096). Sequence-parallel (mesh) attention defaults
    #     to the einsum path too (ring/ulysses follow the same r3
    #     evidence; per-hop logits there are [S/N, S/N] shards, so the
    #     memory pressure is divided by the mesh).
    #   True — force the flash kernel everywhere (the O(S)-memory lever
    #     at any length).
    #   False — force the einsum path everywhere (long S may OOM).
    # The on-chip A/B (tools/validate_flash_tpu.py -> BENCH_FLASH_r05)
    # re-evaluates this default each capture.
    use_flash: Optional[bool] = None
    interpret: bool = False
    # Causal sliding window W (each query attends to its last W steps).
    # Works on every path: single-device flash tightens its k-block loop,
    # the ring truncates its rotation to the hops carrying visible tiles,
    # and ulysses passes W to its full-sequence local attention.
    window: Optional[int] = None
    # Context-parallel strategy when the mesh's sequence axis is >1:
    # "ring" (K/V rotate, O(seq/N) memory/device) or "ulysses" (head-
    # scatter all_to_all, one collective round, needs heads % N == 0).
    sequence_parallel_mode: str = "ring"
    # Incremental decoding: calls carry ONE new step ([B, 1, F]) which is
    # appended to a K/V cache ("cache" variable collection, capacity
    # decode_max_len) and attended against the cached prefix — the
    # streaming-serving mode (O(cache) per step; O(window) when a window
    # caps it). Requires causal=True and no sequence-parallel mesh.
    decode: bool = False
    decode_max_len: int = 2048
    # Grouped-query attention: num_kv_heads < num_heads shares each K/V
    # head across a GROUP of query heads (GQA, arXiv:2305.13245). The
    # projection and — the point for robots — the decode-mode K/V cache
    # shrink by the group factor; K/V are broadcast back to num_heads
    # only at attend time. None = num_heads (standard MHA).
    num_kv_heads: Optional[int] = None
    # Manual sequence parallelism: >1 means this attention already runs
    # INSIDE a shard_map whose manual axes include the sequence axis (the
    # pipelined encoder's per-device program) and its input is the LOCAL
    # sequence shard. Attention then rides the manual entry point of the
    # selected strategy — ring_attention.ring_attention_manual or
    # ulysses_attention.ulysses_attention_manual — over that axis instead
    # of opening its own shard_map (which cannot nest). The piece that
    # composes SP with PP (parallel/planner.py 3D plans).
    manual_sequence_size: int = 1

    def _kv_heads(self) -> int:
        kv = self.num_kv_heads if self.num_kv_heads is not None else self.num_heads
        if self.num_heads % kv != 0:
            raise ValueError(
                f"num_heads={self.num_heads} must be divisible by "
                f"num_kv_heads={kv}"
            )
        return kv

    def _expand_kv(self, t: jax.Array) -> jax.Array:
        """[B, S, KVH, D] -> [B, S, H, D] by repeating each kv head over
        its query group (no-op for standard MHA)."""
        groups = self.num_heads // t.shape[2]
        if groups == 1:
            return t
        return jnp.repeat(t, groups, axis=2)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        batch, seq, _ = x.shape
        features = self.num_heads * self.head_dim
        kv_heads = self._kv_heads()
        kv_features = kv_heads * self.head_dim
        qkv = nn.Dense(
            features + 2 * kv_features, use_bias=False, name="qkv"
        )(x)
        q, k, v = jnp.split(
            qkv, [features, features + kv_features], axis=-1
        )
        q = q.reshape(batch, seq, self.num_heads, self.head_dim)
        k = k.reshape(batch, seq, kv_heads, self.head_dim)
        v = v.reshape(batch, seq, kv_heads, self.head_dim)
        if self.decode:
            # The cache stores kv_heads only (the GQA memory win); the
            # group broadcast happens on the read inside _decode_step.
            out = self._decode_step(q, k, v)
            out = out.reshape(batch, seq, features)
            return nn.Dense(x.shape[-1], use_bias=False, name="out")(out)
        # Training/full-forward paths attend at full head count: the
        # flash/ring/ulysses kernels take equal q/k head dims.
        k, v = self._expand_kv(k), self._expand_kv(v)
        if self.sequence_parallel_mode not in ("ring", "ulysses"):
            # Validate eagerly — a typo must fail on the laptop run, not
            # only once the config reaches a multi-device CP mesh.
            raise ValueError(
                "sequence_parallel_mode must be 'ring' or 'ulysses', "
                f"got {self.sequence_parallel_mode!r}"
            )
        if self.manual_sequence_size > 1:
            if self.sequence_parallel_mode == "ulysses":
                from tensor2robot_tpu.parallel.ulysses_attention import (
                    ulysses_attention_manual,
                )

                out = ulysses_attention_manual(
                    q, k, v,
                    axis_name=mesh_lib.SEQUENCE_AXIS,
                    axis_size=self.manual_sequence_size,
                    causal=self.causal,
                    window=self.window,
                )
            else:
                from tensor2robot_tpu.parallel.ring_attention import (
                    ring_attention_manual,
                )

                out = ring_attention_manual(
                    q, k, v,
                    axis_name=mesh_lib.SEQUENCE_AXIS,
                    axis_size=self.manual_sequence_size,
                    causal=self.causal,
                    window=self.window,
                )
            out = out.reshape(batch, seq, features)
            return nn.Dense(x.shape[-1], use_bias=False, name="out")(out)
        sequence_axis = (
            dict(self.mesh.shape).get(mesh_lib.SEQUENCE_AXIS, 1)
            if self.mesh is not None
            else 1
        )
        if sequence_axis > 1 and self.sequence_parallel_mode == "ulysses":
            from tensor2robot_tpu.parallel.ulysses_attention import (
                ulysses_attention,
            )

            # The sequence-parallel paths KEEP their own None=auto flash
            # default (ring_attention.py:204): per-hop tiles materialize
            # S_local^2 logits on the einsum path, so flash there is a
            # memory lever first and the kernels' shape-fallback applies.
            out = ulysses_attention(
                q, k, v, mesh=self.mesh, causal=self.causal,
                use_flash=self.use_flash, interpret=self.interpret,
                window=self.window,
            )
        elif sequence_axis > 1:
            from tensor2robot_tpu.parallel.ring_attention import ring_attention

            out = ring_attention(
                q, k, v, mesh=self.mesh, causal=self.causal,
                use_flash=self.use_flash, interpret=self.interpret,
                window=self.window,
            )
        else:
            use_flash = self.use_flash
            if use_flash is None:
                # Auto: einsum wins on measured speed at moderate S, but
                # its [S, S] logits are O(S^2) HBM — above the threshold
                # only flash's O(S) tiles fit (use_flash docstring).
                use_flash = seq >= _FLASH_AUTO_SEQ
            if use_flash:
                out = flash_lib.flash_attention(
                    q, k, v, causal=self.causal, interpret=self.interpret,
                    window=self.window,
                )
            else:
                # Plain-XLA attention, measured faster on-chip than the
                # Pallas kernel at these sizes (use_flash docstring).
                out = flash_lib.reference_attention(
                    q, k, v, causal=self.causal, window=self.window
                )
        out = out.reshape(batch, seq, features)
        return nn.Dense(x.shape[-1], use_bias=False, name="out")(out)

    def _decode_step(self, q, k, v):
        """Appends this step's k/v to the cache and attends q against the
        cached prefix. One step per call ([B, 1, H, D]); with a window,
        attention reads only the last `window` cache slots (dynamic_slice
        with clamped start), so per-step cost is O(window) not O(max_len).

        Cache lifecycle: `init` RUNS the module, so the cache it returns
        has already consumed the init step — zero it before the first real
        step (`jax.tree_util.tree_map(jnp.zeros_like, variables["cache"])`)
        and thread the mutated collection between calls
        (`apply(..., mutable=["cache"])`).
        """
        if not self.causal:
            raise ValueError("decode mode requires causal=True")
        if self.mesh is not None and (
            dict(self.mesh.shape).get(mesh_lib.SEQUENCE_AXIS, 1) > 1
        ):
            raise ValueError(
                "decode mode is single-device (serving); drop the "
                "sequence-parallel mesh"
            )
        batch, seq, _, dim = q.shape
        kv_heads = k.shape[2]
        if seq != 1:
            raise ValueError(
                f"decode mode consumes ONE step per call, got seq={seq}; "
                "run the full-sequence forward for teacher forcing"
            )
        cached_k = self.variable(
            "cache", "cached_key",
            jnp.zeros, (batch, self.decode_max_len, kv_heads, dim), k.dtype,
        )
        cached_v = self.variable(
            "cache", "cached_value",
            jnp.zeros, (batch, self.decode_max_len, kv_heads, dim), v.dtype,
        )
        index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        i = index.value
        cached_k.value = lax.dynamic_update_slice(
            cached_k.value, k, (0, i, 0, 0)
        )
        cached_v.value = lax.dynamic_update_slice(
            cached_v.value, v, (0, i, 0, 0)
        )
        index.value = i + 1

        if self.window is not None:
            span = min(self.window, self.decode_max_len)
            # Last `span` slots ending at i (clamped at the left edge; the
            # global-position mask inside reference_attention hides any
            # pre-history the clamp drags in at the start of the episode).
            start = jnp.clip(i - span + 1, 0, self.decode_max_len - span)
            k_ctx = lax.dynamic_slice(
                cached_k.value, (0, start, 0, 0),
                (batch, span, kv_heads, dim),
            )
            v_ctx = lax.dynamic_slice(
                cached_v.value, (0, start, 0, 0),
                (batch, span, kv_heads, dim),
            )
        else:
            start = 0
            k_ctx, v_ctx = cached_k.value, cached_v.value
        # GQA: broadcast the cached kv heads to the query head count only
        # here, at attend time — the cache itself stays kv_heads wide.
        k_ctx, v_ctx = self._expand_kv(k_ctx), self._expand_kv(v_ctx)
        # The numerics oracle already speaks tiled global positions: the
        # single query sits at position i, the cache slice at `start`.
        return flash_lib.reference_attention(
            q.astype(jnp.float32),
            k_ctx.astype(jnp.float32),
            v_ctx.astype(jnp.float32),
            causal=True,
            q_offset=i,
            k_offset=start,
            window=self.window,
        ).astype(q.dtype)


class TransformerBlock(nn.Module):
    """Pre-norm block: x + MHA(LN(x)); x + FFN(LN(x)).

    The feed-forward is dense by default; `num_experts > 1` swaps in the
    expert-parallel MoE (layers/moe.py, experts sharded over the mesh's
    `expert` axis), whose router aux loss is accumulated into the
    "moe_aux_loss" collection for the caller's loss term.
    """

    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    causal: bool = True
    mesh: Optional[object] = None
    use_flash: Optional[bool] = None
    interpret: bool = False
    num_experts: int = 1
    num_selected_experts: int = 2
    sequence_parallel_mode: str = "ring"
    window: Optional[int] = None
    decode: bool = False
    decode_max_len: int = 2048
    num_kv_heads: Optional[int] = None
    manual_sequence_size: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x + MultiHeadAttention(
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            causal=self.causal,
            mesh=self.mesh,
            use_flash=self.use_flash,
            interpret=self.interpret,
            sequence_parallel_mode=self.sequence_parallel_mode,
            window=self.window,
            decode=self.decode,
            decode_max_len=self.decode_max_len,
            num_kv_heads=self.num_kv_heads,
            manual_sequence_size=self.manual_sequence_size,
            name="attention",
        )(nn.LayerNorm(name="ln_attn")(x))
        h = nn.LayerNorm(name="ln_mlp")(x)
        if self.num_experts > 1:
            from tensor2robot_tpu.layers.moe import MoEBlock

            h, aux_loss = MoEBlock(
                num_experts=self.num_experts,
                hidden_dim=self.mlp_ratio * x.shape[-1],
                num_selected=self.num_selected_experts,
                mesh=self.mesh,
                name="moe",
            )(h)
            self.sow("moe_aux_loss", "aux_loss", aux_loss)
        else:
            h = nn.Dense(self.mlp_ratio * x.shape[-1], name="mlp_in")(h)
            h = nn.gelu(h)
            h = nn.Dense(x.shape[-1], name="mlp_out")(h)
        return x + h


class PipelineStage(nn.Module):
    """The repeating unit of the pipelined encoder: a run of pre-norm
    blocks. Stage-internal attention is single-device by default; a
    sequence_axis_size > 1 (the DP x SP x PP composition) runs each
    block's attention as a MANUAL context-parallel strategy — ring K/V
    rotation or ulysses head-scatter, per sequence_parallel_mode — over
    the sequence axis, legal because the stage executes inside
    pipeline_apply's shard_map, where the sequence axis is manual
    alongside pipe."""

    num_blocks: int
    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    causal: bool = True
    use_flash: Optional[bool] = None
    interpret: bool = False
    window: Optional[int] = None
    num_kv_heads: Optional[int] = None
    sequence_axis_size: int = 1
    sequence_parallel_mode: str = "ring"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for i in range(self.num_blocks):
            x = TransformerBlock(
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                mlp_ratio=self.mlp_ratio,
                causal=self.causal,
                mesh=None,
                use_flash=self.use_flash,
                interpret=self.interpret,
                window=self.window,
                num_kv_heads=self.num_kv_heads,
                manual_sequence_size=self.sequence_axis_size,
                sequence_parallel_mode=self.sequence_parallel_mode,
                name=f"block_{i}",
            )(x)
        return x


class TransformerEncoder(nn.Module):
    """N pre-norm blocks with learned positional embeddings over
    [batch, seq, features]; final LayerNorm.

    pipeline_stages > 1 runs the block stack as a GPipe pipeline over the
    mesh's `pipe` axis (parallel/pipeline.py): the blocks split into
    equal stages whose stacked parameters live under the `pipe_stages`
    param key (sharded dim-0 over `pipe` by the trainer's sharding
    rules), and the batch streams through in `pipeline_microbatches`
    microbatches. Composes with the data axis and with sequence
    parallelism (ring or ulysses, run manually inside the pipeline's
    shard_map); mutually exclusive with MoE inside the pipelined stack.
    """

    num_layers: int
    num_heads: int
    head_dim: int
    max_seq_len: int = 2048
    mlp_ratio: int = 4
    causal: bool = True
    mesh: Optional[object] = None
    use_flash: Optional[bool] = None
    interpret: bool = False
    num_experts: int = 1
    num_selected_experts: int = 2
    sequence_parallel_mode: str = "ring"
    pipeline_stages: int = 1
    pipeline_microbatches: Optional[int] = None
    window: Optional[int] = None
    decode: bool = False
    num_kv_heads: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        batch, seq, features = x.shape
        if seq > self.max_seq_len:
            raise ValueError(
                f"sequence length {seq} exceeds max_seq_len={self.max_seq_len}"
            )
        positions = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (self.max_seq_len, features),
        )
        if self.decode:
            return self._decode_step(x, positions)
        x = x + positions[None, :seq, :]
        if self.pipeline_stages > 1:
            x = self._pipelined_blocks(x)
        else:
            for i in range(self.num_layers):
                x = self._block(i)(x)
        return nn.LayerNorm(name="ln_final")(x)

    def _block(self, i: int, decode: bool = False) -> "TransformerBlock":
        """One stack block; the decode twin differs only in cache mode
        (identical param naming, so trained variables slot straight in)."""
        return TransformerBlock(
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            mlp_ratio=self.mlp_ratio,
            causal=self.causal,
            mesh=self.mesh,
            use_flash=self.use_flash,
            interpret=self.interpret,
            num_experts=self.num_experts,
            num_selected_experts=self.num_selected_experts,
            sequence_parallel_mode=self.sequence_parallel_mode,
            window=self.window,
            decode=decode,
            decode_max_len=self.max_seq_len,
            num_kv_heads=self.num_kv_heads,
            name=f"block_{i}",
        )

    def _decode_step(self, x: jax.Array, positions: jax.Array) -> jax.Array:
        """One incremental step: positional embedding at the episode
        position (own cache counter), then the block stack in decode mode
        (each attention appends to its K/V cache). Mutate the "cache"
        collection across calls: `module.apply(..., mutable=["cache"])`.
        """
        if self.pipeline_stages > 1:
            raise ValueError("decode mode does not compose with pipelining")
        pos = self.variable(
            "cache", "position", lambda: jnp.zeros((), jnp.int32)
        )
        step = lax.dynamic_slice(
            positions, (pos.value, 0), (1, positions.shape[1])
        )
        pos.value = pos.value + 1
        x = x + step[None]
        for i in range(self.num_layers):
            x = self._block(i, decode=True)(x)
        return nn.LayerNorm(name="ln_final")(x)

    def _pipelined_blocks(self, x: jax.Array) -> jax.Array:
        """Blocks as a GPipe schedule over the mesh's pipe axis."""
        from tensor2robot_tpu.parallel import mesh as mesh_mod
        from tensor2robot_tpu.parallel import pipeline

        stages = self.pipeline_stages
        if self.num_layers % stages != 0:
            raise ValueError(
                f"num_layers={self.num_layers} not divisible by "
                f"pipeline_stages={stages}"
            )
        if self.num_experts > 1:
            raise ValueError(
                "pipeline_stages > 1 does not compose with MoE feed-"
                "forwards (the router aux-loss channel does not cross the "
                "pipeline schedule)"
            )
        if self.mesh is None:
            raise ValueError("pipeline_stages > 1 requires a mesh")
        mesh_axes = dict(self.mesh.shape)
        if mesh_axes.get(mesh_mod.PIPE_AXIS, 1) != stages:
            raise ValueError(
                f"mesh pipe axis {mesh_axes.get(mesh_mod.PIPE_AXIS, 1)} "
                f"!= pipeline_stages={stages}"
            )
        seq_size = mesh_axes.get(mesh_mod.SEQUENCE_AXIS, 1)
        if seq_size > 1 and self.sequence_parallel_mode not in (
            "ring", "ulysses"
        ):
            raise ValueError(
                "pipeline_stages > 1 composes with sequence parallelism "
                "in ring or ulysses mode (the in-shard_map manual "
                "strategies); got "
                f"sequence_parallel_mode={self.sequence_parallel_mode!r}"
            )
        if (
            seq_size > 1
            and self.sequence_parallel_mode == "ulysses"
            and self.num_heads % seq_size != 0
        ):
            raise ValueError(
                f"ulysses inside the pipeline needs num_heads="
                f"{self.num_heads} divisible by the sequence axis size "
                f"{seq_size} (each device owns whole heads after the "
                "all_to_all scatter); use ring mode otherwise"
            )
        if seq_size > 1 and x.shape[1] % seq_size != 0:
            raise ValueError(
                f"sequence length {x.shape[1]} not divisible by the "
                f"sequence axis size {seq_size}"
            )

        def make_stage(sequence_axis_size: int) -> PipelineStage:
            return PipelineStage(
                num_blocks=self.num_layers // stages,
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                mlp_ratio=self.mlp_ratio,
                causal=self.causal,
                use_flash=self.use_flash,
                interpret=self.interpret,
                window=self.window,
                num_kv_heads=self.num_kv_heads,
                sequence_axis_size=sequence_axis_size,
                sequence_parallel_mode=self.sequence_parallel_mode,
            )

        # The applied stage runs the manual context-parallel strategy
        # (ring or ulysses) when the mesh shards the sequence; init runs
        # OUTSIDE pipeline_apply's shard_map (no
        # manual axes yet), so it uses a single-device twin — attention
        # strategy does not change the parameter structure.
        stage = make_stage(seq_size)
        init_stage = make_stage(1)
        batch = x.shape[0]
        data_size = mesh_axes.get(mesh_mod.DATA_AXIS, 1)
        if self.pipeline_microbatches is not None:
            micro = self.pipeline_microbatches
            if batch % micro != 0:
                raise ValueError(
                    f"batch {batch} not divisible by pipeline_microbatches="
                    f"{micro}"
                )
        else:
            # Default: the largest valid microbatch count up to 2*S (~33%
            # bubble). Valid = divides the batch AND leaves each
            # microbatch's example dim divisible by the data axis
            # (pipeline_apply shards it there under dp x pp).
            if batch % data_size != 0:
                raise ValueError(
                    f"batch {batch} not divisible by data axis {data_size}"
                )
            limit = batch // data_size
            micro = max(
                d
                for d in range(1, min(limit, 2 * stages) + 1)
                if limit % d == 0
            )

        def init_stacked(rng):
            dummy = jnp.zeros((1,) + x.shape[1:], x.dtype)
            rngs = jax.random.split(rng, stages)
            return pipeline.stack_stage_params(
                [init_stage.init(r, dummy)["params"] for r in rngs]
            )

        stacked = self.param(mesh_mod.PIPE_STAGES_KEY, init_stacked)
        return pipeline.pipeline_apply(
            lambda p, h: stage.apply({"params": p}, h),
            stacked,
            x,
            mesh=self.mesh,
            num_microbatches=micro,
            batch_axis=mesh_mod.DATA_AXIS if data_size > 1 else None,
            sequence_axis=(
                mesh_mod.SEQUENCE_AXIS if seq_size > 1 else None
            ),
        )
