"""Vision towers: conv feature extractors + pose heads, flax-native.

Behavioral reference: tensor2robot/layers/vision_layers.py:31-351
(BuildImagesToFeaturesModel / BuildFILMParams /
BuildImagesToFeaturesModelHighRes / BuildImageFeaturesToPoseModel).

Conventions kept from the reference: VALID-padded 3x3 convs, strides (2, 2,
1, 1, ...) over num_blocks, 32 channels per block, optional FiLM with
(1 + gamma) * x + beta applied pre-ReLU, final 1x1 conv to num_output_maps,
optional spatial softmax returning [x1..xN, y1..yN] feature points.
All convs are NHWC and bf16-safe; XLA maps them onto the MXU.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import flax.linen as nn

from tensor2robot_tpu.layers.batch_norm import BatchNorm
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.spatial_softmax import spatial_softmax
from tensor2robot_tpu.ops import pooling


def apply_film(x: jax.Array, film_gamma_beta: Optional[jax.Array]) -> jax.Array:
    """FiLM modulation (1 + gamma) * x + beta with [batch, 2C] params
    (reference film_resnet_model.py:109-120)."""
    if film_gamma_beta is None:
        return x
    gamma, beta = jnp.split(film_gamma_beta[:, None, None, :], 2, axis=-1)
    return (1.0 + gamma) * x + beta


class FilmParams(nn.Module):
    """Linear FiLM generator (reference BuildFILMParams,
    vision_layers.py:163-183)."""

    film_output_size: int = 2 * 5 * 32

    @nn.compact
    def __call__(self, embedding: jax.Array) -> jax.Array:
        return nn.Dense(self.film_output_size, name="film")(embedding)


class ImagesToFeaturesNet(nn.Module):
    """Conv tower: images [B, H, W, C] in [0, 1] -> feature points or maps
    (reference BuildImagesToFeaturesModel, vision_layers.py:31-160).

    Returns (features, extra): with spatial softmax, features is
    [B, 2 * num_output_maps] and extra = {'softmax': maps}; without, features
    is the [B, h, w, num_output_maps] activation and extra = {}.
    """

    filter_size: int = 3
    num_blocks: int = 5
    num_output_maps: int = 32
    num_channels_per_block: int = 32
    use_spatial_softmax: bool = True
    normalizer: str = "layer_norm"  # 'layer_norm' | 'batch_norm' | 'none'

    def _normalize(self, x: jax.Array, train: bool, scale: bool, idx: str) -> jax.Array:
        if self.normalizer == "layer_norm":
            return nn.LayerNorm(use_scale=scale, name=f"norm_{idx}")(x)
        if self.normalizer == "batch_norm":
            return BatchNorm(
                use_running_average=not train,
                momentum=0.99,
                epsilon=1e-4,
                use_scale=scale,
                name=f"norm_{idx}",
            )(x)
        return x

    @nn.compact
    def __call__(
        self,
        images: jax.Array,
        train: bool = False,
        film_output_params: Optional[jax.Array] = None,
    ):
        film_gamma_betas = [None] * self.num_blocks
        if film_output_params is not None:
            expected = 2 * self.num_blocks * self.num_channels_per_block
            if film_output_params.ndim != 2 or film_output_params.shape[-1] != expected:
                raise ValueError(
                    f"FiLM params shape {film_output_params.shape}, expected"
                    f" [batch, {expected}]"
                )
            film_gamma_betas = jnp.split(
                film_output_params, self.num_blocks, axis=-1
            )

        net = images
        for i in range(self.num_blocks):
            stride = 2 if i < 2 else 1
            net = nn.Conv(
                self.num_channels_per_block,
                (self.filter_size, self.filter_size),
                strides=(stride, stride),
                padding="VALID",
                use_bias=True,
                bias_init=nn.initializers.constant(0.01),
                kernel_init=nn.initializers.xavier_uniform(),
                name=f"conv{i + 2}",
            )(net)
            net = self._normalize(net, train, scale=False, idx=f"conv{i + 2}")
            net = apply_film(net, film_gamma_betas[i])
            net = nn.relu(net)

        net = nn.Conv(
            self.num_output_maps,
            (1, 1),
            padding="VALID",
            use_bias=True,
            bias_init=nn.initializers.constant(0.01),
            kernel_init=nn.initializers.xavier_uniform(),
            name="final_conv_1x1",
        )(net)
        net = self._normalize(net, train, scale=True, idx="final")
        net = nn.relu(net)
        if self.use_spatial_softmax:
            points, softmax = spatial_softmax(net)
            return points, {"softmax": softmax}
        return net, {}


class ImagesToFeaturesHighResNet(nn.Module):
    """Multi-resolution conv tower: block outputs at every scale are resized
    to the highest resolution and summed before the spatial softmax
    (reference BuildImagesToFeaturesModelHighRes, vision_layers.py:186-275;
    PI-GPS architecture, arXiv:1610.00529)."""

    filter_size: int = 3
    num_blocks: int = 5
    num_output_maps: int = 32

    @nn.compact
    def __call__(self, images: jax.Array, train: bool = False):
        block_outs = []
        net = nn.avg_pool(images, (2, 2), strides=(2, 2), padding="VALID")
        net = nn.Conv(
            16,
            (self.filter_size, self.filter_size),
            strides=(2, 2),
            padding="VALID",
            name="conv1",
        )(net)
        net = nn.relu(nn.LayerNorm(name="norm1")(net))
        net = nn.Conv(
            32,
            (self.filter_size, self.filter_size),
            padding="VALID",
            name="conv2",
        )(net)
        net = nn.relu(nn.LayerNorm(name="norm2")(net))
        block_outs.append(nn.Conv(32, (1, 1), name="conv2_1x1")(net))
        for i in range(1, self.num_blocks):
            # Non-overlapping pool: backend-dispatched backward
            # (ops/pooling.py; SelectAndScatter on TPU per DIAG_STEP_r05).
            net = pooling.max_pool(net, (2, 2), "VALID")
            net = nn.Conv(
                32,
                (self.filter_size, self.filter_size),
                padding="VALID",
                name=f"conv{i + 2}",
            )(net)
            net = nn.relu(nn.LayerNorm(name=f"norm{i + 2}")(net))
            block_outs.append(
                nn.Conv(32, (1, 1), name=f"conv{i + 2}_1x1")(net)
            )

        target_hw = block_outs[0].shape[1:3]
        resized = [
            jax.image.resize(
                b,
                (b.shape[0], target_hw[0], target_hw[1], b.shape[3]),
                method="nearest",
            )
            for b in block_outs
        ]
        net = sum(resized)
        net = nn.Conv(self.num_output_maps, (1, 1), name="final_conv_1x1")(net)
        points, softmax = spatial_softmax(net)
        return points, {"softmax": softmax}


class ImageFeaturesToPoseNet(nn.Module):
    """FC head mapping feature points (+aux input) to a pose vector, with the
    MAML-friendly learned bias transform (reference
    BuildImageFeaturesToPoseModel, vision_layers.py:278-351)."""

    num_outputs: Optional[int]
    aux_output_dim: int = 0
    hidden_dim: int = 100
    num_layers: int = 2
    bias_transform_size: int = 10

    @nn.compact
    def __call__(
        self,
        expected_feature_points: jax.Array,
        aux_input: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, Optional[jax.Array]]:
        net = expected_feature_points
        if aux_input is not None:
            net = jnp.concatenate([net, aux_input], axis=1)
        if self.bias_transform_size > 0:
            bias_transform = self.param(
                "bias_transform",
                nn.initializers.constant(0.01),
                (self.bias_transform_size,),
            )
            tiled = jnp.broadcast_to(
                bias_transform, (net.shape[0], self.bias_transform_size)
            ).astype(net.dtype)
            net = jnp.concatenate([net, tiled], axis=1)
        dense_kwargs = dict(
            bias_init=nn.initializers.constant(0.01),
            kernel_init=nn.initializers.truncated_normal(stddev=0.01),
        )
        for layer_index in range(self.num_layers):
            net = nn.Dense(
                self.hidden_dim, name=f"pose_fc{layer_index}", **dense_kwargs
            )(net)
            net = nn.relu(nn.LayerNorm(name=f"pose_ln{layer_index}")(net))
        if self.num_outputs:
            net = nn.Dense(
                self.num_outputs, name=f"pose_fc{self.num_layers}", **dense_kwargs
            )(net)
        aux_output = None
        if self.aux_output_dim > 0:
            aux_output = nn.Dense(
                self.aux_output_dim, name="pose_fc_aux", **dense_kwargs
            )(expected_feature_points)
        return net, aux_output
