from tensor2robot_tpu.meta_learning import meta_example, meta_tfdata
from tensor2robot_tpu.meta_learning.maml_inner_loop import (
    MAMLInnerLoopGradientDescent,
)
from tensor2robot_tpu.meta_learning.maml_model import MAMLModel
from tensor2robot_tpu.meta_learning.meta_models import (
    MetalearningModel,
    MetaPreprocessor,
    create_meta_spec,
    select_mode,
)
from tensor2robot_tpu.meta_learning.meta_policies import (
    FixedLengthSequentialRegressionPolicy,
    MAMLCEMPolicy,
    MAMLRegressionPolicy,
    MetaLearningPolicy,
    ScheduledExplorationMAMLRegressionPolicy,
)
from tensor2robot_tpu.meta_learning.preprocessors import (
    FixedLenMetaExamplePreprocessor,
    MAMLPreprocessorV2,
    create_maml_feature_spec,
    create_maml_label_spec,
    create_metaexample_spec,
    stack_intra_task_episodes,
)
from tensor2robot_tpu.meta_learning.run_meta_env import run_meta_env
