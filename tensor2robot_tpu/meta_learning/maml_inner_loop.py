"""MAML inner-loop gradient descent, functional JAX form.

Behavioral reference: tensor2robot/meta_learning/maml_inner_loop.py:28-328.
The reference needed a variable-intercepting custom getter to swap
`var - lr*grad` tensors into a TF graph; with explicit parameter pytrees the
same algorithm is just `jax.grad` + tree arithmetic:

  for each condition step:  params' = params - lr * grad(inner_loss)
  final monitored step      (forward only, tracks adaptation progress)
  conditioned val pass      (adapted params)   — the MAML objective
  unconditioned val pass    (original params)  — for diagnostics

Second-order gradients come for free by differentiating through the update;
`use_second_order=False` stops the gradient on the inner grads (FOMAML,
reference :143-188). Per-variable learned inner learning rates are scalar
leaves in a pytree mirroring the adapted params (reference :83-95), carried
as ordinary meta-parameters so the outer optimizer trains them.
"""

from __future__ import annotations

from typing import Any, Callable, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from tensor2robot_tpu.utils.keypath import path_string

PyTree = Any


class MAMLInnerLoopGradientDescent:
    """Configurable inner-loop SGD (reference class :28-328).

    Args:
      learning_rate: inner-loop step size (initial value when learned).
      use_second_order: backprop through the inner gradients; False = FOMAML.
      var_scope: '/'-joined path prefix selecting which params adapt; others
        stay frozen in the inner loop (outer loop still trains everything).
      learn_inner_lr: per-variable learned LRs initialized at learning_rate.
    """

    def __init__(
        self,
        learning_rate: float = 0.001,
        use_second_order: bool = True,
        var_scope: Optional[str] = None,
        learn_inner_lr: bool = False,
    ):
        self._learning_rate = learning_rate
        self._use_second_order = use_second_order
        self._var_scope = var_scope
        self._learn_inner_lr = learn_inner_lr

    @property
    def learn_inner_lr(self) -> bool:
        return self._learn_inner_lr

    def create_inner_lr_params(self, base_params: PyTree) -> PyTree:
        """Per-variable scalar LRs (empty dict when not learned) — meta-params
        the outer optimizer trains (reference _get_learning_rate :83-95)."""
        if not self._learn_inner_lr:
            return {}
        return jax.tree_util.tree_map(
            lambda _: jnp.asarray(self._learning_rate, jnp.float32),
            base_params,
        )

    def _adapts(self, path) -> bool:
        if self._var_scope is None:
            return True
        return path_string(path).startswith(self._var_scope)

    def _apply_update(
        self, params: PyTree, grads: PyTree, inner_lrs: PyTree
    ) -> PyTree:
        def update(path, p, g, *lr):
            if not self._adapts(path):
                return p
            rate = lr[0] if lr else self._learning_rate
            return p - rate * g

        if self._learn_inner_lr and inner_lrs:
            return jax.tree_util.tree_map_with_path(
                update, params, grads, inner_lrs
            )
        return jax.tree_util.tree_map_with_path(update, params, grads)

    def inner_loop(
        self,
        base_variables: Mapping[str, Any],
        inputs_list: Sequence[Tuple[Any, Any]],
        inference_network_fn: Callable,
        model_train_fn: Callable,
        mode: str,
        inner_lrs: Optional[PyTree] = None,
        inner_inference_network_fn: Optional[Callable] = None,
        inner_model_train_fn: Optional[Callable] = None,
    ):
        """Runs len(inputs_list)-1 adaptation steps (reference :213-328).

        Args:
          base_variables: base-model variable collections; ['params'] adapts.
          inputs_list: ((cond_f, cond_l),)*k + ((val_f, val_l),); the last
            entry is validation data never used for inner gradients.
          inference_network_fn: base model forward,
            (variables, features, mode, labels=...) -> (outputs,
            mutable_updates).
            Mutable updates (batch-stats) are discarded inside the loop —
            the reference's while_loop had the same batch-norm caveat
            (maml_model.py:300-304).
          model_train_fn: (features, labels, outputs, mode) -> loss or
            (loss, metrics).
          mode: train/eval/predict.
          inner_lrs: learned per-variable LR pytree (when learn_inner_lr).
          inner_inference_network_fn: optional distinct forward for the
            adaptation steps and the unconditioned val pass (the reference's
            params['is_inner_loop'] switch, e.g. domain-adaptive models
            withholding inputs in the inner loop); the conditioned val pass
            always uses `inference_network_fn`.
          inner_model_train_fn: optional distinct inner-step loss (the
            reference's learned-loss models keyed off params flags).

        Returns:
          ([unconditioned_val_outputs, conditioned_val_outputs],
           inner_outputs (k+1 entries), inner_losses (k+1 entries)).
        """
        base_variables = dict(base_variables)
        original_params = base_variables["params"]
        inner_forward_fn = inner_inference_network_fn or inference_network_fn
        inner_train_fn = inner_model_train_fn or model_train_fn

        def forward(params, features, labels=None, fn=None):
            variables = dict(base_variables)
            variables["params"] = params
            outputs, _ = (fn or inference_network_fn)(
                variables, features, mode, labels=labels
            )
            return outputs

        def step_loss(params, features, labels):
            outputs = forward(params, features, labels, fn=inner_forward_fn)
            result = inner_train_fn(features, labels, outputs, mode)
            loss = result[0] if isinstance(result, tuple) else result
            return loss, outputs

        adapted = original_params
        inner_outputs: List[Any] = []
        inner_losses: List[jax.Array] = []
        for features, labels in inputs_list[:-1]:
            (loss, outputs), grads = jax.value_and_grad(
                step_loss, has_aux=True
            )(adapted, features, labels)
            inner_outputs.append(outputs)
            inner_losses.append(loss)
            if not self._use_second_order:
                grads = jax.lax.stop_gradient(grads)
            adapted = self._apply_update(adapted, grads, inner_lrs)

        # Final monitored pass on the last condition data: did adaptation
        # help? (reference :291-306). Forward-only, no gradient step.
        final_features, final_labels = inputs_list[-2]
        final_loss, final_outputs = step_loss(
            adapted, final_features, final_labels
        )
        inner_outputs.append(final_outputs)
        inner_losses.append(final_loss)

        val_features, val_labels = inputs_list[-1]
        conditioned = forward(adapted, val_features, val_labels)
        unconditioned = forward(
            original_params, val_features, val_labels, fn=inner_forward_fn
        )
        return [unconditioned, conditioned], inner_outputs, inner_losses
