"""MAML as model composition: wraps any base T2RModel.

Behavioral reference: tensor2robot/meta_learning/maml_model.py:71-549.
The reference mapped a graph-building `task_learn` over the task batch with
tf.map_fn + dtype inference in a throwaway graph; here the same structure is
`jax.vmap` of a functional inner loop — no dtype inference, no custom
getters, and second-order gradients flow through the vmap for free
(SURVEY.md §3.5 mapping).

Meta variables are structured {'params': {'base': ..., 'inner_lrs': ...}},
so learned inner learning rates are ordinary meta-parameters trained by the
outer optimizer alongside the base model weights.

TPU notes: vmap turns the per-task inner loops into one batched XLA program
(k+2 forward passes + k backward passes, all MXU-batched across tasks); the
[tasks, samples] dims flatten into single large batches for the outer loss.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

from tensor2robot_tpu.meta_learning import meta_tfdata, preprocessors
from tensor2robot_tpu.meta_learning.maml_inner_loop import (
    MAMLInnerLoopGradientDescent,
)
from tensor2robot_tpu.models.abstract_model import (
    MODE_TRAIN,
    AbstractT2RModel,
)
from tensor2robot_tpu.specs import TensorSpecStruct


class MAMLModel(AbstractT2RModel):
    """Base class for MAML-style meta models (reference MAMLModel :71-549).

    Subclasses implement `_select_inference_output` to pick the
    `condition_output` / `inference_output` keys meta policies consume.
    """

    def __init__(
        self,
        base_model: AbstractT2RModel,
        preprocessor_cls=None,
        num_inner_loop_steps: int = 1,
        var_scope: Optional[str] = None,
        inner_learning_rate: float = 0.001,
        use_second_order: bool = True,
        learn_inner_lr: bool = False,
        **kwargs,
    ):
        kwargs.setdefault("device_type", base_model.device_type)
        super().__init__(**kwargs)
        self._base_model = base_model
        self._maml_preprocessor_cls = preprocessor_cls
        self._num_inner_loop_steps = max(1, num_inner_loop_steps)
        self._inner_loop = MAMLInnerLoopGradientDescent(
            learning_rate=inner_learning_rate,
            use_second_order=use_second_order,
            var_scope=var_scope,
            learn_inner_lr=learn_inner_lr,
        )

    @property
    def base_model(self) -> AbstractT2RModel:
        return self._base_model

    @property
    def num_inner_loop_steps(self) -> int:
        return self._num_inner_loop_steps

    # -- specs ----------------------------------------------------------------

    @property
    def preprocessor(self):
        cls = self._maml_preprocessor_cls or preprocessors.MAMLPreprocessorV2
        preprocessor = cls(self._base_model.preprocessor)
        if not isinstance(preprocessor, preprocessors.MAMLPreprocessorV2):
            raise ValueError(
                "Only MAMLPreprocessorV2 subclasses are supported."
            )
        return preprocessor

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        return preprocessors.create_maml_feature_spec(
            self._base_model.get_feature_specification(mode),
            self._base_model.get_label_specification(mode),
        )

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        return preprocessors.create_maml_label_spec(
            self._base_model.get_label_specification(mode)
        )

    def get_feature_specification_for_packing(self, mode: str):
        return self._base_model.preprocessor.get_in_feature_specification(mode)

    def get_label_specification_for_packing(self, mode: str):
        return self._base_model.preprocessor.get_in_label_specification(mode)

    # -- variables ------------------------------------------------------------

    def init_variables(self, rng, features, mode: str = MODE_TRAIN):
        """Initializes the base model on one task's condition batch and adds
        the learned inner-LR meta-params."""

        def concrete(leaf):
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return jnp.zeros(leaf.shape, leaf.dtype)
            return jnp.asarray(leaf)

        cond = jax.tree_util.tree_map(
            lambda x: concrete(x)[0], features.condition.features
        )
        base_variables = dict(
            self._base_model.init_variables(rng, cond, mode)
        )
        base_params = base_variables.pop("params")
        variables = dict(base_variables)
        variables["params"] = {
            "base": base_params,
            "inner_lrs": self._inner_loop.create_inner_lr_params(base_params),
        }
        return variables

    def _base_variables(self, variables: Mapping[str, Any]) -> Dict[str, Any]:
        base = {
            k: v for k, v in variables.items() if k != "params"
        }
        base["params"] = variables["params"]["base"]
        return base

    # -- forward --------------------------------------------------------------

    def inference_network_fn(
        self, variables, features, mode, rng=None, labels=None
    ):
        base_variables = self._base_variables(variables)
        inner_lrs = variables["params"].get("inner_lrs") or None
        k = self._num_inner_loop_steps

        def base_inference(vars_, task_features, mode_, labels=None):
            # rng is shared across tasks/steps (flax folds in module paths;
            # per-task decorrelation would need per-task keys plumbed
            # through vmap — not needed for the current model zoo).
            return self._base_model.inference_network_fn(
                vars_, task_features, mode_, rng, labels=labels
            )

        # Optional inner-loop-specific hooks on the base model (the
        # reference's params['is_inner_loop'] switch): a distinct forward
        # and/or a distinct adaptation loss (learned-loss models).
        inner_forward = getattr(
            self._base_model, "inner_inference_network_fn", None
        )
        inner_train = getattr(self._base_model, "model_inner_loop_fn", None)

        def task_learn(cond_features, cond_labels, inf_features, inf_labels):
            # The val entry carries the real meta labels (per-task inference
            # labels) when available so label-consuming base networks (NLL
            # decoder heads) see targets of the right shape; condition
            # labels are only a structural placeholder in PREDICT
            # (reference's unused_inference_labels, maml_model.py:292-296).
            val_labels = inf_labels if inf_labels is not None else cond_labels
            inputs_list = ((cond_features, cond_labels),) * k + (
                (inf_features, val_labels),
            )
            (uncond, cond), inner_outputs, inner_losses = (
                self._inner_loop.inner_loop(
                    base_variables,
                    inputs_list,
                    base_inference,
                    self._base_model.model_train_fn,
                    mode,
                    inner_lrs=inner_lrs,
                    inner_inference_network_fn=inner_forward,
                    inner_model_train_fn=inner_train,
                )
            )
            return uncond, cond, tuple(inner_outputs), tuple(inner_losses)

        uncond, cond, inner_outputs, inner_losses = jax.vmap(task_learn)(
            features.condition.features,
            features.condition.labels,
            features.inference.features,
            labels,
        )

        predictions = TensorSpecStruct()
        for key, value in inner_outputs[0].items():
            predictions[f"full_condition_output/{key}"] = value
        for pos, step_output in enumerate(inner_outputs):
            for key, value in step_output.items():
                predictions[f"full_condition_outputs/output_{pos}/{key}"] = value
        for key, value in uncond.items():
            predictions[f"full_inference_output_unconditioned/{key}"] = value
        for key, value in cond.items():
            predictions[f"full_inference_output/{key}"] = value
        for pos, loss in enumerate(inner_losses):
            predictions[f"inner_losses/step_{pos}"] = loss

        predictions = self._select_inference_output(predictions)
        if "condition_output" not in predictions:
            raise ValueError(
                "The required condition_output is not in predictions "
                f"{list(predictions.keys())}."
            )
        if "inference_output" not in predictions:
            raise ValueError(
                "The required inference_output is not in predictions "
                f"{list(predictions.keys())}."
            )
        return predictions, {}

    @abc.abstractmethod
    def _select_inference_output(
        self, predictions: TensorSpecStruct
    ) -> TensorSpecStruct:
        """Assigns `condition_output` and `inference_output` from the full
        outputs (reference :356-371)."""

    # -- losses ---------------------------------------------------------------

    def model_train_fn(self, features, labels, inference_outputs, mode):
        """Outer loss: the base loss on conditioned inference outputs over
        the flattened [task, samples] batch (reference :415-496)."""
        inference_flat = meta_tfdata.flatten_batch_examples(
            inference_outputs.full_inference_output
        )
        features_flat = meta_tfdata.flatten_batch_examples(
            features.inference.features
        )
        labels_flat = meta_tfdata.flatten_batch_examples(labels)
        loss, metrics = self._base_model.model_train_fn(
            features_flat, labels_flat, inference_flat, mode
        )
        out_metrics = dict(metrics)
        for pos in range(self._num_inner_loop_steps + 1):
            out_metrics[f"inner_loss_{pos}"] = jnp.mean(
                inference_outputs[f"inner_losses/step_{pos}"]
            )
        return loss, out_metrics

    def model_eval_fn(self, features, labels, inference_outputs):
        inference_flat = meta_tfdata.flatten_batch_examples(
            inference_outputs.full_inference_output
        )
        features_flat = meta_tfdata.flatten_batch_examples(
            features.inference.features
        )
        labels_flat = meta_tfdata.flatten_batch_examples(labels)
        return self._base_model.model_eval_fn(
            features_flat, labels_flat, inference_flat
        )

    def create_optimizer(self):
        return self._base_model.create_optimizer()
