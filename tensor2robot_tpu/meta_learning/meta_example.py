"""MetaExample assembly: one proto holding a task's episodes as
prefixed feature columns.

Behavioral reference: tensor2robot/meta_learning/meta_example.py:28-66.
Episode i of the condition (inference) set contributes all its features
under `condition_ep<i>/<name>` (`inference_ep<i>/<name>`) — the layout
`create_metaexample_spec` parses back (preprocessors.py:287-312).
"""

from __future__ import annotations

from typing import Sequence, Union

from tensor2robot_tpu.proto import example_pb2

Example = Union["example_pb2.Example", "example_pb2.SequenceExample"]


def append_example(meta_example, ep_example, prefix: str) -> None:
    """Copies every feature of `ep_example` into `meta_example` with
    `<prefix>/` prepended to the key (reference :47-53)."""
    target = meta_example.features.feature
    for key, feature in ep_example.features.feature.items():
        target[f"{prefix}/{key}"].CopyFrom(feature)


def append_sequence_example(meta_example, ep_example, prefix: str) -> None:
    """SequenceExample variant: prefixes both context features and
    feature_lists (reference :56-66)."""
    context = meta_example.context.feature
    for key, feature in ep_example.context.feature.items():
        context[f"{prefix}/{key}"].CopyFrom(feature)
    lists = meta_example.feature_lists.feature_list
    for key, feature_list in ep_example.feature_lists.feature_list.items():
        lists[f"{prefix}/{key}"].CopyFrom(feature_list)


def make_meta_example(
    condition_examples: Sequence[Example],
    inference_examples: Sequence[Example],
) -> Example:
    """Builds one MetaExample from per-episode examples (reference :28-45)."""
    if isinstance(condition_examples[0], example_pb2.Example):
        meta_example = example_pb2.Example()
        append_fn = append_example
    else:
        meta_example = example_pb2.SequenceExample()
        append_fn = append_sequence_example
    for i, example in enumerate(condition_examples):
        append_fn(meta_example, example, f"condition_ep{i}")
    for i, example in enumerate(inference_examples):
        append_fn(meta_example, example, f"inference_ep{i}")
    return meta_example
