"""Legacy TrainValPair meta-learning path, TPU-native.

Behavioral reference: tensor2robot/meta_learning/meta_tf_models.py
(`select_mode` :51, `_create_meta_spec` :61, `MetaPreprocessor` :121,
`MetalearningModel` :239). This is the V1 meta-learning surface the
reference itself later superseded with `MAMLPreprocessorV2` (this repo's
`meta_learning/preprocessors.py`); it is ported for config/class parity so
legacy RL^2-style models have the same base to inherit from.

Semantics: every feature/label spec is wrapped into a TrainValPair — a
`train/`-prefixed branch, a `val/`-prefixed branch, and a boolean
`val_mode` switch. BOTH branches get their serialized names rewritten
with the branch prefix (exactly the reference's copy_tensorspec
semantics, tensorspec_utils.py:755-780), so the input pipeline writes
`train/<name>` / `val/<name>` features and the auto-generated parser
maps each branch to its own serialized inputs. Both branches
are non-optional (the reference pins this because graph-mode loops needed
identical inputs each iteration; here it keeps the parser contract total).
The network hooks stay abstract exactly as in the reference ("Inherit from
this class to implement a custom RL^2 model"): subclasses combine the two
branches, typically via `select_mode`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.meta_learning import meta_tfdata
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    copy_tensorspec,
    flatten_spec_structure,
)


def select_mode(val_mode, train, val):
    """Per-element switch between the train and val branches.

    Reference select_mode :51-60 (tf.where over the flattened dicts).
    `val_mode` is a boolean of shape [], [tasks] or [tasks, 1]; it is
    right-broadcast against each leaf, so whole tasks switch branches.
    Leaves must have matching shapes across branches (the reference
    inherits the same requirement from tf.where).
    """
    train_flat = flatten_spec_structure(train)
    val_flat = flatten_spec_structure(val)
    train_keys = set(train_flat.keys())
    val_keys = set(val_flat.keys())
    if train_keys != val_keys:
        # The reference's nest.map_structure raised on any structure
        # mismatch; silently dropping a val-only leaf would corrupt
        # val-mode tasks downstream.
        raise ValueError(
            "select_mode requires identical train/val structures; "
            f"train-only: {sorted(train_keys - val_keys)}, "
            f"val-only: {sorted(val_keys - train_keys)}"
        )
    out = TensorSpecStruct()
    for key in train_flat:
        t, v = train_flat[key], val_flat[key]
        cond = jnp.asarray(val_mode).reshape(
            (-1,) + (1,) * (jnp.ndim(t) - 1)
            if jnp.ndim(val_mode) > 0
            else ()
        )
        out[key] = jnp.where(cond, v, t)
    return out


def create_meta_spec(
    tensor_spec,
    spec_type: str,
    num_train_samples_per_task: Optional[int],
    num_val_samples_per_task: Optional[int],
) -> TensorSpecStruct:
    """Wraps a spec structure into a flattened TrainValPair spec.

    Reference _create_meta_spec :61-118: both branches' serialized names
    are rewritten with the branch prefix (`train/<name>`, `val/<name>`)
    so each branch maps to its own serialized inputs; both branches are
    forced non-optional; a boolean `val_mode` switch is added per spec
    type.
    """
    if spec_type not in ("features", "labels"):
        raise ValueError(
            'We only support spec_type "features" or "labels" '
            f"but received {spec_type}."
        )
    train_spec = flatten_spec_structure(
        copy_tensorspec(
            tensor_spec, batch_size=num_train_samples_per_task, prefix="train"
        )
    )
    for key, value in train_spec.items():
        train_spec[key] = ExtendedTensorSpec.from_spec(
            value, is_optional=False
        )
    val_spec = flatten_spec_structure(
        copy_tensorspec(
            tensor_spec, batch_size=num_val_samples_per_task, prefix="val"
        )
    )
    for key, value in val_spec.items():
        val_spec[key] = ExtendedTensorSpec.from_spec(value, is_optional=False)

    val_mode_shape = () if num_train_samples_per_task is None else (1,)
    out = TensorSpecStruct()
    out.train = train_spec
    out.val = val_spec
    out.val_mode = ExtendedTensorSpec(
        shape=val_mode_shape,
        dtype=np.bool_,
        name=f"val_mode/{spec_type}",
    )
    return flatten_spec_structure(out)


class MetaPreprocessor(AbstractPreprocessor):
    """Wraps a base preprocessor's contract into TrainValPairs.

    Reference MetaPreprocessor :121-237. The transform flattens each
    branch's [tasks, samples, ...] leaves to a flat batch, applies the
    base preprocessor per branch (train and val see independent rng
    streams), and restores the task structure.
    """

    def __init__(
        self,
        base_preprocessor: AbstractPreprocessor,
        num_train_samples_per_task: int,
        num_val_samples_per_task: int,
    ):
        super().__init__()
        self._base_preprocessor = base_preprocessor
        self._num_train_samples_per_task = num_train_samples_per_task
        self._num_val_samples_per_task = num_val_samples_per_task

    @property
    def base_preprocessor(self) -> AbstractPreprocessor:
        return self._base_preprocessor

    @property
    def num_train_samples_per_task(self) -> int:
        return self._num_train_samples_per_task

    @property
    def num_val_samples_per_task(self) -> int:
        return self._num_val_samples_per_task

    def get_in_feature_specification(self, mode):
        return create_meta_spec(
            self._base_preprocessor.get_in_feature_specification(mode),
            "features",
            self._num_train_samples_per_task,
            self._num_val_samples_per_task,
        )

    def get_in_label_specification(self, mode):
        return create_meta_spec(
            self._base_preprocessor.get_in_label_specification(mode),
            "labels",
            self._num_train_samples_per_task,
            self._num_val_samples_per_task,
        )

    def get_out_feature_specification(self, mode):
        return create_meta_spec(
            self._base_preprocessor.get_out_feature_specification(mode),
            "features",
            self._num_train_samples_per_task,
            self._num_val_samples_per_task,
        )

    def get_out_label_specification(self, mode):
        return create_meta_spec(
            self._base_preprocessor.get_out_label_specification(mode),
            "labels",
            self._num_train_samples_per_task,
            self._num_val_samples_per_task,
        )

    def _preprocess_fn(self, features, labels, mode, rng):
        if mode is None:
            raise ValueError("The mode should never be None.")
        rng_train, rng_val = (
            jax.random.split(rng) if rng is not None else (None, None)
        )
        flat_train_features = meta_tfdata.flatten_batch_examples(
            features.train
        )
        flat_val_features = meta_tfdata.flatten_batch_examples(features.val)
        flat_train_labels = flat_val_labels = None
        if labels is not None:
            flat_train_labels = meta_tfdata.flatten_batch_examples(
                labels.train
            )
            flat_val_labels = meta_tfdata.flatten_batch_examples(labels.val)

        train_features_out, train_labels_out = (
            self._base_preprocessor.preprocess(
                flat_train_features, flat_train_labels, mode=mode,
                rng=rng_train,
            )
        )
        val_features_out, val_labels_out = self._base_preprocessor.preprocess(
            flat_val_features, flat_val_labels, mode=mode, rng=rng_val
        )

        out_features = TensorSpecStruct()
        out_features.train = meta_tfdata.unflatten_batch_examples(
            train_features_out, self._num_train_samples_per_task
        )
        out_features.val = meta_tfdata.unflatten_batch_examples(
            val_features_out, self._num_val_samples_per_task
        )
        out_features.val_mode = jnp.reshape(features.val_mode, (-1, 1))
        out_labels = None
        if labels is not None:
            out_labels = TensorSpecStruct()
            out_labels.train = meta_tfdata.unflatten_batch_examples(
                train_labels_out, self._num_train_samples_per_task
            )
            out_labels.val = meta_tfdata.unflatten_batch_examples(
                val_labels_out, self._num_val_samples_per_task
            )
            out_labels.val_mode = jnp.reshape(labels.val_mode, (-1, 1))
        return out_features, out_labels


class MetalearningModel(AbstractT2RModel):
    """Base class for legacy TrainValPair meta models (e.g. RL^2).

    Reference MetalearningModel :239-320: wraps a base model, exposes the
    TrainValPair spec surface, and leaves the network/train hooks to
    subclasses, which minimize some `L_val(update(L_train))`.
    """

    def __init__(
        self,
        base_model: AbstractT2RModel,
        num_train_samples_per_task: int,
        num_val_samples_per_task: int,
        preprocessor_cls=None,
        **kwargs,
    ):
        super().__init__(preprocessor_cls=preprocessor_cls, **kwargs)
        self._base_model = base_model
        self._num_train_samples_per_task = num_train_samples_per_task
        self._num_val_samples_per_task = num_val_samples_per_task

    @property
    def base_model(self) -> AbstractT2RModel:
        return self._base_model

    @property
    def default_preprocessor_cls(self):
        return MetaPreprocessor

    @property
    def preprocessor(self) -> AbstractPreprocessor:
        preprocessor_cls = self._preprocessor_cls
        if preprocessor_cls is None:
            preprocessor_cls = self.default_preprocessor_cls
        return preprocessor_cls(
            self._base_model.preprocessor,
            num_train_samples_per_task=self._num_train_samples_per_task,
            num_val_samples_per_task=self._num_val_samples_per_task,
        )

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        return create_meta_spec(
            self._base_model.get_feature_specification(mode),
            "features",
            self._num_train_samples_per_task,
            self._num_val_samples_per_task,
        )

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        return create_meta_spec(
            self._base_model.get_label_specification(mode),
            "labels",
            self._num_train_samples_per_task,
            self._num_val_samples_per_task,
        )

    def flatten_and_add_meta_dim(
        self, train_data, val_data, val_mode
    ) -> TensorSpecStruct:
        """Packs one task's data into a flattened TrainValPair with the
        meta (tasks) dimension prepended — the on-robot inference path
        (reference _flatten_and_add_meta_dim :297-320)."""
        pair = TensorSpecStruct()
        pair.train = flatten_spec_structure(train_data)
        pair.val = flatten_spec_structure(val_data)
        pair.val_mode = val_mode
        flat = flatten_spec_structure(pair)
        for key in flat.train:
            flat.train[key] = np.expand_dims(flat.train[key], 0)
        for key in flat.val:
            flat.val[key] = np.expand_dims(flat.val[key], 0)
        return flat
