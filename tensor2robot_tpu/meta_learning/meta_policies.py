"""Meta-learning policies: fast adaptation via conditioning episodes.

Behavioral reference: tensor2robot/meta_learning/meta_policies.py:27-201.
A MetaLearningPolicy carries the current task's conditioning episode
(`adapt(episode_data)` / `reset_task()`); every action query feeds both the
conditioning data and the live observation, and the exported MAML model runs
its inner-loop adaptation inside the serving function — the robot never
computes gradients itself.

The conditioning data rides through the policy's pack_fn as the `context`
argument (this framework's equivalent of the reference's
`pack_features(state, prev_episode_data, timestep)` convention).
"""

from __future__ import annotations

import abc
from typing import Any, Optional

import numpy as np

from tensor2robot_tpu.policies.policies import (
    CEMPolicy,
    Policy,
    RegressionPolicy,
    ScheduledExplorationRegressionPolicy,
)


class MetaLearningPolicy(Policy):
    """Adds task-adaptation state to a policy (reference :27-37)."""

    _prev_episode_data: Optional[Any] = None

    def reset_task(self) -> None:
        self._prev_episode_data = None

    def adapt(self, episode_data) -> None:
        """Stores the conditioning episode(s) for the current task."""
        self._prev_episode_data = episode_data

    @property
    def prev_episode_data(self):
        return self._prev_episode_data


class MAMLCEMPolicy(MetaLearningPolicy, CEMPolicy):
    """CEM policy over a MAML critic: conditioning data joins the CEM
    objective features each query (reference MAMLCEMPolicy :40-94). Before
    the first adaptation the Q estimate is meaningless, so it is zeroed
    (the reference's `q_values *= 0` guard) — actions are then effectively
    random draws from the proposal."""

    def _objective_fn(self, features):
        objective = super()._objective_fn(features)
        if self._prev_episode_data is not None:
            return objective
        return lambda samples: objective(samples) * 0.0

    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        features = self._pack(state, self._prev_episode_data, timestep)
        return self.get_cem_action(features)


class _MAMLRegressionActionMixin(MetaLearningPolicy):
    """Shared MAML action selection: feed conditioning data, read the MAML
    model's required `inference_output`, drop the episode(/time) dims."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("action_key", "inference_output")
        super().__init__(*args, **kwargs)

    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        features = self._pack(state, self._prev_episode_data, timestep)
        action = self._predict_action(features)
        # MAML outputs carry [inference_episode(, time), action] dims.
        if action.ndim == 3:
            return action[0, 0]
        if action.ndim == 2:
            return action[0]
        return action


class MAMLRegressionPolicy(_MAMLRegressionActionMixin, RegressionPolicy):
    """Feeds condition episode + live observation (reference :98-132)."""

    def sample_action(self, obs, explore_prob: float = 0.0):
        del explore_prob
        action = self.SelectAction(obs, None, 0)
        # Replay writers require is_demo when forming MetaExamples.
        return action, {"is_demo": False}


class FixedLengthSequentialRegressionPolicy(MetaLearningPolicy, RegressionPolicy):
    """Fixed-episode-length sequential policy: a_t is the t'th output of the
    model conditioned on the demo + the current episode so far
    (reference :136-163)."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("action_key", "inference_output")
        super().__init__(*args, **kwargs)
        self._current_episode_data = None
        self._t = 0

    def reset(self) -> None:
        self._current_episode_data = None
        self._t = 0

    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        features = self._pack(
            state, (self._prev_episode_data, self._current_episode_data),
            self._t,
        )
        batch = {k: np.asarray(v)[None, ...] for k, v in features.items()}
        out = self._predictor.predict(batch)
        action = np.asarray(out[self._action_key])[0]
        self._current_episode_data = features
        # [inference_episode, T, action_dim] -> step t.
        action = action[0, self._t]
        self._t += 1
        return action


class ScheduledExplorationMAMLRegressionPolicy(
    _MAMLRegressionActionMixin, ScheduledExplorationRegressionPolicy
):
    """MAMLRegressionPolicy + linearly-scheduled gaussian action noise
    (reference :167-201). Noise/clip logic lives in the scheduled base;
    this class only tags the MetaExample demo flag."""

    def sample_action(self, obs, explore_prob: float = 0.0):
        action, debug = super().sample_action(obs, explore_prob)
        debug["is_demo"] = False
        return action, debug
