"""Task-batched data utilities for meta-learning.

Behavioral reference: tensor2robot/meta_learning/meta_tfdata.py. Meta
batches carry two leading dims — [num_tasks, num_samples_per_task, ...] —
and these helpers move structures between that layout and the flat
[num_tasks * num_samples, ...] layout base models expect. All are pure
jnp reshapes, so they fuse into surrounding jitted programs; `multi_batch_apply`
is the workhorse models use to run image ops over [task, time] dims
(reference :222-281).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

PyTree = Any


def _is_array(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def flatten_batch_examples(structure: PyTree) -> PyTree:
    """[num_tasks, num_samples, ...] -> [num_tasks * num_samples, ...]
    (reference flatten_batch_examples :174-199; rank-1 tensors pass
    through untouched, matching the reference's per-task scalars)."""

    def reshape(x):
        if not _is_array(x) or x.ndim <= 1:
            return x
        return jnp.reshape(x, (-1,) + tuple(x.shape[2:]))

    return jax.tree_util.tree_map(reshape, structure)


def unflatten_batch_examples(structure: PyTree, num_samples_per_task: int) -> PyTree:
    """[num_tasks * num_samples, ...] -> [num_tasks, num_samples, ...]
    (reference :201-219)."""

    def reshape(x):
        if not _is_array(x):
            return x
        return jnp.reshape(
            x, (-1, num_samples_per_task) + tuple(x.shape[1:])
        )

    return jax.tree_util.tree_map(reshape, structure)


def merge_first_n_dims(structure: PyTree, n: int) -> PyTree:
    """Collapses the first n dims of every array (reference :222-238).
    Scalars (0-d) pass through — they carry no batch dims to merge."""

    def reshape(x):
        if not _is_array(x) or x.ndim == 0:
            return x
        return jnp.reshape(x, (-1,) + tuple(x.shape[n:]))

    return jax.tree_util.tree_map(reshape, structure)


def expand_batch_dims(structure: PyTree, batch_sizes: Sequence[int]) -> PyTree:
    """Re-expands the first dim of every array to `batch_sizes`
    (reference :241-257). Scalars (0-d, e.g. reduced losses) pass through.

    Dims stay as-is (no int() coercion): under jax.export shape polymorphism
    a batch dim is symbolic and jnp.reshape consumes it directly — coercing
    would break batch-polymorphic serving of episode-batched models."""
    batch_sizes = tuple(batch_sizes)

    def reshape(x):
        if not _is_array(x) or x.ndim == 0:
            return x
        return jnp.reshape(x, batch_sizes + tuple(x.shape[1:]))

    return jax.tree_util.tree_map(reshape, structure)


def multi_batch_apply(
    f: Callable, num_batch_dims: int, *args, **kwargs
) -> PyTree:
    """Runs `f` (which expects one batch dim) over inputs with
    `num_batch_dims` leading batch dims, restoring them on the outputs
    (reference :260-281). Unlike vmap this is a single reshaped call, so
    batch-norm and other cross-batch ops see the full flattened batch."""
    leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves((args, kwargs))
        if _is_array(leaf)
    ]
    if not leaves:
        raise ValueError("multi_batch_apply needs at least one array input.")
    batch_sizes = leaves[0].shape[:num_batch_dims]
    merged_args = merge_first_n_dims(args, num_batch_dims)
    merged_kwargs = merge_first_n_dims(kwargs, num_batch_dims)
    outputs = f(*merged_args, **merged_kwargs)
    return expand_batch_dims(outputs, batch_sizes)


def split_train_val(
    structure: PyTree, num_train_samples_per_task: int
) -> Tuple[PyTree, PyTree]:
    """Splits the per-task samples dim into (train, val) structures
    (reference split_train_val :130-151)."""

    def train_part(x):
        return x[:, :num_train_samples_per_task] if _is_array(x) else x

    def val_part(x):
        return x[:, num_train_samples_per_task:] if _is_array(x) else x

    return (
        jax.tree_util.tree_map(train_part, structure),
        jax.tree_util.tree_map(val_part, structure),
    )


def tile_val_mode(structure: PyTree, num_tiles: int) -> PyTree:
    """Tiles val samples along the per-task samples dim (reference
    tile_val_mode :154-171)."""

    def tile(x):
        if not _is_array(x):
            return x
        reps = (1, num_tiles) + (1,) * (x.ndim - 2)
        return jnp.tile(x, reps)

    return jax.tree_util.tree_map(tile, structure)
