"""Meta-learning spec builders and preprocessors.

Behavioral reference: tensor2robot/meta_learning/preprocessors.py.
Meta specs nest a base model's contract into:

  features.condition.features / features.condition.labels   (adaptation data)
  features.inference.features                               (evaluation data)
  labels (meta_labels prefix)                               (outer-loss labels)

with an explicit per-task samples dim prepended to every spec. The
MetaExample layout stores each episode of a task as `<prefix>_ep<i>/<name>`
feature columns of one example (reference create_metaexample_spec :287-312).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from tensor2robot_tpu.meta_learning import meta_tfdata
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    copy_tensorspec,
    flatten_spec_structure,
)


def create_maml_feature_spec(feature_spec, label_spec) -> TensorSpecStruct:
    """Meta feature spec from base specs: condition carries features+labels,
    inference carries features; every spec gains a per-task samples dim and
    a routing prefix (reference create_maml_feature_spec :34-66)."""
    condition_spec = TensorSpecStruct()
    condition_spec.features = flatten_spec_structure(
        copy_tensorspec(feature_spec, batch_size=-1, prefix="condition_features")
    )
    condition_spec.labels = flatten_spec_structure(
        copy_tensorspec(label_spec, batch_size=-1, prefix="condition_labels")
    )
    inference_spec = TensorSpecStruct()
    inference_spec.features = flatten_spec_structure(
        copy_tensorspec(feature_spec, batch_size=-1, prefix="inference_features")
    )
    meta_feature_spec = TensorSpecStruct()
    meta_feature_spec.condition = condition_spec
    meta_feature_spec.inference = inference_spec
    return meta_feature_spec


def create_maml_label_spec(label_spec) -> TensorSpecStruct:
    """Outer-loss label spec (reference :69-81)."""
    return flatten_spec_structure(
        copy_tensorspec(label_spec, batch_size=-1, prefix="meta_labels")
    )


class MAMLPreprocessorV2(AbstractPreprocessor):
    """Wraps a base preprocessor's contract into meta shape; the transform
    flattens [task, samples] to a flat batch, applies the base preprocessor,
    and restores the task structure (reference MAMLPreprocessorV2 :84-285).
    """

    def __init__(self, base_preprocessor: AbstractPreprocessor):
        super().__init__()
        self._base_preprocessor = base_preprocessor

    @property
    def base_preprocessor(self) -> AbstractPreprocessor:
        return self._base_preprocessor

    def get_in_feature_specification(self, mode):
        return create_maml_feature_spec(
            self._base_preprocessor.get_in_feature_specification(mode),
            self._base_preprocessor.get_in_label_specification(mode),
        )

    def get_in_label_specification(self, mode):
        return create_maml_label_spec(
            self._base_preprocessor.get_in_label_specification(mode)
        )

    def get_out_feature_specification(self, mode):
        return create_maml_feature_spec(
            self._base_preprocessor.get_out_feature_specification(mode),
            self._base_preprocessor.get_out_label_specification(mode),
        )

    def get_out_label_specification(self, mode):
        return create_maml_label_spec(
            self._base_preprocessor.get_out_label_specification(mode)
        )

    def _preprocess_fn(self, features, labels, mode, rng):
        cond_feature = list(features.condition.features.values())[0]
        inf_feature = list(features.inference.features.values())[0]
        num_condition = cond_feature.shape[1]
        num_inference = inf_feature.shape[1]

        rng_cond, rng_inf = (
            jax.random.split(rng) if rng is not None else (None, None)
        )
        flat_cond_features = meta_tfdata.flatten_batch_examples(
            features.condition.features
        )
        flat_cond_labels = meta_tfdata.flatten_batch_examples(
            features.condition.labels
        )
        flat_inf_features = meta_tfdata.flatten_batch_examples(
            features.inference.features
        )
        flat_labels = (
            meta_tfdata.flatten_batch_examples(labels)
            if labels is not None
            else None
        )

        cond_features_out, cond_labels_out = self._base_preprocessor.preprocess(
            flat_cond_features, flat_cond_labels, mode=mode, rng=rng_cond
        )
        inf_features_out, labels_out = self._base_preprocessor.preprocess(
            flat_inf_features, flat_labels, mode=mode, rng=rng_inf
        )

        out = TensorSpecStruct()
        condition = TensorSpecStruct()
        condition.features = meta_tfdata.unflatten_batch_examples(
            cond_features_out, num_condition
        )
        condition.labels = meta_tfdata.unflatten_batch_examples(
            cond_labels_out, num_condition
        )
        inference = TensorSpecStruct()
        inference.features = meta_tfdata.unflatten_batch_examples(
            inf_features_out, num_inference
        )
        out.condition = condition
        out.inference = inference
        out_labels = None
        if labels_out is not None:
            out_labels = meta_tfdata.unflatten_batch_examples(
                labels_out, num_inference
            )
        return out, out_labels


def create_metaexample_spec(
    model_spec, num_samples_per_task: int, prefix: str
) -> TensorSpecStruct:
    """Expands each spec into per-episode columns `<key>/<i>` named
    `<prefix>_ep<i>/<name>` (reference :287-312)."""
    model_spec = flatten_spec_structure(model_spec)
    meta_example_spec = TensorSpecStruct()
    for key in model_spec.keys():
        for i in range(num_samples_per_task):
            spec = model_spec[key]
            name = spec.name if spec.name is not None else key
            meta_example_spec[f"{key}/{i}"] = ExtendedTensorSpec.from_spec(
                spec, name=f"{prefix}_ep{i}/{name}"
            )
    return meta_example_spec


def stack_intra_task_episodes(
    in_tensors, num_samples_per_task: int
) -> TensorSpecStruct:
    """Stacks `<key>/<i>` episode columns into one [batch, samples, ...]
    tensor per key (reference :315-338)."""
    out_tensors = TensorSpecStruct()
    key_set = sorted(
        {"/".join(key.split("/")[:-1]) for key in in_tensors.keys()}
    )
    for key in key_set:
        data = [
            in_tensors[f"{key}/{i}"] for i in range(num_samples_per_task)
        ]
        out_tensors[key] = jnp.stack(data, axis=1)
    return out_tensors


class FixedLenMetaExamplePreprocessor(MAMLPreprocessorV2):
    """Parses per-episode MetaExample columns, stacks them into the task
    layout, then applies the MAML preprocessing (reference :341-413)."""

    def __init__(
        self,
        base_preprocessor: AbstractPreprocessor,
        num_condition_samples_per_task: int = 1,
        num_inference_samples_per_task: int = 1,
    ):
        self._num_condition_samples_per_task = num_condition_samples_per_task
        self._num_inference_samples_per_task = num_inference_samples_per_task
        super().__init__(base_preprocessor)

    @property
    def num_condition_samples_per_task(self) -> int:
        return self._num_condition_samples_per_task

    @property
    def num_inference_samples_per_task(self) -> int:
        return self._num_inference_samples_per_task

    def get_in_feature_specification(self, mode):
        condition_spec = TensorSpecStruct()
        condition_spec.features = (
            self._base_preprocessor.get_in_feature_specification(mode)
        )
        condition_spec.labels = (
            self._base_preprocessor.get_in_label_specification(mode)
        )
        inference_spec = TensorSpecStruct()
        inference_spec.features = (
            self._base_preprocessor.get_in_feature_specification(mode)
        )
        feature_spec = TensorSpecStruct()
        feature_spec.condition = create_metaexample_spec(
            condition_spec, self._num_condition_samples_per_task, "condition"
        )
        feature_spec.inference = create_metaexample_spec(
            inference_spec, self._num_inference_samples_per_task, "inference"
        )
        return flatten_spec_structure(feature_spec)

    def get_in_label_specification(self, mode):
        return flatten_spec_structure(
            create_metaexample_spec(
                self._base_preprocessor.get_in_label_specification(mode),
                self._num_inference_samples_per_task,
                "inference",
            )
        )

    def _preprocess_fn(self, features, labels, mode, rng):
        stacked = TensorSpecStruct()
        stacked.condition = stack_intra_task_episodes(
            features.condition, self._num_condition_samples_per_task
        )
        stacked.inference = stack_intra_task_episodes(
            features.inference, self._num_inference_samples_per_task
        )
        stacked_labels = None
        if labels is not None:
            stacked_labels = stack_intra_task_episodes(
                labels, self._num_inference_samples_per_task
            )
        return super()._preprocess_fn(stacked, stacked_labels, mode, rng)
