"""Task-structured environment loop for meta-learning collect/eval.

Behavioral reference: tensor2robot/meta_learning/run_meta_env.py:33-258.
Per task: gather conditioning demos (via a demo policy or env-provided task
data), adapt the policy, run episodes, re-adapt on everything collected so
far, and track reward as a function of adaptation step — the curve that
shows whether fast adaptation works. Episodes stream to a replay writer as
transition protos; per-step reward/improvement statistics land in the
metrics stream (this framework's summary channel).
"""

from __future__ import annotations

import collections
import copy
import inspect
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.train.metrics import MetricsWriter
from tensor2robot_tpu.utils import writer as writer_lib


def _convert_episode(episode_to_transitions_fn, episode_data, is_demo=None):
    """Runs the converter, passing is_demo only to converters that take it
    (the VRGripper-style fns do; the meta converters read debug['is_demo']
    themselves), and serializes the outputs for the replay writer."""
    kwargs = {}
    if is_demo is not None:
        try:
            parameters = inspect.signature(
                episode_to_transitions_fn
            ).parameters
            if "is_demo" in parameters or any(
                p.kind == inspect.Parameter.VAR_KEYWORD
                for p in parameters.values()
            ):
                kwargs["is_demo"] = is_demo
        except (TypeError, ValueError):
            pass
    return writer_lib.serialize_transition_records(
        episode_to_transitions_fn(episode_data, **kwargs)
    )


def _run_demo_episode(env, demo_policy) -> List[tuple]:
    """Rolls out a demonstration; the demo policy signals the end by
    returning action None (reference :127-139)."""
    obs = env.reset()
    episode_data = []
    while True:
        action, debug = demo_policy.sample_action(obs, 0)
        if action is None:
            break
        next_obs, reward, done, env_debug = env.step(action)
        debug = dict(debug or {})
        debug.update(env_debug or {})
        debug["is_demo"] = True
        episode_data.append((obs, action, reward, next_obs, done, debug))
        obs = next_obs
        if done:
            break
    return episode_data


@configurable("run_meta_env")
def run_meta_env(
    env,
    policy=None,
    demo_policy_cls: Optional[Callable] = None,
    explore_schedule=None,
    episode_to_transitions_fn: Optional[Callable] = None,
    replay_writer=None,
    root_dir: Optional[str] = None,
    output_dir: Optional[str] = None,
    task: int = 0,
    global_step: int = 0,
    num_episodes: Optional[int] = None,
    num_tasks: int = 10,
    num_adaptations_per_task: int = 2,
    num_episodes_per_adaptation: int = 1,
    num_demos: int = 1,
    break_after_one_task: bool = False,
    tag: str = "collect",
    write_summaries: bool = False,
) -> Dict[str, float]:
    """Runs the meta agent/env loop; returns the summary statistics dict
    (reference run_meta_env :33-258 — summaries land in metrics.jsonl
    instead of tf events). `num_episodes` is accepted-and-ignored and
    `output_dir` aliases root_dir, for collect_eval_loop's run_agent_fn
    calling convention (the reference ignores num_episodes too, :85)."""
    del num_episodes
    if root_dir is None:
        root_dir = output_dir
    task_step_rewards: Dict[int, Dict[int, List[float]]] = (
        collections.defaultdict(lambda: collections.defaultdict(list))
    )
    episode_q_values: Dict[int, List[float]] = collections.defaultdict(list)

    for task_idx in range(num_tasks):
        if hasattr(policy, "reset_task"):
            policy.reset_task()
        if hasattr(env, "reset_task"):
            env.reset_task()

        # Writing needs the writer, a converter, AND a destination; gate all
        # three together so write() is never reachable without open().
        writing = bool(replay_writer and episode_to_transitions_fn and root_dir)
        if writing:
            replay_writer.open(
                writer_lib.timestamped_record_path(
                    root_dir, global_step, suffix=f"t{task}_{task_idx}"
                )
            )

        # Conditioning data: demos from a demo policy, or task data the env
        # provides directly (reference :125-167).
        condition_data: List[Any] = []
        if (
            demo_policy_cls is not None
            and hasattr(env, "get_demonstration")
            and hasattr(policy, "adapt")
        ):
            for _ in range(num_demos):
                episode_data = _run_demo_episode(env, demo_policy_cls(env))
                condition_data.append(episode_data)
                if writing:
                    replay_writer.write(
                        _convert_episode(
                            episode_to_transitions_fn,
                            episode_data,
                            is_demo=True,
                        )
                    )
            policy.adapt(copy.copy(condition_data))
        elif hasattr(env, "task_data") and hasattr(policy, "adapt"):
            for episode_name, episode_data in env.task_data.items():
                if str(episode_name).startswith("condition_ep"):
                    condition_data.append(episode_data)
            policy.adapt(copy.copy(condition_data))

        for step_num in range(num_adaptations_per_task):
            if step_num != 0 and hasattr(policy, "adapt"):
                policy.adapt(copy.copy(condition_data))
            for _ in range(num_episodes_per_adaptation):
                done, env_step, episode_reward = False, 0, 0.0
                episode_data = []
                policy.reset()
                obs = env.reset()
                # Schedules are plain callables framework-wide (run_env.py
                # convention); .value objects are accepted for parity with
                # reference gin configs.
                if explore_schedule is None:
                    explore_prob = 0
                elif hasattr(explore_schedule, "value"):
                    explore_prob = explore_schedule.value(global_step)
                else:
                    explore_prob = explore_schedule(global_step)
                while not done:
                    action, policy_debug = policy.sample_action(
                        obs, explore_prob
                    )
                    debug = dict(policy_debug or {})
                    if policy_debug and "q_predicted" in policy_debug:
                        episode_q_values[env_step].append(
                            float(np.mean(policy_debug["q_predicted"]))
                        )
                    new_obs, reward, done, env_debug = env.step(action)
                    debug.update(env_debug or {})
                    env_step += 1
                    episode_reward += reward
                    episode_data.append(
                        (obs, action, reward, new_obs, done, debug)
                    )
                    obs = new_obs
                task_step_rewards[task_idx][step_num].append(episode_reward)
                if writing:
                    replay_writer.write(
                        _convert_episode(
                            episode_to_transitions_fn, episode_data
                        )
                    )
                condition_data.append(episode_data)

        if writing:
            replay_writer.close()
        if break_after_one_task:
            break

    # Aggregate: per-adaptation-step mean reward + improvement deltas
    # (reference :232-258).
    stats: Dict[str, float] = {}
    ran_tasks = sorted(task_step_rewards.keys())
    for step_num in range(num_adaptations_per_task):
        step_rewards = [
            np.mean(task_step_rewards[t][step_num])
            for t in ran_tasks
            if task_step_rewards[t][step_num]
        ]
        if step_rewards:
            stats[f"{tag}/step_{step_num}_reward"] = float(
                np.mean(step_rewards)
            )
        if step_num > 0:
            deltas = [
                np.mean(task_step_rewards[t][step_num])
                - np.mean(task_step_rewards[t][step_num - 1])
                for t in ran_tasks
                if task_step_rewards[t][step_num]
                and task_step_rewards[t][step_num - 1]
            ]
            if deltas:
                stats[f"{tag}/step_{step_num}_improvement"] = float(
                    np.mean(deltas)
                )
    for step, q_values in episode_q_values.items():
        stats[f"{tag}/Q/{step}"] = float(np.mean(q_values))

    if write_summaries and root_dir:
        writer = MetricsWriter(os.path.join(root_dir, f"live_eval_{task}"))
        writer.write(global_step, stats)
        writer.close()
    return stats
