"""Model abstraction: the central T2RModel contract, JAX-native.

A T2RModel declares its tensor specs and provides four pure hooks —
`inference_network_fn`, `model_train_fn`, `model_eval_fn`,
`create_export_outputs_fn` — from which the trainer derives jit/pjit-compiled
`init`/`train_step`/`eval_step`/`predict` functions. Parameters are explicit
pytrees (flax collections), never hidden graph state; device placement comes
from the mesh the trainer compiles against, not from the model.

Contract parity with the reference's AbstractT2RModel / ModelInterface
(tensor2robot/models/abstract_model.py:161-938, model_interface.py:48-146):
spec getters incl. *_for_packing variants, preprocessor ownership, device
typing, optimizer creation, warm-start hooks. What the reference composed in
`model_fn` (validate/pack -> network -> loss -> optimizer -> EstimatorSpec)
lives here as `make_train_model_fn` etc., consumed by train/train_eval.py.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, Mapping, Optional, Tuple, Union

import flax
import jax
import jax.numpy as jnp
import optax

from tensor2robot_tpu.preprocessors import (
    AbstractPreprocessor,
    NoOpPreprocessor,
)
from tensor2robot_tpu.specs import TensorSpecStruct, validate_and_pack

MODE_TRAIN = "train"
MODE_EVAL = "eval"
MODE_PREDICT = "predict"

# Model variables are a dict of flax collections: {'params': ..., and
# optionally 'batch_stats': ... for batch-norm moving statistics}.
ModelVariables = Mapping[str, Any]


class ModelInterface(abc.ABC):
    """The minimal interface infra relies on (reference
    model_interface.py:48-146)."""

    @abc.abstractmethod
    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        ...

    @abc.abstractmethod
    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        ...

    def get_feature_specification_for_packing(self, mode: str) -> TensorSpecStruct:
        """Spec used by policies to pack raw observations; defaults to the
        model in-spec (CEM critics override to drop the tiled action)."""
        return self.get_feature_specification(mode)

    def get_label_specification_for_packing(self, mode: str) -> TensorSpecStruct:
        return self.get_label_specification(mode)

    @property
    @abc.abstractmethod
    def preprocessor(self) -> AbstractPreprocessor:
        ...

    @property
    def device_type(self) -> str:
        return "cpu"

    @property
    def is_device_tpu(self) -> bool:
        return self.device_type == "tpu"

    @property
    def is_device_gpu(self) -> bool:
        return self.device_type == "gpu"


class AbstractT2RModel(ModelInterface):
    """Base model: subclass and implement the spec getters plus
    `inference_network_fn` and `model_train_fn`.

    Attributes:
      use_avg_model_params: maintain an EMA of params; checkpoints hold both
        and exports select the EMA (reference MovingAverageOptimizer +
        swapping saver, abstract_model.py:855-863).
      init_checkpoint: optional warm-start source (path or (path, filter_fn)).
    """

    def __init__(
        self,
        preprocessor_cls: Optional[Callable[..., AbstractPreprocessor]] = None,
        create_optimizer_fn: Optional[Callable[[], optax.GradientTransformation]] = None,
        device_type: str = "tpu",
        use_avg_model_params: bool = False,
        avg_model_params_decay: float = 0.9999,
        init_from_checkpoint_fn: Optional[Callable[[ModelVariables], ModelVariables]] = None,
        use_summaries: Optional[bool] = None,
    ):
        self._preprocessor_cls = preprocessor_cls
        self._create_optimizer_fn = create_optimizer_fn
        self._device_type = device_type
        self.use_avg_model_params = use_avg_model_params
        self.avg_model_params_decay = avg_model_params_decay
        self._init_from_checkpoint_fn = init_from_checkpoint_fn
        # Summaries default off on TPU (host transfers in the hot loop;
        # reference :873-893); scalars still flow via train metrics.
        self._use_summaries = (
            use_summaries if use_summaries is not None else device_type != "tpu"
        )

    # -- device / preprocessor ------------------------------------------------

    @property
    def device_type(self) -> str:
        return self._device_type

    @property
    def use_summaries(self) -> bool:
        return self._use_summaries

    @property
    def preprocessor(self) -> AbstractPreprocessor:
        if self._preprocessor_cls is not None:
            return self._preprocessor_cls(self)
        return NoOpPreprocessor(self)

    # -- parameter lifecycle --------------------------------------------------

    @abc.abstractmethod
    def init_variables(
        self, rng: jax.Array, features: TensorSpecStruct, mode: str = MODE_TRAIN
    ) -> ModelVariables:
        """Initializes model variables from example (or ShapeDtypeStruct)
        features. Flax models: `module.init(rng, features, mode)`."""

    def maybe_init_from_checkpoint(self, variables: ModelVariables) -> ModelVariables:
        """Warm-start hook: rewrite freshly-initialized variables from a
        foreign checkpoint (reference default_init_from_checkpoint_fn
        :86-126)."""
        if self._init_from_checkpoint_fn is not None:
            return self._init_from_checkpoint_fn(variables)
        return variables

    # -- the four hooks -------------------------------------------------------

    @abc.abstractmethod
    def inference_network_fn(
        self,
        variables: ModelVariables,
        features: TensorSpecStruct,
        mode: str,
        rng: Optional[jax.Array] = None,
        labels: Optional[TensorSpecStruct] = None,
    ) -> Tuple[TensorSpecStruct, ModelVariables]:
        """Pure forward pass. Returns (outputs, updated_mutable_collections);
        the second element carries e.g. new batch_stats in train mode and is
        {} when the model has no mutable state (reference
        inference_network_fn's optional update_ops tuple, :703-712).

        `labels` mirrors the reference's inference_network_fn(features,
        labels, ...) signature (:703): density-style heads (MDN/MAF decoders)
        emit their negative log-likelihood as an output tensor when labels
        are available, since the loss depends on network-internal params."""

    @abc.abstractmethod
    def model_train_fn(
        self,
        features: TensorSpecStruct,
        labels: TensorSpecStruct,
        inference_outputs: TensorSpecStruct,
        mode: str,
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Returns (scalar loss, {metric_name: scalar}) — the metrics dict
        replaces TF summaries as the observability channel.

        Metric values are normally scalars (or fixed-size vectors),
        averaged across gradient-accumulation microbatches. A metric whose
        value carries a leading BATCH dimension (per-example captures)
        must declare it by key prefix — `golden/` (see add_golden_tensor)
        or `per_example/` — so the trainer concatenates microbatch slices
        back to the full batch instead of averaging them."""

    def model_eval_fn(
        self,
        features: TensorSpecStruct,
        labels: TensorSpecStruct,
        inference_outputs: TensorSpecStruct,
    ) -> Dict[str, jax.Array]:
        """Per-batch eval statistics, averaged across batches by the
        evaluator. Defaults to the train loss/metrics."""
        loss, metrics = self.model_train_fn(
            features, labels, inference_outputs, MODE_EVAL
        )
        out = {"loss": loss}
        out.update(metrics)
        return out

    def create_export_outputs_fn(
        self,
        features: TensorSpecStruct,
        inference_outputs: TensorSpecStruct,
    ) -> TensorSpecStruct:
        """Selects the serving outputs; defaults to all inference outputs."""
        return inference_outputs

    # -- optimizer ------------------------------------------------------------

    def create_optimizer(self) -> optax.GradientTransformation:
        if self._create_optimizer_fn is not None:
            return self._create_optimizer_fn()
        from tensor2robot_tpu.models import optimizers

        return optimizers.create_adam_optimizer()

    # -- composed validated-forward (what model_fn composed in the reference) --

    def packed_inference(
        self,
        variables: ModelVariables,
        features,
        mode: str,
        labels=None,
        rng: Optional[jax.Array] = None,
    ) -> Tuple[TensorSpecStruct, Optional[TensorSpecStruct], TensorSpecStruct, ModelVariables]:
        """validate_and_pack features/labels against the model specs, run the
        network, return (features, labels, outputs, mutable_updates)."""
        packed_features = validate_and_pack(
            self.get_feature_specification(mode), features, ignore_batch=True
        )
        packed_labels = None
        if labels is not None:
            packed_labels = validate_and_pack(
                self.get_label_specification(mode), labels, ignore_batch=True
            )
        outputs, mutable = self.inference_network_fn(
            variables, packed_features, mode, rng, labels=packed_labels
        )
        return packed_features, packed_labels, outputs, mutable


class FlaxT2RModel(AbstractT2RModel):
    """T2RModel over a flax linen module.

    Subclasses implement `create_network() -> nn.Module` whose
    `__call__(features, mode)` consumes the packed feature struct; batch-norm
    moving stats live in the standard 'batch_stats' collection.
    """

    _MUTABLE_COLLECTIONS = ("batch_stats",)
    # Networks whose __call__ accepts (features, mode, labels) — e.g. models
    # with density-decoder heads — set this True to receive packed labels.
    _NETWORK_TAKES_LABELS = False
    # Set by CompiledModel(fuse_batch_stats_update=True): TRAIN applies open
    # the 'batch_stats_new' collection so layers.batch_norm.BatchNorm
    # defers its running-stats EMA to the trainer's single fused
    # cross-layer update instead of per-layer in-place axpys.
    defer_batch_stats_update: bool = False

    @abc.abstractmethod
    def create_network(self) -> "flax.linen.Module":
        ...

    @property
    def network(self) -> "flax.linen.Module":
        # Flax modules are cheap immutable dataclasses; fresh instance per
        # access keeps the model object pickle-free and fork-safe.
        return self.create_network()

    def init_variables(self, rng, features, mode=MODE_TRAIN) -> ModelVariables:
        def make_zero(leaf):
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return jnp.zeros(leaf.shape, leaf.dtype)
            return jnp.asarray(leaf)

        example = jax.tree_util.tree_map(make_zero, features)
        variables = self.network.init(rng, example, mode)
        return flax.core.unfreeze(variables)

    def _extra_mutable_collections(self, mode) -> tuple:
        """Extra flax collections to open during a TRAIN apply (beyond
        _MUTABLE_COLLECTIONS); subclasses whose networks sow auxiliary
        values (e.g. MoE router losses) name the collections here and
        consume them in `_postprocess_network_outputs`."""
        del mode
        return ()

    def _postprocess_network_outputs(self, outputs, updates, mode):
        """Hook between network.apply and the trainer: subclasses may move
        sown collection values from `updates` into `outputs` (anything
        left in `updates` is merged into the train state's variables).
        Receives mutable copies; returns (outputs, updates)."""
        del mode
        return outputs, updates

    def inference_network_fn(
        self, variables, features, mode, rng=None, labels=None
    ):
        mutable = [c for c in self._MUTABLE_COLLECTIONS if c in variables]
        if rng is not None:
            rng_dropout, rng_sample = jax.random.split(rng)
            rngs = {"dropout": rng_dropout, "sample": rng_sample}
        else:
            rngs = {}
        args = (features, mode)
        if self._NETWORK_TAKES_LABELS:
            args = (features, mode, labels)
        if mode == MODE_TRAIN:
            mutable = mutable + [
                c
                for c in self._extra_mutable_collections(mode)
                if c not in mutable
            ]
            if (
                getattr(self, "defer_batch_stats_update", False)
                and "batch_stats" in variables
                and "batch_stats_new" not in mutable
            ):
                mutable = mutable + ["batch_stats_new"]
        if mode == MODE_TRAIN and mutable:
            outputs, updates = self.network.apply(
                variables, *args, mutable=mutable, rngs=rngs
            )
            return self._postprocess_network_outputs(
                dict(outputs), flax.core.unfreeze(updates), mode
            )
        outputs = self.network.apply(variables, *args, rngs=rngs)
        outputs, _ = self._postprocess_network_outputs(
            dict(outputs), {}, mode
        )
        return outputs, {}
