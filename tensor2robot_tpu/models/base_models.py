"""Concrete model-family bases: Classification, Regression, Critic.

These encode the subclass contracts of the reference's model zoo:
  * ClassificationModel: `a_func` producing `a_predicted` logits; sigmoid
    cross-entropy; accuracy/precision/recall/mse eval metrics
    (reference models/classification_model.py:43-237).
  * RegressionModel: `a_func` producing `inference_output`; MSE against
    labels.target (reference models/regression_model.py:45-167).
  * CriticModel: Q(state, action) with split state/action specs, action
    tiling for CEM batched evaluation, `q_func` producing `q_predicted`,
    loss against labels.reward (reference models/critic_model.py:43-238).
"""

from __future__ import annotations

import abc
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tensor2robot_tpu.models.abstract_model import (
    MODE_PREDICT,
    AbstractT2RModel,
    FlaxT2RModel,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    copy_tensorspec,
)


class ClassificationModel(FlaxT2RModel):
    """Binary/multi-label classifier contract. The network must emit
    `a_predicted` logits; labels must contain `a_target`."""

    def model_train_fn(self, features, labels, inference_outputs, mode):
        logits = inference_outputs["a_predicted"]
        targets = labels["a_target"]
        loss = jnp.mean(
            optax.sigmoid_binary_cross_entropy(logits, targets)
        )
        return loss, {"loss/sigmoid_ce": loss}

    def model_eval_fn(self, features, labels, inference_outputs):
        logits = inference_outputs["a_predicted"]
        targets = labels["a_target"]
        probabilities = jax.nn.sigmoid(logits)
        predictions = (probabilities > 0.5).astype(jnp.float32)
        targets_f = targets.astype(jnp.float32)
        accuracy = jnp.mean((predictions == targets_f).astype(jnp.float32))
        true_positives = jnp.sum(predictions * targets_f)
        precision = true_positives / jnp.maximum(jnp.sum(predictions), 1.0)
        recall = true_positives / jnp.maximum(jnp.sum(targets_f), 1.0)
        mse = jnp.mean(jnp.square(probabilities - targets_f))
        loss = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, targets))
        return {
            "loss": loss,
            "accuracy": accuracy,
            "precision": precision,
            "recall": recall,
            "mean_squared_error": mse,
        }


class RegressionModel(FlaxT2RModel):
    """Regressor contract: network emits `inference_output`; labels carry
    `target`."""

    def model_train_fn(self, features, labels, inference_outputs, mode):
        prediction = inference_outputs["inference_output"]
        loss = jnp.mean(jnp.square(prediction - labels["target"]))
        return loss, {"loss/mse": loss}


class CriticModel(FlaxT2RModel):
    """Q(s, a) critic with CEM-friendly action tiling.

    Subclasses provide `get_state_specification` / `get_action_specification`;
    the combined feature spec nests them under state/ and action/. For
    PREDICT, the action spec gains a leading `action_batch_size` dim so one
    forward pass scores a whole CEM population per state
    (reference critic_model.py:123-136; megabatch reshape networks.py:412-421).
    """

    def __init__(self, action_batch_size: Optional[int] = None, **kwargs):
        super().__init__(**kwargs)
        self._action_batch_size = action_batch_size

    @abc.abstractmethod
    def get_state_specification(self) -> TensorSpecStruct:
        ...

    @abc.abstractmethod
    def get_action_specification(self) -> TensorSpecStruct:
        ...

    @property
    def action_batch_size(self) -> Optional[int]:
        return self._action_batch_size

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        spec = TensorSpecStruct()
        spec.state = self.get_state_specification()
        if mode == MODE_PREDICT and self._action_batch_size is not None:
            spec.action = copy_tensorspec(
                self.get_action_specification(),
                batch_size=self._action_batch_size,
            )
        else:
            spec.action = self.get_action_specification()
        return spec

    def get_feature_specification_for_packing(self, mode: str) -> TensorSpecStruct:
        # Policies pack raw observations only; the CEM layer supplies actions.
        spec = TensorSpecStruct()
        spec.state = self.get_state_specification()
        return spec

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        spec = TensorSpecStruct()
        spec["reward"] = ExtendedTensorSpec(
            shape=(1,), dtype=np.float32, name="reward"
        )
        return spec

    def model_train_fn(self, features, labels, inference_outputs, mode):
        q = inference_outputs["q_predicted"]
        reward = labels["reward"]
        if reward.ndim == q.ndim + 1:
            reward = jnp.squeeze(reward, axis=-1)
        loss = jnp.mean(
            optax.sigmoid_binary_cross_entropy(q, reward)
        )
        return loss, {"loss/bellman_supervised": loss}

    def model_eval_fn(self, features, labels, inference_outputs):
        q = inference_outputs["q_predicted"]
        reward = labels["reward"]
        if reward.ndim == q.ndim + 1:
            reward = jnp.squeeze(reward, axis=-1)
        probabilities = jax.nn.sigmoid(q)
        loss = jnp.mean(optax.sigmoid_binary_cross_entropy(q, reward))
        predictions = (probabilities > 0.5).astype(jnp.float32)
        accuracy = jnp.mean((predictions == reward).astype(jnp.float32))
        return {
            "loss": loss,
            "accuracy": accuracy,
            "q_mean": jnp.mean(probabilities),
        }


def tile_actions_for_cem(
    state_features: TensorSpecStruct,
    actions: jax.Array,
) -> Tuple[TensorSpecStruct, jax.Array]:
    """Expands [B, N, A] CEM action populations + [B, ...] states into the
    megabatch layout [B*N, ...]: states are repeated N times so the critic
    scores every (state, candidate) pair in one MXU-friendly batched pass
    (reference networks.py:412-421 action tiling)."""
    b, n = actions.shape[0], actions.shape[1]
    flat_actions = actions.reshape((b * n,) + actions.shape[2:])
    tiled = TensorSpecStruct()
    for key, value in state_features.items():
        tiled[key] = jnp.repeat(value, n, axis=0)
    return tiled, flat_actions
