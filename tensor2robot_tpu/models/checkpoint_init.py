"""Default warm-start: partial restore from a foreign orbax checkpoint.

The reference shipped default_init_from_checkpoint_fn — assignment-map
restore with allow_partial_restore and a filter_restorables_fn so a model
can warm-start from a checkpoint of a *different* model
(models/abstract_model.py:86-126, exercised by train_eval_test.py:204). The
JAX rebuild matches leaves by '/'-joined tree path over orbax checkpoints:

    model = MyModel(init_from_checkpoint_fn=default_init_from_checkpoint_fn(
        "/path/to/other/model_dir",
        assignment_map={"encoder/": "tower/"},   # dest prefix -> src prefix
        allow_partial_restore=True,
    ))

Leaves present in both trees (after prefix rewriting) with matching shapes
are taken from the checkpoint (cast to the destination dtype); everything
else keeps its fresh initialization. Missing leaves raise unless
allow_partial_restore; shape mismatches always raise (silently keeping a
mis-shaped leaf would corrupt the warm start).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Mapping, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


def path_str(key_path) -> str:
    """'/'-joined string form of a jax tree key path (DictKey/GetAttrKey/
    SequenceKey all reduce to their key/name/index)."""
    parts = []
    for entry in key_path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "name"):
            parts.append(str(entry.name))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def flatten_with_paths(tree) -> Dict[str, Any]:
    """Flattens a pytree to {'/'.joined/path: leaf}."""
    return {
        path_str(key_path): leaf
        for key_path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }


def _checkpoint_root_and_step(
    checkpoint_path: str, step: Optional[int]
) -> tuple[str, int]:
    """Accepts a model_dir, a checkpoints root, or a specific step dir."""
    path = os.path.abspath(checkpoint_path)
    nested = os.path.join(path, "checkpoints")
    if os.path.isdir(nested):
        path = nested
    base = os.path.basename(path)
    if base.isdigit():
        if step is not None and step != int(base):
            raise FileNotFoundError(
                f"Requested step {step} but {checkpoint_path!r} is the "
                f"step-{base} directory."
            )
        return os.path.dirname(path), int(base)
    steps = [
        int(entry)
        for entry in (os.listdir(path) if os.path.isdir(path) else [])
        if entry.isdigit() and os.path.isdir(os.path.join(path, entry))
    ]
    if not steps:
        raise FileNotFoundError(
            f"No checkpoint steps under {checkpoint_path!r}"
        )
    if step is None:
        return path, max(steps)
    if step not in steps:
        raise FileNotFoundError(
            f"Step {step} not in {sorted(steps)} under {checkpoint_path!r}"
        )
    return path, step


def load_checkpoint_variables(
    checkpoint_path: str,
    step: Optional[int] = None,
    use_ema: bool = False,
) -> Dict[str, Any]:
    """Loads a TrainState checkpoint's variables as a raw pytree.

    use_ema swaps the averaged params in as 'params' (the reference's
    swapping-saver semantics: warm starts consume the averaged weights).
    """
    root, resolved = _checkpoint_root_and_step(checkpoint_path, step)
    manager = ocp.CheckpointManager(root)
    try:
        # Restore against the checkpoint's own metadata with host-placed
        # leaves: a bare StandardRestore() replays the TRAINER topology's
        # sharding file and fails whenever the warm-starting job runs on a
        # different device count (pod checkpoint -> single-host finetune).
        from tensor2robot_tpu.train.state import checkpoint_metadata_template

        try:
            abstract = checkpoint_metadata_template(root, resolved)
        except Exception:  # noqa: BLE001 — metadata probing is best-effort
            abstract = None
        tree = manager.restore(
            resolved, args=ocp.args.StandardRestore(abstract)
        )
    finally:
        manager.close()
    variables = tree.get("variables", tree) if isinstance(tree, dict) else tree
    if use_ema:
        if not isinstance(tree, dict) or tree.get("ema_params") is None:
            raise ValueError(
                f"use_ema=True but checkpoint {checkpoint_path!r} holds no "
                "ema_params (trained without use_avg_model_params)."
            )
        variables = dict(variables)
        # ema_as_tree: a flat-EMA checkpoint (flatten_optimizer_update)
        # stores one 1-D vector; unravel it against the checkpoint's own
        # params structure before path-based matching sees it.
        from tensor2robot_tpu.train.state import ema_as_tree

        variables["params"] = ema_as_tree(
            tree["ema_params"], variables["params"]
        )
    return variables


def _rewrite(path: str, assignment_map: Optional[Mapping[str, str]]) -> Optional[str]:
    """Maps a destination path to its source path. Longest-prefix match;
    mapping a prefix to None drops the leaf from restoring."""
    if not assignment_map:
        return path
    best = None
    for dest_prefix in sorted(assignment_map, key=len, reverse=True):
        if path.startswith(dest_prefix) or dest_prefix == "":
            best = dest_prefix
            break
    if best is None:
        return path
    src_prefix = assignment_map[best]
    if src_prefix is None:
        return None
    return src_prefix + path[len(best):]


def default_init_from_checkpoint_fn(
    checkpoint_path: str,
    step: Optional[int] = None,
    assignment_map: Optional[Mapping[str, str]] = None,
    filter_restorables_fn: Optional[Callable[[str], bool]] = None,
    allow_partial_restore: bool = False,
    use_ema: bool = False,
) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """Builds an init_from_checkpoint_fn for AbstractT2RModel.

    Args mirror the reference (models/abstract_model.py:86-126):
      checkpoint_path: foreign model_dir / checkpoints root / step dir.
      step: specific step (default latest).
      assignment_map: destination-prefix -> source-prefix rewrites applied to
        '/'-joined variable paths ('params/dense/kernel'); a None source
        drops the subtree from restoring.
      filter_restorables_fn: path -> bool; False keeps the fresh init (the
        reference's filter_restorables_fn).
      allow_partial_restore: tolerate leaves missing from the checkpoint.
      use_ema: restore averaged params as 'params'.
    """

    def init_fn(variables: Dict[str, Any]) -> Dict[str, Any]:
        source_flat = flatten_with_paths(
            load_checkpoint_variables(checkpoint_path, step=step, use_ema=use_ema)
        )
        paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(variables)
        new_leaves = []
        missing = []
        for key_path, leaf in paths_and_leaves:
            path = path_str(key_path)
            if filter_restorables_fn is not None and not filter_restorables_fn(path):
                new_leaves.append(leaf)
                continue
            source_path = _rewrite(path, assignment_map)
            if source_path is None:
                new_leaves.append(leaf)
                continue
            if source_path not in source_flat:
                missing.append(f"{path} (from {source_path})")
                new_leaves.append(leaf)
                continue
            value = source_flat[source_path]
            dest_shape = tuple(getattr(leaf, "shape", ()))
            if tuple(np.shape(value)) != dest_shape:
                raise ValueError(
                    f"Warm-start shape mismatch for {path!r}: checkpoint "
                    f"{tuple(np.shape(value))} vs model {dest_shape}"
                )
            dtype = getattr(leaf, "dtype", None)
            new_leaves.append(
                np.asarray(value, dtype=dtype) if dtype is not None else value
            )
        if missing and not allow_partial_restore:
            raise KeyError(
                "Warm-start leaves missing from checkpoint (pass "
                f"allow_partial_restore=True to keep their init): {missing[:10]}"
            )
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    return init_fn
