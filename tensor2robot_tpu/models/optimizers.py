"""Optimizer and learning-rate factories over optax.

Parity with the reference's gin factories (tensor2robot/models/optimizers.py:
27-159): constant / exponential-decay learning rates; Adam / SGD / Momentum /
RMSProp creators; moving-average ("swapping saver") semantics are provided by
the trainer keeping an EMA param tree (see train/state.py) — in optax terms
an `optax.ema` over params, checkpointed alongside the raw params, with
export selecting the EMA copy.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import optax

ScalarOrSchedule = Union[float, optax.Schedule]


def create_constant_learning_rate(learning_rate: float = 1e-3) -> optax.Schedule:
    return optax.constant_schedule(learning_rate)


def create_exponential_decay_learning_rate(
    initial_learning_rate: float = 1e-3,
    decay_steps: int = 10000,
    decay_rate: float = 0.9,
    staircase: bool = True,
) -> optax.Schedule:
    return optax.exponential_decay(
        init_value=initial_learning_rate,
        transition_steps=decay_steps,
        decay_rate=decay_rate,
        staircase=staircase,
    )


def create_adam_optimizer(
    learning_rate: ScalarOrSchedule = 1e-3,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8,
) -> optax.GradientTransformation:
    return optax.adam(learning_rate, b1=beta1, b2=beta2, eps=epsilon)


def create_sgd_optimizer(
    learning_rate: ScalarOrSchedule = 1e-2,
) -> optax.GradientTransformation:
    return optax.sgd(learning_rate)


def create_momentum_optimizer(
    learning_rate: ScalarOrSchedule = 1e-2,
    momentum: float = 0.9,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    return optax.sgd(learning_rate, momentum=momentum, nesterov=nesterov)


def create_rms_prop_optimizer(
    learning_rate: ScalarOrSchedule = 1e-3,
    decay: float = 0.9,
    momentum: float = 0.0,
    epsilon: float = 1e-10,
) -> optax.GradientTransformation:
    return optax.rmsprop(
        learning_rate, decay=decay, momentum=momentum, eps=epsilon
    )


def with_gradient_clipping(
    optimizer: optax.GradientTransformation,
    max_global_norm: Optional[float] = None,
    max_abs_value: Optional[float] = None,
) -> optax.GradientTransformation:
    """Composes clipping in front of an optimizer (the reference exposed
    clipping via contrib_training.create_train_op kwargs)."""
    chain = []
    if max_abs_value is not None:
        chain.append(optax.clip(max_abs_value))
    if max_global_norm is not None:
        chain.append(optax.clip_by_global_norm(max_global_norm))
    chain.append(optimizer)
    return optax.chain(*chain)
