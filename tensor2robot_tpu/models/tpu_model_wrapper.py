"""TPU dtype-policy model wrapper.

Decorates a T2RModel for TPU execution:
  * feature/label specs re-declare float32 as bfloat16 (the infeed contract),
  * the preprocessor is auto-wrapped with TPUPreprocessorWrapper,
  * `train_in_bfloat16` (default ON — the TPU-native policy, reference
    models/tpu_model_wrapper.py:185-191 bfloat16_scope) keeps the network
    inputs bf16 so dtype-following networks compute their matmuls/convs on
    the MXU in bf16 with float32 master params and float32 losses; with it
    off, bf16 inputs are upcast to float32 at the network boundary and the
    whole forward runs full precision.

What the reference additionally did here — CrossShardOptimizer wrapping and
scaffold-deferred init (models/tpu_model_wrapper.py:45-49,236-278) — has no
JAX analogue: gradient cross-replica reduction is implicit in pjit's sharded
autodiff (psum inserted by GSPMD), and init is an explicit jitted function.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.models.abstract_model import (
    MODE_TRAIN,
    AbstractT2RModel,
)
from tensor2robot_tpu.preprocessors import TPUPreprocessorWrapper
from tensor2robot_tpu.specs import (
    TensorSpecStruct,
    cast_float32_to_bfloat16,
    cast_tensors,
)


class TPUT2RModelWrapper(AbstractT2RModel):
    """Wraps `model` with the TPU bf16 spec + activation policy."""

    def __init__(self, model: AbstractT2RModel, train_in_bfloat16: bool = True):
        super().__init__(device_type="tpu")
        self._model = model
        self._train_in_bfloat16 = train_in_bfloat16
        self.use_avg_model_params = model.use_avg_model_params
        self.avg_model_params_decay = model.avg_model_params_decay

    @property
    def wrapped(self) -> AbstractT2RModel:
        return self._model

    # -- specs: f32 -> bf16 (reference :107-120) ------------------------------

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        return cast_float32_to_bfloat16(
            self._model.get_feature_specification(mode)
        )

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        return cast_float32_to_bfloat16(self._model.get_label_specification(mode))

    def get_feature_specification_for_packing(self, mode: str) -> TensorSpecStruct:
        return self._model.get_feature_specification_for_packing(mode)

    def get_label_specification_for_packing(self, mode: str) -> TensorSpecStruct:
        return self._model.get_label_specification_for_packing(mode)

    @property
    def preprocessor(self):
        return TPUPreprocessorWrapper(self._model.preprocessor)

    # -- parameter lifecycle delegates ---------------------------------------

    def init_variables(self, rng, features, mode=MODE_TRAIN):
        # Params initialize at the wrapped model's (f32) contract.
        f32_features = jax.tree_util.tree_map(self._to_f32_struct, features)
        return self._model.init_variables(rng, f32_features, mode)

    @staticmethod
    def _to_f32_struct(leaf):
        if hasattr(leaf, "dtype") and leaf.dtype == jnp.bfloat16:
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return jax.ShapeDtypeStruct(leaf.shape, np.float32)
            return jnp.asarray(leaf, jnp.float32)
        return leaf

    def maybe_init_from_checkpoint(self, variables):
        return self._model.maybe_init_from_checkpoint(variables)

    def create_optimizer(self):
        return self._model.create_optimizer()

    # -- hooks: cast at the boundary (reference :174-191) --------------------

    def inference_network_fn(
        self, variables, features, mode, rng=None, labels=None
    ):
        if not self._train_in_bfloat16:
            features = cast_tensors(features, jnp.bfloat16, np.float32)
        return self._model.inference_network_fn(
            variables, features, mode, rng, labels=labels
        )

    def model_train_fn(self, features, labels, inference_outputs, mode):
        # Losses accumulate in float32 regardless of the forward dtype.
        features = cast_tensors(features, jnp.bfloat16, np.float32)
        labels = cast_tensors(labels, jnp.bfloat16, np.float32)
        inference_outputs = cast_tensors(
            inference_outputs, jnp.bfloat16, np.float32
        )
        return self._model.model_train_fn(
            features, labels, inference_outputs, mode
        )

    def model_eval_fn(self, features, labels, inference_outputs):
        features = cast_tensors(features, jnp.bfloat16, np.float32)
        labels = cast_tensors(labels, jnp.bfloat16, np.float32)
        inference_outputs = cast_tensors(
            inference_outputs, jnp.bfloat16, np.float32
        )
        return self._model.model_eval_fn(features, labels, inference_outputs)

    def create_export_outputs_fn(self, features, inference_outputs):
        # Exports serve float32 so CPU/GPU clients consume them unchanged
        # (reference kept graphs CPU/GPU-servable via no-op casts, :174-183).
        return cast_tensors(
            self._model.create_export_outputs_fn(features, inference_outputs),
            jnp.bfloat16,
            np.float32,
        )
