"""Transformer model family: long-context behavioral cloning.

A model family beyond the reference's temporal ceiling: the reference's
sequence models top out at SNAIL/TCN scale over ~40-step episodes
(reference layers/snail.py, research/vrgripper/vrgripper_env_models.py
:139-324 — the BC contract this family mirrors); this one runs a causal
transformer over the episode with flash attention on TPU and ring
attention when the mesh has a sequence axis — the same model trains short
episodes on one chip and long-horizon demonstrations on a context-
parallel mesh without code changes. Optional mixture-of-experts feed-
forwards ride the `expert` axis (docs/PARALLELISM.md).
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers.spatial_softmax import spatial_softmax
from tensor2robot_tpu.layers.transformer import TransformerEncoder
from tensor2robot_tpu.models.abstract_model import (
    MODE_TRAIN,
    FlaxT2RModel,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    copy_tensorspec,
)


class _TransformerBCNet(nn.Module):
    """Per-step conv embed -> causal transformer over time -> action head."""

    action_size: int
    d_model: int = 64
    num_layers: int = 2
    num_heads: int = 4
    head_dim: int = 16
    max_seq_len: int = 2048
    num_experts: int = 1
    mesh: Optional[object] = None
    use_flash: Optional[bool] = None
    interpret: bool = False
    sequence_parallel_mode: str = "ring"
    pipeline_stages: int = 1
    pipeline_microbatches: Optional[int] = None
    # Causal sliding window over the episode (None = full history): each
    # step attends to its last `attention_window` steps, O(T*W) compute —
    # the streaming-robot regime where recent context dominates.
    attention_window: Optional[int] = None

    @nn.compact
    def __call__(self, features, mode):
        image = features["image"]  # [B, T, H, W, 3]
        pose = features["gripper_pose"]  # [B, T, P]
        batch, steps = image.shape[:2]
        x = image.reshape((batch * steps,) + image.shape[2:])
        for filters in (32, 64):
            x = nn.Conv(filters, (3, 3), strides=(2, 2))(x)
            x = nn.relu(x)
        points, _ = spatial_softmax(x)  # [B*T, 2*filters]
        x = points.reshape(batch, steps, -1)
        x = jnp.concatenate([x, pose], axis=-1)
        x = nn.Dense(self.d_model, name="embed")(x)
        x = TransformerEncoder(
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            max_seq_len=self.max_seq_len,
            causal=True,
            mesh=self.mesh,
            use_flash=self.use_flash,
            interpret=self.interpret,
            num_experts=self.num_experts,
            sequence_parallel_mode=self.sequence_parallel_mode,
            pipeline_stages=self.pipeline_stages,
            pipeline_microbatches=self.pipeline_microbatches,
            window=self.attention_window,
            name="encoder",
        )(x)
        action = nn.Dense(self.action_size, name="action_head")(x)
        return {"inference_output": action, "action": action}


class TransformerBCModel(FlaxT2RModel):
    """Behavioral cloning over episodes with a causal transformer.

    Same spec contract as the VRGripper BC family (per-step image +
    proprioception in, per-step action out; reference
    vrgripper_env_models.py:139-324), but the temporal core is attention:
    flash on a single chip, ring attention over the mesh's `sequence`
    axis for long-horizon episodes, optional expert-parallel MoE
    feed-forwards (`num_experts > 1`, router aux loss folded into the
    training loss).
    """

    def __init__(
        self,
        action_size: int = 7,
        pose_size: int = 14,
        episode_length: int = 40,
        image_size: Tuple[int, int] = (64, 64),
        d_model: int = 64,
        num_layers: int = 2,
        num_heads: int = 4,
        head_dim: int = 16,
        num_experts: int = 1,
        moe_aux_weight: float = 0.01,
        mesh: Optional[object] = None,
        use_flash: Optional[bool] = None,
        interpret: bool = False,
        sequence_parallel_mode: str = "ring",
        pipeline_stages: int = 1,
        pipeline_microbatches: Optional[int] = None,
        attention_window: Optional[int] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self._action_size = action_size
        self._pose_size = pose_size
        self._episode_length = episode_length
        self._image_size = tuple(image_size)
        self._d_model = d_model
        self._num_layers = num_layers
        self._num_heads = num_heads
        self._head_dim = head_dim
        self._num_experts = num_experts
        self._moe_aux_weight = moe_aux_weight
        self._mesh = mesh
        self._use_flash = use_flash
        self._interpret = interpret
        self._sequence_parallel_mode = sequence_parallel_mode
        self._pipeline_stages = pipeline_stages
        self._pipeline_microbatches = pipeline_microbatches
        self._attention_window = attention_window

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            image=ExtendedTensorSpec(
                shape=self._image_size + (3,),
                dtype=np.float32,
                name="image",
                data_format="jpeg",
            ),
            gripper_pose=ExtendedTensorSpec(
                shape=(self._pose_size,),
                dtype=np.float32,
                name="gripper_pose",
            ),
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            action=ExtendedTensorSpec(
                shape=(self._action_size,), dtype=np.float32, name="action"
            )
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    def create_network(self) -> nn.Module:
        return _TransformerBCNet(
            action_size=self._action_size,
            d_model=self._d_model,
            num_layers=self._num_layers,
            num_heads=self._num_heads,
            head_dim=self._head_dim,
            max_seq_len=max(self._episode_length, 8),
            num_experts=self._num_experts,
            mesh=self._mesh,
            use_flash=self._use_flash,
            interpret=self._interpret,
            sequence_parallel_mode=self._sequence_parallel_mode,
            pipeline_stages=self._pipeline_stages,
            pipeline_microbatches=self._pipeline_microbatches,
            attention_window=self._attention_window,
        )

    def init_variables(self, rng, features, mode=MODE_TRAIN):
        variables = super().init_variables(rng, features, mode)
        # Flax init keeps custom collections: drop the init-time sown aux
        # values so they neither persist into checkpoints nor get averaged
        # into later forwards (sow APPENDS to a pre-existing collection).
        variables.pop("moe_aux_loss", None)
        return variables

    def inference_network_fn(
        self, variables, features, mode, rng=None, labels=None
    ):
        # Defense in depth against stale sown values riding in (a
        # warm-start from a checkpoint written before init_variables
        # stripped the collection): sow APPENDS to pre-existing entries,
        # which would bias the aux-loss mean.
        if "moe_aux_loss" in variables:
            variables = {
                key: value
                for key, value in variables.items()
                if key != "moe_aux_loss"
            }
        return super().inference_network_fn(
            variables, features, mode, rng=rng, labels=labels
        )

    def _extra_mutable_collections(self, mode):
        del mode
        return ("moe_aux_loss",) if self._num_experts > 1 else ()

    def _postprocess_network_outputs(self, outputs, updates, mode):
        # The router aux loss is sown into moe_aux_loss by each block;
        # surface its mean in the TRAIN outputs (only — the scalar must
        # not leak into eval/serving signatures, which export all outputs)
        # so model_train_fn can fold it into the loss. Popping it from
        # `updates` also keeps it out of the train state's variables.
        aux_leaves = jax.tree_util.tree_leaves(
            updates.pop("moe_aux_loss", {})
        )
        if mode == MODE_TRAIN and aux_leaves:
            outputs["moe_aux_loss"] = sum(aux_leaves) / len(aux_leaves)
        return outputs, updates

    def model_train_fn(self, features, labels, inference_outputs, mode):
        mse = jnp.mean(
            jnp.square(inference_outputs["inference_output"] - labels["action"])
        )
        metrics = {"loss/mse": mse}
        loss = mse
        if "moe_aux_loss" in inference_outputs:
            aux = inference_outputs["moe_aux_loss"]
            metrics["loss/moe_aux"] = aux
            loss = loss + self._moe_aux_weight * aux
        return loss, metrics

    def model_eval_fn(self, features, labels, inference_outputs):
        return {
            "eval/mse": jnp.mean(
                jnp.square(
                    inference_outputs["inference_output"] - labels["action"]
                )
            )
        }
