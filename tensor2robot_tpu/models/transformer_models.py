"""Transformer model family: long-context behavioral cloning.

A model family beyond the reference's temporal ceiling: the reference's
sequence models top out at SNAIL/TCN scale over ~40-step episodes
(reference layers/snail.py, research/vrgripper/vrgripper_env_models.py
:139-324 — the BC contract this family mirrors); this one runs a causal
transformer over the episode with flash attention on TPU and ring
attention when the mesh has a sequence axis — the same model trains short
episodes on one chip and long-horizon demonstrations on a context-
parallel mesh without code changes. Optional mixture-of-experts feed-
forwards ride the `expert` axis (docs/PARALLELISM.md).
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers.spatial_softmax import spatial_softmax
from tensor2robot_tpu.layers.transformer import TransformerEncoder
from tensor2robot_tpu.models.abstract_model import (
    MODE_TRAIN,
    FlaxT2RModel,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    copy_tensorspec,
)


class _TransformerBCNet(nn.Module):
    """Per-step conv embed -> causal transformer over time -> action head."""

    action_size: int
    d_model: int = 64
    num_layers: int = 2
    num_heads: int = 4
    head_dim: int = 16
    max_seq_len: int = 2048
    num_experts: int = 1
    mesh: Optional[object] = None
    use_flash: Optional[bool] = None
    interpret: bool = False
    sequence_parallel_mode: str = "ring"
    pipeline_stages: int = 1
    pipeline_microbatches: Optional[int] = None
    # Causal sliding window over the episode (None = full history): each
    # step attends to its last `attention_window` steps, O(T*W) compute —
    # the streaming-robot regime where recent context dominates.
    attention_window: Optional[int] = None
    # Incremental serving: one step per call against a K/V cache (see
    # MultiHeadAttention.decode). Training always uses the full forward.
    decode: bool = False
    # Grouped-query attention (see MultiHeadAttention.num_kv_heads).
    num_kv_heads: Optional[int] = None

    @nn.compact
    def __call__(self, features, mode):
        image = features["image"]  # [B, T, H, W, 3]
        pose = features["gripper_pose"]  # [B, T, P]
        batch, steps = image.shape[:2]
        x = image.reshape((batch * steps,) + image.shape[2:])
        for filters in (32, 64):
            x = nn.Conv(filters, (3, 3), strides=(2, 2))(x)
            x = nn.relu(x)
        points, _ = spatial_softmax(x)  # [B*T, 2*filters]
        x = points.reshape(batch, steps, -1)
        x = jnp.concatenate([x, pose], axis=-1)
        x = nn.Dense(self.d_model, name="embed")(x)
        x = TransformerEncoder(
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            max_seq_len=self.max_seq_len,
            causal=True,
            mesh=self.mesh,
            use_flash=self.use_flash,
            interpret=self.interpret,
            num_experts=self.num_experts,
            sequence_parallel_mode=self.sequence_parallel_mode,
            pipeline_stages=self.pipeline_stages,
            pipeline_microbatches=self.pipeline_microbatches,
            window=self.attention_window,
            decode=self.decode,
            num_kv_heads=self.num_kv_heads,
            name="encoder",
        )(x)
        action = nn.Dense(self.action_size, name="action_head")(x)
        return {"inference_output": action, "action": action}


class TransformerBCModel(FlaxT2RModel):
    """Behavioral cloning over episodes with a causal transformer.

    Same spec contract as the VRGripper BC family (per-step image +
    proprioception in, per-step action out; reference
    vrgripper_env_models.py:139-324), but the temporal core is attention:
    flash on a single chip, ring attention over the mesh's `sequence`
    axis for long-horizon episodes, optional expert-parallel MoE
    feed-forwards (`num_experts > 1`, router aux loss folded into the
    training loss).
    """

    def __init__(
        self,
        action_size: int = 7,
        pose_size: int = 14,
        episode_length: int = 40,
        image_size: Tuple[int, int] = (64, 64),
        d_model: int = 64,
        num_layers: int = 2,
        num_heads: int = 4,
        head_dim: int = 16,
        num_experts: int = 1,
        moe_aux_weight: float = 0.01,
        mesh: Optional[object] = None,
        use_flash: Optional[bool] = None,
        interpret: bool = False,
        sequence_parallel_mode: str = "ring",
        pipeline_stages: int = 1,
        pipeline_microbatches: Optional[int] = None,
        attention_window: Optional[int] = None,
        num_kv_heads: Optional[int] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self._action_size = action_size
        self._pose_size = pose_size
        self._episode_length = episode_length
        self._image_size = tuple(image_size)
        self._d_model = d_model
        self._num_layers = num_layers
        self._num_heads = num_heads
        self._head_dim = head_dim
        self._num_experts = num_experts
        self._moe_aux_weight = moe_aux_weight
        self._mesh = mesh
        self._use_flash = use_flash
        self._interpret = interpret
        self._sequence_parallel_mode = sequence_parallel_mode
        self._pipeline_stages = pipeline_stages
        self._pipeline_microbatches = pipeline_microbatches
        self._attention_window = attention_window
        self._num_kv_heads = num_kv_heads

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            image=ExtendedTensorSpec(
                shape=self._image_size + (3,),
                dtype=np.float32,
                name="image",
                data_format="jpeg",
            ),
            gripper_pose=ExtendedTensorSpec(
                shape=(self._pose_size,),
                dtype=np.float32,
                name="gripper_pose",
            ),
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            action=ExtendedTensorSpec(
                shape=(self._action_size,), dtype=np.float32, name="action"
            )
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    def create_network(self, decode: bool = False) -> nn.Module:
        return _TransformerBCNet(
            action_size=self._action_size,
            d_model=self._d_model,
            num_layers=self._num_layers,
            num_heads=self._num_heads,
            head_dim=self._head_dim,
            max_seq_len=max(self._episode_length, 8),
            num_experts=self._num_experts,
            mesh=None if decode else self._mesh,
            use_flash=self._use_flash,
            interpret=self._interpret,
            sequence_parallel_mode=self._sequence_parallel_mode,
            pipeline_stages=1 if decode else self._pipeline_stages,
            pipeline_microbatches=self._pipeline_microbatches,
            attention_window=self._attention_window,
            num_kv_heads=self._num_kv_heads,
            decode=decode,
        )

    def create_streaming_policy(
        self, variables, batch_size: int = 1
    ) -> "StreamingBCPolicy":
        """Per-step serving over trained variables (KV-cache decode)."""
        return StreamingBCPolicy(self, variables, batch_size=batch_size)

    def init_variables(self, rng, features, mode=MODE_TRAIN):
        variables = super().init_variables(rng, features, mode)
        # Flax init keeps custom collections: drop the init-time sown aux
        # values so they neither persist into checkpoints nor get averaged
        # into later forwards (sow APPENDS to a pre-existing collection).
        variables.pop("moe_aux_loss", None)
        return variables

    def inference_network_fn(
        self, variables, features, mode, rng=None, labels=None
    ):
        # Defense in depth against stale sown values riding in (a
        # warm-start from a checkpoint written before init_variables
        # stripped the collection): sow APPENDS to pre-existing entries,
        # which would bias the aux-loss mean.
        if "moe_aux_loss" in variables:
            variables = {
                key: value
                for key, value in variables.items()
                if key != "moe_aux_loss"
            }
        return super().inference_network_fn(
            variables, features, mode, rng=rng, labels=labels
        )

    def _extra_mutable_collections(self, mode):
        del mode
        return ("moe_aux_loss",) if self._num_experts > 1 else ()

    def _postprocess_network_outputs(self, outputs, updates, mode):
        # The router aux loss is sown into moe_aux_loss by each block;
        # surface its mean in the TRAIN outputs (only — the scalar must
        # not leak into eval/serving signatures, which export all outputs)
        # so model_train_fn can fold it into the loss. Popping it from
        # `updates` also keeps it out of the train state's variables.
        aux_leaves = jax.tree_util.tree_leaves(
            updates.pop("moe_aux_loss", {})
        )
        if mode == MODE_TRAIN and aux_leaves:
            outputs["moe_aux_loss"] = sum(aux_leaves) / len(aux_leaves)
        return outputs, updates

    def model_train_fn(self, features, labels, inference_outputs, mode):
        mse = jnp.mean(
            jnp.square(inference_outputs["inference_output"] - labels["action"])
        )
        metrics = {"loss/mse": mse}
        loss = mse
        if "moe_aux_loss" in inference_outputs:
            aux = inference_outputs["moe_aux_loss"]
            metrics["loss/moe_aux"] = aux
            loss = loss + self._moe_aux_weight * aux
        return loss, metrics

    def model_eval_fn(self, features, labels, inference_outputs):
        return {
            "eval/mse": jnp.mean(
                jnp.square(
                    inference_outputs["inference_output"] - labels["action"]
                )
            )
        }


class StreamingBCPolicy:
    """Stateful per-step serving for a trained TransformerBCModel.

    Each step() consumes ONE observation (image + proprioception) and
    returns that step's action: the conv embed runs on the single frame
    and attention reads the K/V cache — O(attention_window) per step when
    the model has one, never a full-episode recompute. The robot-loop
    counterpart of the training-time forward; the whole step is one jitted
    dispatch with the cache donated in place.

    Episodes are bounded by the model's max_seq_len (steps beyond it
    overwrite the last cache slot — call reset() between episodes).
    """

    def __init__(self, model: TransformerBCModel, variables, batch_size=1):
        self._net = model.create_network(decode=True)
        self._params = variables["params"]
        dummy = {
            "image": jnp.zeros(
                (batch_size, 1) + model._image_size + (3,), jnp.float32
            ),
            "gripper_pose": jnp.zeros(
                (batch_size, 1, model._pose_size), jnp.float32
            ),
        }
        # init RUNS the module (consuming one cache step); zero for the
        # real episode start.
        cache = self._net.init(jax.random.PRNGKey(0), dummy, "predict")[
            "cache"
        ]
        self._zero_cache = jax.tree_util.tree_map(jnp.zeros_like, cache)
        self._cache = self._zero_cache

        def step(params, cache, image, pose):
            out, mutated = self._net.apply(
                {"params": params, "cache": cache},
                {"image": image, "gripper_pose": pose},
                "predict",
                mutable=["cache"],
            )
            return out["action"][:, 0], mutated["cache"]

        # No cache donation: the zeroed template must stay alive for
        # reset(), and per-step cache copies are a few MB at robot rates.
        self._step = jax.jit(step)

    def reset(self) -> None:
        """Starts a new episode (empty cache, position 0)."""
        self._cache = self._zero_cache

    def step(self, image, gripper_pose) -> np.ndarray:
        """One control step: [B?, H, W, 3] image + [B?, P] pose -> [B, A]
        action for THIS step (batch dim optional for batch_size=1)."""
        image = jnp.asarray(image, jnp.float32)
        pose = jnp.asarray(gripper_pose, jnp.float32)
        if image.ndim == 3:
            image = image[None]
        if pose.ndim == 1:
            pose = pose[None]
        action, self._cache = self._step(
            self._params, self._cache, image[:, None], pose[:, None]
        )
        return np.asarray(jax.device_get(action))
