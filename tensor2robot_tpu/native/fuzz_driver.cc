// Sanitizer fuzz driver for the native wire/jpeg parsers.
//
// The hot path (PRs 1-2) runs raw pointer/span arithmetic over UNTRUSTED
// record bytes: the TFRecord indexers walk length fields read from the
// file, and the jpeg decoders write scanlines into caller buffers sized
// from the SPEC, not from the file. Every one of those is a classic
// out-of-bounds read/write shape. This driver feeds corpus files (valid,
// truncated, bit-flipped, dimension-lying — tools/gen_fuzz_corpus.py)
// through every native entry point, compiled under ASan/UBSan
// (`make -C tensor2robot_tpu/native sanitize`):
//
//   * t2r_index_records / t2r_index_records_partial, verify_crc on+off,
//     plus an undersized max_records to exercise the counting tail;
//   * t2r_decode_jpeg into a spec-sized buffer AND into a deliberately
//     undersized buffer (the -3 path);
//   * t2r_decode_jpeg_roi with interior, edge, and out-of-frame crops.
//
// The contract under test is NOT "parse everything" — it is "return a
// negative status and touch only your own buffers, whatever the bytes
// say". Any OOB access, UB, or leak aborts the process with a sanitizer
// report; exit 0 means every file was survived. The driver prints one
// line per file so a crash names its input.
//
// Build: make -C tensor2robot_tpu/native sanitize
//        ./t2r_fuzz_asan <dir|files>   (plain twin: make t2r_fuzz)

#include <dirent.h>
#include <sys/stat.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
int64_t t2r_index_records(const uint8_t* buf, size_t n, uint64_t* offsets,
                          uint64_t* lengths, size_t max_records,
                          int verify_crc);
int64_t t2r_index_records_partial(const uint8_t* buf, size_t n,
                                  uint64_t* offsets, uint64_t* lengths,
                                  size_t max_records, int verify_crc,
                                  uint64_t* consumed);
int t2r_decode_jpeg(const unsigned char* data, size_t len, unsigned char* out,
                    size_t out_capacity, int want_channels, int* height,
                    int* width);
int t2r_decode_jpeg_roi(const unsigned char* data, size_t len,
                        unsigned char* out, size_t out_capacity,
                        int want_channels, int crop_y, int crop_x, int crop_h,
                        int crop_w, int* full_height, int* full_width);
}

namespace {

// Big enough for the QT-Opt 512x640 frames the corpus uses; a file whose
// header claims more must fail with -3, never scribble past the end.
constexpr size_t kDecodeCap = size_t(1024) * 1024 * 3;
constexpr size_t kMaxRecords = 4096;

std::vector<uint8_t> ReadFile(const std::string& path) {
  std::vector<uint8_t> data;
  FILE* f = std::fopen(path.c_str(), "rb");
  if (f == nullptr) return data;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size > 0) {
    data.resize(static_cast<size_t>(size));
    if (std::fread(data.data(), 1, data.size(), f) != data.size()) {
      data.clear();
    }
  }
  std::fclose(f);
  return data;
}

void DriveTfrecord(const std::vector<uint8_t>& data) {
  std::vector<uint64_t> offsets(kMaxRecords), lengths(kMaxRecords);
  for (int verify = 0; verify <= 1; ++verify) {
    t2r_index_records(data.data(), data.size(), offsets.data(),
                      lengths.data(), kMaxRecords, verify);
    // Undersized max_records: the indexer keeps counting past the
    // arrays; the tail must not write them.
    t2r_index_records(data.data(), data.size(), offsets.data(),
                      lengths.data(), 1, verify);
    uint64_t consumed = 0;
    t2r_index_records_partial(data.data(), data.size(), offsets.data(),
                              lengths.data(), kMaxRecords, verify, &consumed);
    // Feed every tail of the buffer too: streaming readers resume at
    // arbitrary offsets after a partial block.
    if (data.size() > 1) {
      t2r_index_records_partial(data.data() + data.size() / 2,
                                data.size() - data.size() / 2, offsets.data(),
                                lengths.data(), kMaxRecords, verify,
                                &consumed);
    }
  }
}

void DriveJpeg(const std::vector<uint8_t>& data) {
  static std::vector<unsigned char> out(kDecodeCap);
  int h = 0, w = 0;
  for (int channels = 1; channels <= 3; channels += 2) {
    t2r_decode_jpeg(data.data(), data.size(), out.data(), out.size(),
                    channels, &h, &w);
    // Undersized output: must return -3 before writing row 0.
    t2r_decode_jpeg(data.data(), data.size(), out.data(), 64, channels, &h,
                    &w);
  }
  struct Rect {
    int y, x, h, w;
  };
  const Rect rects[] = {
      {0, 0, 16, 16},      // interior, top-left
      {17, 23, 23, 29},    // sub-MCU offsets
      {0, 0, 1, 1},        // minimal
      {500, 620, 12, 20},  // bottom-right edge of a 512x640 source
      {0, 0, 100000, 100000},  // far outside any frame (-5)
      {100000, 100000, 8, 8},  // offset outside the frame (-5)
  };
  int fh = 0, fw = 0;
  for (const Rect& r : rects) {
    t2r_decode_jpeg_roi(data.data(), data.size(), out.data(), out.size(), 3,
                        r.y, r.x, r.h, r.w, &fh, &fw);
    // Exact-fit output for the crop: any margin-handling bug that writes
    // one row/column extra lands outside this allocation.
    size_t need = size_t(r.h) * size_t(r.w) * 3;
    if (need <= kDecodeCap && r.h <= 4096 && r.w <= 4096) {
      std::vector<unsigned char> exact(need);
      t2r_decode_jpeg_roi(data.data(), data.size(), exact.data(),
                          exact.size(), 3, r.y, r.x, r.h, r.w, &fh, &fw);
    }
  }
}

int DriveFile(const std::string& path) {
  std::vector<uint8_t> data = ReadFile(path);
  std::printf("[t2r_fuzz] %s (%zu bytes)\n", path.c_str(), data.size());
  std::fflush(stdout);
  if (data.empty()) return 0;
  // Every file goes through BOTH parser families: the corpus does not
  // promise well-formedness in either format — that is the point.
  DriveTfrecord(data);
  DriveJpeg(data);
  return 0;
}

void CollectInputs(const std::string& path, std::vector<std::string>* files) {
  struct stat st;
  if (stat(path.c_str(), &st) != 0) return;
  if (!S_ISDIR(st.st_mode)) {
    files->push_back(path);
    return;
  }
  DIR* dir = opendir(path.c_str());
  if (dir == nullptr) return;
  std::vector<std::string> entries;
  while (dirent* entry = readdir(dir)) {
    if (entry->d_name[0] == '.') continue;
    entries.push_back(path + "/" + entry->d_name);
  }
  closedir(dir);
  // Deterministic order: a crash report names the same file every run.
  for (size_t i = 1; i < entries.size(); ++i) {
    for (size_t j = i; j > 0 && entries[j] < entries[j - 1]; --j) {
      std::swap(entries[j], entries[j - 1]);
    }
  }
  for (const std::string& entry : entries) CollectInputs(entry, files);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr,
                 "usage: %s <corpus-dir-or-files...> | --self-test-oob\n",
                 argv[0]);
    return 2;
  }
  if (std::strcmp(argv[1], "--self-test-oob") == 0) {
    // Sanitizer canary: a deliberate heap OOB read. Under the `sanitize`
    // build this MUST abort with an ASan report — a run of the corpus
    // only means something if this exits nonzero first (otherwise the
    // binary was silently built without instrumentation and "survived"
    // is vacuous). tools/run_checks.sh asserts the abort.
    volatile uint8_t* buf = new uint8_t[16];
    volatile uint8_t poison = buf[16];
    std::printf("[t2r_fuzz] self-test OOB read returned %d — sanitizer "
                "NOT active\n",
                int(poison));
    delete[] buf;
    return 3;
  }
  std::vector<std::string> files;
  for (int i = 1; i < argc; ++i) CollectInputs(argv[i], &files);
  if (files.empty()) {
    std::fprintf(stderr, "[t2r_fuzz] no corpus files found\n");
    return 2;
  }
  for (const std::string& file : files) DriveFile(file);
  std::printf("[t2r_fuzz] OK: %zu files survived\n", files.size());
  return 0;
}
