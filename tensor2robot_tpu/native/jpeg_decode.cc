// Direct libjpeg(-turbo) JPEG decode into a caller-provided buffer.
//
// The Python parse pipeline's profile (docs/PERFORMANCE.md host-feed
// section) shows ~90% of record-parse time inside PIL's chunked jpeg
// decode: the bytes are fed to the decoder in 64 KB increments through a
// Python-level loop, the decoded image lands in a PIL object, and the
// mode conversion + numpy export each copy the full frame. This path
// decodes the whole in-memory buffer in ONE libjpeg call directly into
// the numpy array the parser hands over — no chunk loop, no PIL object,
// no convert copy.
//
// Exported C ABI (ctypes-consumed by tensor2robot_tpu/data/parser.py):
//   t2r_decode_jpeg(data, len, out, out_capacity, want_channels,
//                   &h, &w) -> 0 on success, negative on failure.
//     want_channels: 3 (RGB) or 1 (grayscale); the decoder converts
//     whatever subsampling/colorspace the file uses.
//   t2r_decode_jpeg_roi(data, len, out, out_capacity, want_channels,
//                       crop_y, crop_x, crop_h, crop_w, &full_h, &full_w)
//     -> decode ONLY the crop window into `out` (crop_h x crop_w x C).
//     Rows above the window are skipped before IDCT/upsampling
//     (jpeg_skip_scanlines), rows below are never read
//     (jpeg_abort_decompress), and columns are trimmed at iMCU
//     granularity (jpeg_crop_scanline); the sub-MCU horizontal residual
//     is resolved by decoding the MCU-aligned span into a scratch row
//     and memcpy'ing the requested window — so the output is
//     bit-identical to a full decode followed by the same crop.
//     Requires the libjpeg-turbo API (Makefile probes jpeglib.h and
//     defines T2R_HAVE_JPEG_ROI); without it the entry point returns -6
//     and the Python caller falls back to full-decode-then-crop.
//
// libjpeg's default error handler calls exit(); a setjmp-based handler
// turns decode errors into error returns instead.

#include <csetjmp>
#include <cstddef>
#include <cstdio>
#include <cstring>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  std::longjmp(mgr->jump, 1);
}

void emit_message(j_common_ptr, int) {}  // silence warnings

}  // namespace

extern "C" {

// Returns 0 on success; -1 bad args, -2 decode error, -3 buffer too
// small, -4 unsupported channel request.
int t2r_decode_jpeg(const unsigned char* data, size_t len,
                    unsigned char* out, size_t out_capacity,
                    int want_channels, int* height, int* width) {
  if (data == nullptr || out == nullptr || len == 0) return -1;
  if (want_channels != 1 && want_channels != 3) return -4;

  jpeg_decompress_struct cinfo;
  ErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = error_exit;
  err.pub.emit_message = emit_message;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  cinfo.out_color_space = (want_channels == 3) ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);

  const size_t row_stride =
      static_cast<size_t>(cinfo.output_width) * cinfo.output_components;
  const size_t need =
      row_stride * static_cast<size_t>(cinfo.output_height);
  if (need > out_capacity) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }

  while (cinfo.output_scanline < cinfo.output_height) {
    // Decode as many rows per call as libjpeg will give us, straight
    // into the output buffer (rec_outbuf_height rows per call typically).
    JSAMPROW rows[4];
    unsigned int n = 0;
    for (; n < 4 && cinfo.output_scanline + n < cinfo.output_height; ++n) {
      rows[n] = out + (cinfo.output_scanline + n) * row_stride;
    }
    jpeg_read_scanlines(&cinfo, rows, n);
  }

  *height = static_cast<int>(cinfo.output_height);
  *width = static_cast<int>(cinfo.output_width);
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Returns 0 on success; -1 bad args, -2 decode error, -3 buffer too
// small, -4 unsupported channel request, -5 crop outside the image,
// -6 ROI API not compiled in, -7 progressive source (ROI skip is not
// worth it there: progressive decode buffers whole passes anyway).
int t2r_decode_jpeg_roi(const unsigned char* data, size_t len,
                        unsigned char* out, size_t out_capacity,
                        int want_channels, int crop_y, int crop_x,
                        int crop_h, int crop_w, int* full_height,
                        int* full_width) {
#ifndef T2R_HAVE_JPEG_ROI
  (void)data; (void)len; (void)out; (void)out_capacity;
  (void)want_channels; (void)crop_y; (void)crop_x; (void)crop_h;
  (void)crop_w; (void)full_height; (void)full_width;
  return -6;
#else
  if (data == nullptr || out == nullptr || len == 0) return -1;
  if (want_channels != 1 && want_channels != 3) return -4;
  if (crop_y < 0 || crop_x < 0 || crop_h <= 0 || crop_w <= 0) return -5;

  jpeg_decompress_struct cinfo;
  ErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = error_exit;
  err.pub.emit_message = emit_message;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  if (cinfo.progressive_mode) {
    jpeg_destroy_decompress(&cinfo);
    return -7;
  }
  cinfo.out_color_space = (want_channels == 3) ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);

  *full_height = static_cast<int>(cinfo.output_height);
  *full_width = static_cast<int>(cinfo.output_width);
  if (crop_y + crop_h > *full_height || crop_x + crop_w > *full_width) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -5;
  }
  const size_t out_stride =
      static_cast<size_t>(crop_w) * cinfo.output_components;
  if (out_stride * static_cast<size_t>(crop_h) > out_capacity) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }

  // Fancy upsampling (the libjpeg default, and what a full decode uses)
  // reads neighboring chroma samples; at the edges of a cropped span it
  // falls back to edge replication, which changes the boundary pixels.
  // A full decode only replicates at the true image edges — so to stay
  // bit-identical we decode a MARGIN around the requested window (2 px,
  // then iMCU-aligned, clamped to the image) and slice the exact window
  // out. The margin is at most one extra iMCU row/column of work.
  const int mcu_w = cinfo.max_h_samp_factor * DCTSIZE;
  const int mcu_h = cinfo.max_v_samp_factor * DCTSIZE;
  const int margin = 2;

  // Columns: trim to the iMCU span covering the margin-padded window.
  // jpeg_crop_scanline aligns xoff DOWN and widens the span; the
  // sub-MCU residual `lead` is sliced off each scratch row below.
  const int left = crop_x > margin ? (crop_x - margin) / mcu_w * mcu_w : 0;
  const int right =
      crop_x + crop_w + margin < *full_width ? crop_x + crop_w + margin
                                             : *full_width;
  JDIMENSION xoff = static_cast<JDIMENSION>(left);
  JDIMENSION xw = static_cast<JDIMENSION>(right - left);
  jpeg_crop_scanline(&cinfo, &xoff, &xw);
  if (static_cast<JDIMENSION>(crop_x) < xoff ||
      static_cast<JDIMENSION>(crop_x + crop_w) > xoff + xw) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  const size_t lead =
      (static_cast<size_t>(crop_x) - xoff) * cinfo.output_components;
  const JDIMENSION span_stride = xw * cinfo.output_components;

  // Scratch rows come from libjpeg's image-lifetime pool, freed by
  // jpeg_destroy_decompress on every exit path (including longjmp).
  const JDIMENSION n_scratch = 4;
  JSAMPARRAY scratch = (*cinfo.mem->alloc_sarray)(
      reinterpret_cast<j_common_ptr>(&cinfo), JPOOL_IMAGE, span_stride,
      n_scratch);

  // Rows above the window: skip whole iMCU rows up to the margin-padded
  // start (entropy decode still walks them — the bitstream is
  // sequential — but IDCT/upsample/color-convert are bypassed), then
  // decode-and-discard the residual margin rows so the upsampler enters
  // the window with the same context a full decode would have.
  JDIMENSION target = static_cast<JDIMENSION>(crop_y);
  const JDIMENSION y_start = static_cast<JDIMENSION>(
      crop_y > margin ? (crop_y - margin) / mcu_h * mcu_h : 0);
  while (cinfo.output_scanline < y_start) {
    if (jpeg_skip_scanlines(&cinfo, y_start - cinfo.output_scanline) == 0) {
      jpeg_abort_decompress(&cinfo);
      jpeg_destroy_decompress(&cinfo);
      return -2;
    }
  }
  while (cinfo.output_scanline < target) {
    JDIMENSION want = target - cinfo.output_scanline;
    if (want > n_scratch) want = n_scratch;
    if (jpeg_read_scanlines(&cinfo, scratch, want) == 0) {
      jpeg_abort_decompress(&cinfo);
      jpeg_destroy_decompress(&cinfo);
      return -2;
    }
  }

  const JDIMENSION end = target + static_cast<JDIMENSION>(crop_h);
  while (cinfo.output_scanline < end) {
    JDIMENSION want = end - cinfo.output_scanline;
    if (want > n_scratch) want = n_scratch;
    JDIMENSION got = jpeg_read_scanlines(&cinfo, scratch, want);
    if (got == 0) {
      jpeg_abort_decompress(&cinfo);
      jpeg_destroy_decompress(&cinfo);
      return -2;
    }
    for (JDIMENSION r = 0; r < got; ++r) {
      const size_t out_row = cinfo.output_scanline - got + r - target;
      std::memcpy(out + out_row * out_stride, scratch[r] + lead,
                  out_stride);
    }
  }

  // Rows below the window are never decoded: abort instead of finish.
  jpeg_abort_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
#endif  // T2R_HAVE_JPEG_ROI
}

}  // extern "C"
