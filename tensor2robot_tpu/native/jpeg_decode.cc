// Direct libjpeg(-turbo) JPEG decode into a caller-provided buffer.
//
// The Python parse pipeline's profile (docs/PERFORMANCE.md host-feed
// section) shows ~90% of record-parse time inside PIL's chunked jpeg
// decode: the bytes are fed to the decoder in 64 KB increments through a
// Python-level loop, the decoded image lands in a PIL object, and the
// mode conversion + numpy export each copy the full frame. This path
// decodes the whole in-memory buffer in ONE libjpeg call directly into
// the numpy array the parser hands over — no chunk loop, no PIL object,
// no convert copy.
//
// Exported C ABI (ctypes-consumed by tensor2robot_tpu/data/parser.py):
//   t2r_decode_jpeg(data, len, out, out_capacity, want_channels,
//                   &h, &w) -> 0 on success, negative on failure.
//     want_channels: 3 (RGB) or 1 (grayscale); the decoder converts
//     whatever subsampling/colorspace the file uses.
//
// libjpeg's default error handler calls exit(); a setjmp-based handler
// turns decode errors into error returns instead.

#include <csetjmp>
#include <cstddef>
#include <cstdio>
#include <cstring>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  std::longjmp(mgr->jump, 1);
}

void emit_message(j_common_ptr, int) {}  // silence warnings

}  // namespace

extern "C" {

// Returns 0 on success; -1 bad args, -2 decode error, -3 buffer too
// small, -4 unsupported channel request.
int t2r_decode_jpeg(const unsigned char* data, size_t len,
                    unsigned char* out, size_t out_capacity,
                    int want_channels, int* height, int* width) {
  if (data == nullptr || out == nullptr || len == 0) return -1;
  if (want_channels != 1 && want_channels != 3) return -4;

  jpeg_decompress_struct cinfo;
  ErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = error_exit;
  err.pub.emit_message = emit_message;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  cinfo.out_color_space = (want_channels == 3) ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);

  const size_t row_stride =
      static_cast<size_t>(cinfo.output_width) * cinfo.output_components;
  const size_t need =
      row_stride * static_cast<size_t>(cinfo.output_height);
  if (need > out_capacity) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }

  while (cinfo.output_scanline < cinfo.output_height) {
    // Decode as many rows per call as libjpeg will give us, straight
    // into the output buffer (rec_outbuf_height rows per call typically).
    JSAMPROW rows[4];
    unsigned int n = 0;
    for (; n < 4 && cinfo.output_scanline + n < cinfo.output_height; ++n) {
      rows[n] = out + (cinfo.output_scanline + n) * row_stride;
    }
    jpeg_read_scanlines(&cinfo, rows, n);
  }

  *height = static_cast<int>(cinfo.output_height);
  *width = static_cast<int>(cinfo.output_width);
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // extern "C"
