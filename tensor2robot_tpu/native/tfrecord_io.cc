// Native TFRecord codec: CRC32-C (Castagnoli) + record framing.
//
// The TFRecord container format (public): each record is
//   uint64  length            (little-endian)
//   uint32  masked_crc32c(length bytes)
//   bytes   data[length]
//   uint32  masked_crc32c(data)
// with mask(crc) = ((crc >> 15) | (crc << 17)) + 0xa282ead8.
//
// The reference delegated record IO to the TensorFlow runtime; here it is a
// small standalone C++ library driven from Python via ctypes, used by
// tensor2robot_tpu/data/tfrecord.py for both the replay-writer and the
// training input pipeline (reference behavior: utils/tfdata.py,
// utils/writer.py).
//
// CRC32-C uses slicing-by-8 for ~1 GB/s/core in portable C++ (no SSE4.2
// dependency so it builds anywhere, including TPU-VM images).

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    const uint32_t poly = 0x82f63b78u;  // reversed Castagnoli polynomial
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int j = 0; j < 8; ++j) {
        crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
      }
      t[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = t[0][i];
      for (int s = 1; s < 8; ++s) {
        crc = t[0][crc & 0xff] ^ (crc >> 8);
        t[s][i] = crc;
      }
    }
  }
};

// C++11 magic static: thread-safe one-time init (ctypes calls arrive from
// multiple Python prefetch threads with the GIL released).
const CrcTables& Tables() {
  static const CrcTables tables;
  return tables;
}
#define kTable Tables().t

inline uint32_t Crc32cUpdate(uint32_t crc, const uint8_t* data, size_t n) {
  crc = ~crc;
  while (n >= 8) {
    uint64_t word;
    std::memcpy(&word, data, 8);
    word ^= crc;  // little-endian assumed (x86/ARM/TPU hosts)
    crc = kTable[7][word & 0xff] ^ kTable[6][(word >> 8) & 0xff] ^
          kTable[5][(word >> 16) & 0xff] ^ kTable[4][(word >> 24) & 0xff] ^
          kTable[3][(word >> 32) & 0xff] ^ kTable[2][(word >> 40) & 0xff] ^
          kTable[1][(word >> 48) & 0xff] ^ kTable[0][(word >> 56) & 0xff];
    data += 8;
    n -= 8;
  }
  while (n--) {
    crc = kTable[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
  }
  return ~crc;
}

inline uint32_t Mask(uint32_t crc) {
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

inline uint32_t ReadU32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t ReadU64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" {

uint32_t t2r_crc32c(const uint8_t* data, size_t n) {
  (void)Tables();
  return Crc32cUpdate(0, data, n);
}

uint32_t t2r_masked_crc32c(const uint8_t* data, size_t n) {
  (void)Tables();
  return Mask(Crc32cUpdate(0, data, n));
}

// Scans a TFRecord buffer, writing each record's payload offset and length.
// Returns the record count, or -(byte_position+1) on corruption so Python can
// report where the file went bad. verify_crc=0 skips payload CRC checks
// (header CRC is always checked — it guards the framing).
int64_t t2r_index_records(const uint8_t* buf, size_t n, uint64_t* offsets,
                          uint64_t* lengths, size_t max_records,
                          int verify_crc) {
  (void)Tables();
  size_t pos = 0;
  int64_t count = 0;
  while (pos < n) {
    if (pos + 12 > n) return -(int64_t)(pos + 1);
    uint64_t len = ReadU64(buf + pos);
    uint32_t len_crc = ReadU32(buf + pos + 8);
    if (Mask(Crc32cUpdate(0, buf + pos, 8)) != len_crc) {
      return -(int64_t)(pos + 1);
    }
    // Overflow-safe bounds check: a corrupt length near 2^64 must report
    // corruption, not wrap around and read out of bounds.
    size_t remaining = n - (pos + 12);
    if (remaining < 4 || len > remaining - 4) return -(int64_t)(pos + 1);
    if (verify_crc) {
      uint32_t data_crc = ReadU32(buf + pos + 12 + len);
      if (Mask(Crc32cUpdate(0, buf + pos + 12, len)) != data_crc) {
        return -(int64_t)(pos + 1);
      }
    }
    if ((size_t)count < max_records) {
      offsets[count] = pos + 12;
      lengths[count] = len;
    }
    ++count;
    pos += 12 + len + 4;
  }
  return count;
}

// Like t2r_index_records, but for STREAMING use over a block buffer that
// may end mid-record: a trailing incomplete record is not an error.
// Scans complete records only, stops at max_records or the first
// incomplete tail, and reports via *consumed how many leading bytes of
// buf were fully indexed (the caller slides its window by that amount and
// reads more). Corruption inside a complete record (bad header or payload
// CRC) still returns -(byte_position+1). Note a corrupt length field that
// claims more bytes than the buffer holds is indistinguishable from an
// incomplete tail here; the Python caller bounds that case (implausible
// lengths, leftover bytes at EOF) and reports corruption itself.
int64_t t2r_index_records_partial(const uint8_t* buf, size_t n,
                                  uint64_t* offsets, uint64_t* lengths,
                                  size_t max_records, int verify_crc,
                                  uint64_t* consumed) {
  (void)Tables();
  size_t pos = 0;
  int64_t count = 0;
  while (pos < n && (size_t)count < max_records) {
    if (pos + 12 > n) break;  // incomplete header
    uint64_t len = ReadU64(buf + pos);
    uint32_t len_crc = ReadU32(buf + pos + 8);
    if (Mask(Crc32cUpdate(0, buf + pos, 8)) != len_crc) {
      return -(int64_t)(pos + 1);
    }
    size_t remaining = n - (pos + 12);
    if (remaining < 4 || len > remaining - 4) break;  // incomplete payload
    if (verify_crc) {
      uint32_t data_crc = ReadU32(buf + pos + 12 + len);
      if (Mask(Crc32cUpdate(0, buf + pos + 12, len)) != data_crc) {
        return -(int64_t)(pos + 1);
      }
    }
    offsets[count] = pos + 12;
    lengths[count] = len;
    ++count;
    pos += 12 + len + 4;
  }
  *consumed = pos;
  return count;
}

// Frames a single record into out (which must hold 16 + len bytes).
// Returns the framed size.
size_t t2r_frame_record(const uint8_t* data, size_t len, uint8_t* out) {
  (void)Tables();
  uint64_t len64 = len;
  std::memcpy(out, &len64, 8);
  uint32_t len_crc = Mask(Crc32cUpdate(0, out, 8));
  std::memcpy(out + 8, &len_crc, 4);
  std::memcpy(out + 12, data, len);
  uint32_t data_crc = Mask(Crc32cUpdate(0, data, len));
  std::memcpy(out + 12 + len, &data_crc, 4);
  return 16 + len;
}

}  // extern "C"
