"""Shared network layer: the CRC-framed wire both fabrics speak.

`net.frames` holds the frame codec (magic/len/crc32,
whole-frame-or-nothing decode), the published-address `transport.json`
discovery contract with incarnation stamps, the accept-loop
`FrameServer` (request/reply and duplex shapes), and the self-healing
`SocketChannel` client. The replay fabric (`replay/transport.py`) and
the serving fabric (`serving/pool.py`) both consume THIS module, so
their wires cannot drift."""

from tensor2robot_tpu.net.frames import (  # noqa: F401
    ADDRESS_FILENAME,
    BadFrame,
    ConnectionClosed,
    FrameServer,
    MAX_FRAME_BYTES,
    SocketChannel,
    TransportError,
    encode_frame,
    publish_address,
    read_address,
    read_address_info,
    read_frame,
    write_frame,
)

__all__ = [
    "ADDRESS_FILENAME",
    "BadFrame",
    "ConnectionClosed",
    "FrameServer",
    "MAX_FRAME_BYTES",
    "SocketChannel",
    "TransportError",
    "encode_frame",
    "publish_address",
    "read_address",
    "read_address_info",
    "read_frame",
    "write_frame",
]
