"""Spec-native frame codec: scatter-gather segments, pooled receive.

`net/frames.py` gives every t2r fabric one CRC-framed wire, but its
payload is a single `pickle.dumps` blob — for image-bearing serving
observations that is several full-array copies per hop (dumps copies
the array into the stream, the header concat copies the stream, the
receiver joins chunks and `pickle.loads` copies the arrays back out).
This module is the zero-copy alternative, selected by `T2R_WIRE=spec`:

    u32 magic         (SEG_MAGIC, 0x54325357 — distinct from the pickle
                       wire's MAGIC so receivers auto-detect the codec)
    u32 body_length
    u32 adler32(body)
    u32 crc32(table + skeleton)
    u32 nsegs
    u32 skeleton_length
    body:
        u32 x nsegs   segment lengths (the segment table)
        skeleton      pickled message with array/bytes leaves replaced
                      by small placeholder objects (op, request id and
                      every other scalar ride here)
        segments      raw array bytes, each 64-byte aligned, in index
                      order

Encode is **zero concatenation**: the frame is a list of memoryviews —
prefix, table, skeleton, then each array's own buffer — checksummed
incrementally (`zlib.adler32(seg, a)`) and handed to `socket.sendmsg`
as an iovec. Integrity is two-tier on purpose: the bulk body rides
adler32, which runs ~2.5x faster than this zlib's crc32 and still
detects every single-byte corruption (the chaos `corrupt` action and
every corpus bitflip variant); the small structural region (segment
table + skeleton) additionally carries its own crc32, so the part of
the frame that steers decoding keeps the stronger check at ~zero
cost. The pickle wire's frames are untouched — crc32, bit-identical
to the pre-spec bytes.

Decode `recv_into`s a pooled reusable buffer (the body checksum
verified incrementally during the read, so a corrupt 64MB frame is
rejected in one pass) and resolves placeholders straight to
`np.frombuffer` views into that buffer, validated against the
placeholder's dtype/shape spec — wrong segment length, bad index, or
an undecodable skeleton is a typed `CodecError` the framing layer
turns into `BadFrame` (whole-frame-or-nothing, same contract as the
pickle wire).

Buffer pool discipline: a decoded frame's views share one pooled
buffer lease; each view carries a `weakref.finalize` that releases the
lease when the LAST view dies, returning the buffer for the next
frame. Steady-state serving therefore allocates nothing per frame on
the receive path (`BufferPool.snapshot()["allocs"]` is the audit
surface). Frames that decode to no views release their lease
immediately.

Quantized observation payloads (`T2R_WIRE_QUANT`): float arrays ride
the `BlockScaledCollective` wire format from `parallel/collectives.py`
(`{'q': values, 's': per-block max-abs scales}`, numpy mirror — no
jax dispatch on the hot path), uint8 image planes pass through
untouched as raw segments. Every quantized array is round-tripped at
encode time against its per-mode parity gate (`QUANT_PARITY_REL_LINF`,
rel-Linf vs the array's max-abs); an array that misses its gate is
sent dense and counted (`quant_parity_fallbacks`) — lossy-beyond-gate
bytes never reach the wire.
"""

from __future__ import annotations

import pickle
import struct
import threading
import time
import weakref
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.testing import locksmith

try:  # fp8 wire formats need ml_dtypes (jax ships it); gate, don't require
    import ml_dtypes as _ml_dtypes
except Exception:  # pragma: no cover - environment without ml_dtypes
    _ml_dtypes = None

__all__ = [
    "CodecError",
    "SEG_MAGIC",
    "SPEC_PREFIX",
    "SEGMENT_MIN_BYTES",
    "QUANT_PARITY_REL_LINF",
    "BufferPool",
    "WireStats",
    "POOL",
    "WIRE",
    "wire_mode",
    "quant_mode",
    "encode_spec_frame",
    "encode_spec_frame_bytes",
    "decode_spec_body",
    "quant_encode_array",
    "quant_decode_array",
    "wire_snapshot",
    "reset_wire_stats",
]

SEG_MAGIC = 0x54325357  # "WS2T" on the wire; >=2 bitflips from MAGIC
# magic, body_len, adler32(body), crc32(table+skeleton), nsegs,
# skeleton_len
SPEC_PREFIX = struct.Struct("<IIIIII")
# Leaves below this stay in the pickled skeleton: a placeholder +
# table entry + alignment pad costs more than pickling a small array.
SEGMENT_MIN_BYTES = 256
MAX_SEGMENTS = 4096
_SEG_ALIGN = 64
_ZEROS = bytes(_SEG_ALIGN)

# Per-mode parity gates (rel-Linf of the encode-time round trip vs the
# array's max-abs). int8/fp16 sit far inside 5e-2; fp8_e4m3's 3
# mantissa bits bound worst-case relative rounding at ~3.2e-2 (inside
# the shared gate); e5m2's 2 bits bound it at ~6.3e-2, so it declares
# the wider gate rather than silently falling back on every array.
QUANT_PARITY_REL_LINF: Dict[str, float] = {
    "fp16": 5e-2,
    "int8": 5e-2,
    "fp8_e4m3": 5e-2,
    "fp8_e5m2": 1e-1,
}
_FP8_MAX = {"fp8_e4m3": 448.0, "fp8_e5m2": 57344.0}


class CodecError(ValueError):
    """Spec-frame violation (bad table, bad placeholder, spec
    mismatch). The framing layer maps this to BadFrame: the stream
    position is fine (the body was length-delimited and CRC-clean) but
    the frame is refused whole."""


def wire_mode() -> str:
    """The frame codec every *send* uses; receivers auto-detect."""
    return t2r_flags.get_enum("T2R_WIRE")


def quant_mode() -> str:
    return t2r_flags.get_enum("T2R_WIRE_QUANT")


# -- stats ---------------------------------------------------------------------


class WireStats:
    """Per-process wire accounting: per-segment-class byte counters and
    per-stage timings (serialize/crc/send/recv/deserialize). Pool and
    router snapshots surface this; the bench artifact pins it."""

    def __init__(self):
        self._lock = locksmith.make_lock("WireStats._lock")
        self._counters: Dict[str, int] = {}
        self._timings: Dict[str, float] = {}

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + int(n)

    def time(self, key: str, seconds: float) -> None:
        with self._lock:
            self._timings[key] = self._timings.get(key, 0.0) + seconds

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timings_ms": {
                    k: round(v * 1e3, 3) for k, v in self._timings.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timings.clear()


WIRE = WireStats()


# -- the receive-side buffer pool ----------------------------------------------


class _Lease:
    """One pooled buffer on loan to one frame's worth of consumers.

    The decoder holds the initial reference; every `np.frombuffer` view
    it hands out retains once and releases through a `weakref.finalize`
    when the view dies. The buffer returns to the pool exactly when the
    last holder lets go — never while a consumer can still read it."""

    __slots__ = ("_pool", "buf", "_refs", "_lock")

    def __init__(self, pool: "BufferPool", buf: bytearray):
        self._pool = pool
        self.buf = buf
        self._refs = 1
        self._lock = locksmith.make_lock("BufferPool._lease_lock")

    def retain(self) -> None:
        with self._lock:
            self._refs += 1

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            done = self._refs == 0
        if done:
            self._pool._put(self.buf)


class BufferPool:
    """Reusable receive buffers, power-of-two sized.

    `acquire(n)` hands back a lease on a buffer of at least n bytes —
    reusing a pooled one when any fits (steady state), allocating and
    counting otherwise (`allocs` is the audit counter: flat after
    warmup means the receive path allocates nothing per frame)."""

    def __init__(self, max_retained: int = 8, min_bytes: int = 1 << 16):
        self._lock = locksmith.make_lock("BufferPool._lock")
        self._free: List[bytearray] = []
        self._max_retained = max_retained
        self._min_bytes = min_bytes
        self._allocs = 0
        self._reuses = 0
        self._discards = 0

    @staticmethod
    def _round_up(n: int, floor: int) -> int:
        size = floor
        while size < n:
            size <<= 1
        return size

    def acquire(self, n: int) -> _Lease:
        size = self._round_up(max(1, n), self._min_bytes)
        with self._lock:
            best = None
            for i, buf in enumerate(self._free):
                if len(buf) >= size and (
                    best is None or len(buf) < len(self._free[best])
                ):
                    best = i
            if best is not None:
                self._reuses += 1
                return _Lease(self, self._free.pop(best))
            self._allocs += 1
        return _Lease(self, bytearray(size))

    def _put(self, buf: bytearray) -> None:
        with self._lock:
            if len(self._free) < self._max_retained:
                self._free.append(buf)
            else:
                self._discards += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "allocs": self._allocs,
                "reuses": self._reuses,
                "discards": self._discards,
                "retained": len(self._free),
                "retained_bytes": sum(len(b) for b in self._free),
            }


POOL = BufferPool()


def wire_snapshot() -> Dict[str, Any]:
    """One merged observability surface: stats + pool audit."""
    snap = WIRE.snapshot()
    snap["pool"] = POOL.snapshot()
    return snap


def reset_wire_stats() -> None:
    WIRE.reset()


# -- skeleton placeholders -----------------------------------------------------


class _SegRef:
    """Raw array segment: decodes to an np.frombuffer view."""

    __slots__ = ("i", "dtype", "shape")

    def __init__(self, i: int, dtype: str, shape: Tuple[int, ...]):
        self.i, self.dtype, self.shape = i, dtype, shape

    def __getstate__(self):
        return (self.i, self.dtype, self.shape)

    def __setstate__(self, state):
        self.i, self.dtype, self.shape = state


class _SegBytes:
    """Raw bytes segment (e.g. an already-serialized replay episode or
    a packed reply blob): decodes to bytes copied out of the pool."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i

    def __getstate__(self):
        return self.i

    def __setstate__(self, state):
        self.i = state


class _SegQuant:
    """Blockwise-quantized float array: q-values segment + float32
    per-block-scales segment, the BlockScaledCollective wire format."""

    __slots__ = ("qi", "si", "dtype", "shape", "mode", "block")

    def __init__(self, qi, si, dtype, shape, mode, block):
        self.qi, self.si = qi, si
        self.dtype, self.shape = dtype, shape
        self.mode, self.block = mode, block

    def __getstate__(self):
        return (self.qi, self.si, self.dtype, self.shape,
                self.mode, self.block)

    def __setstate__(self, state):
        (self.qi, self.si, self.dtype, self.shape,
         self.mode, self.block) = state


def _quant_dtype(mode: str):
    if mode == "int8":
        return np.dtype(np.int8)
    if mode == "fp16":
        return np.dtype(np.float16)
    if mode in _FP8_MAX:
        if _ml_dtypes is None:
            raise CodecError(
                f"wire quant mode {mode!r} needs ml_dtypes, which this "
                "interpreter does not have"
            )
        return np.dtype(
            _ml_dtypes.float8_e4m3fn if mode == "fp8_e4m3"
            else _ml_dtypes.float8_e5m2
        )
    raise CodecError(f"unknown wire quant mode {mode!r}")


def quant_encode_array(
    arr: np.ndarray, mode: str, block: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(q, scales) in the BlockScaledCollective format, or None when
    the round trip misses the mode's parity gate (caller sends dense).
    Pure numpy on purpose: a jnp dispatch per message would cost more
    than the bytes it saves on this hot path."""
    try:
        qdtype = _quant_dtype(mode)
    except CodecError:
        return None
    flat = np.ascontiguousarray(arr).reshape(-1).astype(
        np.float32, copy=False
    )
    n = flat.size
    nblocks = -(-n // block)
    if nblocks * block != n:
        padded = np.zeros(nblocks * block, dtype=np.float32)
        padded[:n] = flat
        flat = padded
    blocks = flat.reshape(nblocks, block)
    maxabs = np.max(np.abs(blocks), axis=1)
    if not np.all(np.isfinite(maxabs)):
        # An inf/nan anywhere poisons its block's scale (and the parity
        # measurement itself): such arrays ride dense.
        return None
    base = np.where(maxabs > 0, maxabs, 1.0).astype(np.float32)
    if mode == "int8":
        scales = base / 127.0
        q = np.clip(
            np.rint(blocks / scales[:, None]), -127, 127
        ).astype(np.int8)
    elif mode == "fp16":
        scales = base
        q = (blocks / scales[:, None]).astype(np.float16)
    else:
        fmax = _FP8_MAX[mode]
        scales = base / fmax
        # The clip is load-bearing (same reason as the collectives):
        # fp8 casts do not saturate, an overflow is inf/NaN.
        q = np.clip(blocks / scales[:, None], -fmax, fmax).astype(qdtype)
    # Encode-time parity gate: round-trip and measure rel-Linf against
    # the array's own max-abs. Zero-pad blocks round-trip exactly. The
    # inverted comparison is load-bearing: a nan `rel` (all-nan input
    # that dodged the maxabs guard) must read as a MISS, never as
    # "within gate".
    decoded = q.astype(np.float32) * scales[:, None]
    denom = float(maxabs.max()) if maxabs.size else 0.0
    if denom > 0:
        rel = float(np.max(np.abs(blocks - decoded))) / denom
        if not rel <= QUANT_PARITY_REL_LINF[mode]:
            return None
    return np.ascontiguousarray(q), np.ascontiguousarray(scales)


def quant_decode_array(
    q: np.ndarray, scales: np.ndarray, shape: Tuple[int, ...], dtype
) -> np.ndarray:
    blocks = q.astype(np.float32) * scales[:, None].astype(np.float32)
    n = 1
    for dim in shape:
        n *= int(dim)
    flat = blocks.reshape(-1)[:n]
    return flat.astype(np.dtype(dtype), copy=False).reshape(shape)


# -- encode --------------------------------------------------------------------


class _EncodeState:
    __slots__ = ("segs", "mode", "block", "raw_bytes", "quant_bytes",
                 "blob_bytes", "noncontig", "fallbacks")

    def __init__(self, mode: str, block: int):
        self.segs: List[Any] = []  # buffer-protocol objects
        self.mode = mode
        self.block = block
        self.raw_bytes = 0
        self.quant_bytes = 0
        self.blob_bytes = 0
        self.noncontig = 0
        self.fallbacks = 0

    def add(self, buf) -> int:
        self.segs.append(buf)
        return len(self.segs) - 1


def _quant_eligible(arr: np.ndarray, state: _EncodeState) -> bool:
    return (
        state.mode != "none"
        and arr.dtype.kind == "f"
        and arr.dtype.itemsize >= 4
        and arr.size >= state.block
    )


def _flatten(obj: Any, state: _EncodeState) -> Any:
    t = type(obj)
    if t is dict:
        return {k: _flatten(v, state) for k, v in obj.items()}
    if t is list:
        return [_flatten(v, state) for v in obj]
    if t is tuple:
        return tuple(_flatten(v, state) for v in obj)
    if t is bytes and len(obj) >= SEGMENT_MIN_BYTES:
        state.blob_bytes += len(obj)
        return _SegBytes(state.add(obj))
    if (
        isinstance(obj, np.ndarray)
        and obj.dtype != object
        and obj.nbytes >= SEGMENT_MIN_BYTES
        and obj.dtype.itemsize > 0
    ):
        if _quant_eligible(obj, state):
            encoded = quant_encode_array(obj, state.mode, state.block)
            if encoded is not None:
                q, scales = encoded
                qi = state.add(q.data.cast("B"))
                si = state.add(scales.data.cast("B"))
                state.quant_bytes += q.nbytes + scales.nbytes
                return _SegQuant(
                    qi, si, str(obj.dtype), obj.shape,
                    state.mode, state.block,
                )
            state.fallbacks += 1
        arr = obj
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
            state.noncontig += 1
        state.raw_bytes += arr.nbytes
        return _SegRef(
            state.add(arr.data.cast("B")), str(arr.dtype), arr.shape
        )
    return obj


def _align_up(n: int) -> int:
    return (n + _SEG_ALIGN - 1) // _SEG_ALIGN * _SEG_ALIGN


def encode_spec_frame(
    message: Any, max_bytes: int = 64 << 20
) -> Tuple[List[Any], int]:
    """(buffers, body_len): the scatter-gather iovec for one frame —
    prefix, segment table, skeleton, then each segment (64-byte
    aligned via shared zero pads). No buffer is a concatenation of any
    other; array segments are views of the caller's own arrays."""
    t0 = time.perf_counter()
    mode = quant_mode()
    state = _EncodeState(
        mode, t2r_flags.get_int("T2R_COLLECTIVE_BLOCK")
    )
    skeleton_obj = _flatten(message, state)
    skeleton = pickle.dumps(
        skeleton_obj, protocol=pickle.HIGHEST_PROTOCOL
    )
    nsegs = len(state.segs)
    if nsegs > MAX_SEGMENTS:
        raise CodecError(
            f"message flattened to {nsegs} segments "
            f"(bound {MAX_SEGMENTS})"
        )
    table = struct.pack(f"<{nsegs}I", *[len(s) for s in state.segs])
    t1 = time.perf_counter()

    body: List[Any] = [table, skeleton]
    pos = len(table) + len(skeleton)
    for seg in state.segs:
        pad = _align_up(pos) - pos
        if pad:
            body.append(_ZEROS[:pad])
            pos += pad
        body.append(seg)
        pos += len(seg)
    if pos > max_bytes:
        # Mirrors encode_frame's bound; framing layer re-raises as a
        # TransportError with the frame-bound wording.
        raise CodecError(
            f"message of {pos} bytes exceeds the {max_bytes}-byte "
            "frame bound"
        )
    adler = 1
    for buf in body:
        adler = zlib.adler32(buf, adler)
    crc = zlib.crc32(skeleton, zlib.crc32(table))
    t2 = time.perf_counter()
    prefix = SPEC_PREFIX.pack(
        SEG_MAGIC, pos, adler & 0xFFFFFFFF, crc & 0xFFFFFFFF,
        nsegs, len(skeleton),
    )
    WIRE.time("serialize_ms", t1 - t0)
    WIRE.time("crc_ms", t2 - t1)
    WIRE.count("frames_spec_tx")
    WIRE.count("bytes_header", SPEC_PREFIX.size)
    WIRE.count("bytes_table", len(table))
    WIRE.count("bytes_skeleton", len(skeleton))
    WIRE.count("bytes_raw", state.raw_bytes)
    WIRE.count("bytes_quant", state.quant_bytes)
    WIRE.count("bytes_blob", state.blob_bytes)
    WIRE.count("bytes_pad", pos - len(table) - len(skeleton)
               - sum(len(s) for s in state.segs))
    if state.fallbacks:
        WIRE.count("quant_parity_fallbacks", state.fallbacks)
    if state.noncontig:
        WIRE.count("noncontiguous_copies", state.noncontig)
    return [prefix] + body, pos


def encode_spec_frame_bytes(message: Any, max_bytes: int = 64 << 20) -> bytes:
    """One contiguous spec frame — for tests and the corruption corpus
    (the wire itself never materializes this join)."""
    buffers, _ = encode_spec_frame(message, max_bytes)
    return b"".join(bytes(b) for b in buffers)


# -- decode --------------------------------------------------------------------


def _resolve(obj: Any, ctx: "_DecodeCtx") -> Any:
    t = type(obj)
    if t is dict:
        return {k: _resolve(v, ctx) for k, v in obj.items()}
    if t is list:
        return [_resolve(v, ctx) for v in obj]
    if t is tuple:
        return tuple(_resolve(v, ctx) for v in obj)
    if t is _SegRef:
        return ctx.view(obj)
    if t is _SegBytes:
        off, length = ctx.seg(obj.i)
        return bytes(ctx.body[off:off + length])
    if t is _SegQuant:
        return ctx.quant(obj)
    return obj


class _DecodeCtx:
    __slots__ = ("body", "offsets", "table", "lease", "views")

    def __init__(self, body, offsets, table, lease):
        self.body = body
        self.offsets = offsets
        self.table = table
        self.lease = lease
        self.views = 0

    def seg(self, i) -> Tuple[int, int]:
        if not isinstance(i, int) or not 0 <= i < len(self.table):
            raise CodecError(f"segment index {i!r} out of range")
        return self.offsets[i], self.table[i]

    def view(self, ref: _SegRef) -> np.ndarray:
        off, length = self.seg(ref.i)
        try:
            dtype = np.dtype(ref.dtype)
        except TypeError as err:
            raise CodecError(f"bad segment dtype {ref.dtype!r}") from err
        count = 1
        for dim in ref.shape:
            count *= int(dim)
        if count * dtype.itemsize != length:
            raise CodecError(
                f"segment {ref.i} is {length} bytes but its spec "
                f"{ref.dtype}{tuple(ref.shape)} wants "
                f"{count * dtype.itemsize}"
            )
        arr = np.frombuffer(
            self.body, dtype=dtype, count=count, offset=off
        ).reshape(ref.shape)
        # The view aliases the pooled buffer: retain the lease and let
        # the view's death release it (derived views keep this base
        # array alive through .base, so the finalizer fires exactly
        # when the last consumer lets go).
        if self.lease is not None:
            self.lease.retain()
            weakref.finalize(arr, self.lease.release)
        self.views += 1
        return arr

    def quant(self, ref: _SegQuant) -> np.ndarray:
        qoff, qlen = self.seg(ref.qi)
        soff, slen = self.seg(ref.si)
        qdtype = _quant_dtype(ref.mode)
        block = int(ref.block)
        if block <= 0:
            raise CodecError(f"bad quant block {ref.block!r}")
        n = 1
        for dim in ref.shape:
            n *= int(dim)
        nblocks = -(-n // block)
        if slen != nblocks * 4 or qlen != nblocks * block * qdtype.itemsize:
            raise CodecError(
                f"quant segments ({qlen}, {slen}) bytes do not match "
                f"spec {ref.dtype}{tuple(ref.shape)} "
                f"mode={ref.mode} block={block}"
            )
        q = np.frombuffer(
            self.body, dtype=qdtype, count=nblocks * block, offset=qoff
        ).reshape(nblocks, block)
        scales = np.frombuffer(
            self.body, dtype=np.float32, count=nblocks, offset=soff
        )
        # Dequantization materializes a fresh array — no lease ref.
        return quant_decode_array(q, scales, tuple(ref.shape), ref.dtype)


def decode_spec_body(
    body, nsegs: int, skeleton_len: int, lease: Optional[_Lease]
) -> Any:
    """Decode one CRC-clean spec body (a memoryview over the pooled
    buffer). Raises CodecError on any structural violation; on success
    the returned message's array views co-own `lease`."""
    t0 = time.perf_counter()
    table_len = 4 * nsegs
    if table_len + skeleton_len > len(body):
        raise CodecError(
            f"segment table ({table_len}) + skeleton ({skeleton_len}) "
            f"overrun the {len(body)}-byte body"
        )
    table = struct.unpack_from(f"<{nsegs}I", body, 0)
    offsets: List[int] = []
    pos = table_len + skeleton_len
    for length in table:
        pos = _align_up(pos)
        offsets.append(pos)
        pos += length
    if pos != len(body):
        raise CodecError(
            f"segment table sums to {pos} bytes, body is {len(body)}"
        )
    try:
        skeleton = pickle.loads(body[table_len:table_len + skeleton_len])
    except Exception as err:
        raise CodecError(f"skeleton failed to decode: {err}") from err
    ctx = _DecodeCtx(body, offsets, table, lease)
    message = _resolve(skeleton, ctx)
    WIRE.time("deserialize_ms", time.perf_counter() - t0)
    WIRE.count("frames_spec_rx")
    if lease is not None:
        # Drop the decoder's own reference. A frame with no array
        # views returns to the pool right here; otherwise the last
        # surviving view's finalizer returns it.
        lease.release()
    return message
