"""Shared CRC-framed wire: one frame contract for every t2r fabric.

PR 9 built this machinery inside `replay/transport.py` for the replay
fabric; the serving fabric (serving/pool.py, serving/fabric.py) now
speaks the SAME wire. Factoring the frame codec, the published-address
discovery, the accept-loop server, and the client channel into this
module is what keeps the two fabrics from drifting: there is exactly
one encoder, one decoder, one address file format, and one set of
chaos hooks — a fuzz finding against one fabric's wire is a finding
against both, and a fix lands in both by construction.
`replay/transport.py` re-exports everything here unchanged, so the
replay fabric's imports, tests, and bytes are untouched.

Frame format (little-endian), one frame per message:

    u32 magic        (0x54325254, "T2RT" — rejects cross-protocol junk)
    u32 payload_length
    u32 crc32(payload)
    payload          (pickled message tuple)

Decode discipline — the fuzz suite's contract: a frame is either
decoded WHOLE (magic ok, length sane, CRC verifies, unpickles) or the
connection is torn down with `BadFrame`. There is no partial decode,
no resync-and-continue: after garbage, the stream position is
untrustworthy, so the stream dies and the client's retry opens a fresh
one. Forged lengths are bounded by `MAX_FRAME_BYTES` *before* any
allocation.

Address discovery: a service binds an ephemeral localhost port and
publishes `{host, port, pid, incarnation}` to `<root>/transport.json`
(atomic tmp+replace). Clients resolve the file per (re)connect — a
respawned service incarnation publishes its fresh port and clients
find it on their next retry, with no supervisor in the data path (the
property that lets shards and serving replicas live on other hosts:
the file becomes a name service, the frames don't change).

Chaos sites (`testing/chaos.py`): `net_send` fires before every frame
write, `net_recv` after every frame read, with the remote end's scope
as `peer` — `drop` discards the frame (the peer sees a timeout),
`slow:<ms>` injects link latency, `corrupt` flips a payload byte so
the receiver's CRC rejects it, and `partition:<peers>` drops every
frame to the named peers from that occurrence on. Replay shards use
`s<k>` peer names; serving fabric replicas use `z<zone>.r<i>`.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import socket
import struct
import threading

from tensor2robot_tpu.testing import locksmith
import time
import zlib
from typing import Any, Callable, List, Optional, Tuple

from tensor2robot_tpu.net import codec
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "ADDRESS_FILENAME",
    "BadFrame",
    "ConnectionClosed",
    "FrameServer",
    "MAX_FRAME_BYTES",
    "PipelinedChannel",
    "SocketChannel",
    "TransportError",
    "encode_frame",
    "publish_address",
    "read_address",
    "read_address_info",
    "read_frame",
    "wire_snapshot",
    "write_frame",
]

MAGIC = 0x54325254  # "T2RT"
FRAME_HEADER = struct.Struct("<III")  # magic, payload_length, crc32
# Forged-length bound: reject before allocating. Replay batches and
# serving observations are a few MB at most; 64 MB is an order of
# magnitude of headroom.
MAX_FRAME_BYTES = 64 << 20
ADDRESS_FILENAME = "transport.json"


class TransportError(RuntimeError):
    """Retryable wire failure (timeout, refused, reset, torn frame)."""


class ConnectionClosed(TransportError):
    """The peer closed the stream at a frame boundary."""


class BadFrame(TransportError):
    """Frame integrity violation: bad magic, forged length, CRC
    mismatch, or an undecodable payload. The stream position is
    untrustworthy after this — the connection MUST be torn down."""


def encode_frame(message: Any) -> bytes:
    blob = pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL)
    if len(blob) > MAX_FRAME_BYTES:
        raise TransportError(
            f"message of {len(blob)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte frame bound"
        )
    return FRAME_HEADER.pack(
        MAGIC, len(blob), zlib.crc32(blob) & 0xFFFFFFFF
    ) + blob


def _recv_exact(sock: socket.socket, count: int, deadline: Optional[float],
                mid_frame: bool) -> bytes:
    """Reads exactly `count` bytes or raises: ConnectionClosed on EOF at
    a frame boundary, BadFrame on EOF mid-frame (a truncated frame is a
    torn frame, not a clean goodbye), TransportError on timeout."""
    chunks: List[bytes] = []
    got = 0
    while got < count:
        try:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportError(
                        f"transport read timed out with {count - got} "
                        "bytes outstanding"
                    )
                sock.settimeout(remaining)
            else:
                sock.settimeout(None)
            chunk = sock.recv(count - got)
        except socket.timeout as err:
            raise TransportError("transport read timed out") from err
        except OSError as err:
            # Includes EBADF when the owner closed the socket under a
            # reader mid-teardown: a transport failure like any other.
            raise TransportError(f"transport read failed: {err}") from err
        if not chunk:
            if got or mid_frame:
                raise BadFrame(
                    f"stream closed mid-frame ({got} of {count} bytes)"
                )
            raise ConnectionClosed("stream closed at a frame boundary")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _recv_into_exact(
    sock: socket.socket,
    view: memoryview,
    deadline: Optional[float],
    checksum=zlib.crc32,
    seed: int = 0,
) -> int:
    """Fills `view` from the stream with `recv_into` (no intermediate
    chunk objects) and returns the incremental checksum of the bytes —
    computed DURING the read, so a corrupt 64MB frame costs one pass,
    not an allocate-copy-then-checksum second one. Always mid-frame:
    EOF here is a torn frame. `checksum`/`seed` select the codec's
    check (crc32 from 0 for pickle frames, adler32 from 1 for spec
    bodies)."""
    got = 0
    count = len(view)
    crc = seed
    t0 = time.perf_counter()
    while got < count:
        try:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportError(
                        f"transport read timed out with {count - got} "
                        "bytes outstanding"
                    )
                sock.settimeout(remaining)
            else:
                sock.settimeout(None)
            n = sock.recv_into(view[got:])
        except socket.timeout as err:
            raise TransportError("transport read timed out") from err
        except OSError as err:
            raise TransportError(f"transport read failed: {err}") from err
        if n == 0:
            raise BadFrame(
                f"stream closed mid-frame ({got} of {count} bytes)"
            )
        crc = checksum(view[got:got + n], crc)
        got += n
    codec.WIRE.time("recv_ms", time.perf_counter() - t0)
    return crc & 0xFFFFFFFF


def read_frame(sock: socket.socket, deadline: Optional[float] = None) -> Any:
    """One whole message off the stream, or a typed failure — never a
    partially-decoded object (see module docstring).

    The codec is auto-detected per frame from the magic (the SENDER's
    `T2R_WIRE` picks it), so mixed-codec peers interoperate on one
    stream. Both codecs receive into a pooled buffer with the CRC
    verified incrementally during `recv_into`; the spec codec's array
    views then alias that buffer (returned to the pool when the last
    view dies), the pickle codec releases it as soon as
    `pickle.loads` has copied the objects out."""
    first = _recv_exact(sock, 4, deadline, mid_frame=False)
    (magic,) = struct.unpack("<I", first)
    if magic == MAGIC:
        rest = _recv_exact(
            sock, FRAME_HEADER.size - 4, deadline, mid_frame=True
        )
        length, crc = struct.unpack("<II", rest)
        if length > MAX_FRAME_BYTES:
            raise BadFrame(
                f"forged frame length {length} (bound {MAX_FRAME_BYTES})"
            )
        lease = codec.POOL.acquire(length)
        try:
            view = memoryview(lease.buf)[:length]
            if _recv_into_exact(sock, view, deadline) != crc:
                raise BadFrame(
                    f"frame of {length} bytes failed its CRC32 check"
                )
            t0 = time.perf_counter()
            try:
                message = pickle.loads(view)
            except Exception as err:
                # Checksummed but undecodable: same wire failure.
                raise BadFrame(
                    f"frame payload failed to decode: {err}"
                ) from err
            codec.WIRE.time("deserialize_ms", time.perf_counter() - t0)
            codec.WIRE.count("frames_pickle_rx")
            return message
        finally:
            # pickle.loads copied everything out of the buffer.
            lease.release()
    if magic == codec.SEG_MAGIC:
        rest = _recv_exact(
            sock, codec.SPEC_PREFIX.size - 4, deadline, mid_frame=True
        )
        body_len, adler, crc, nsegs, skeleton_len = struct.unpack(
            "<IIIII", rest
        )
        if body_len > MAX_FRAME_BYTES:
            raise BadFrame(
                f"forged frame length {body_len} "
                f"(bound {MAX_FRAME_BYTES})"
            )
        if nsegs > codec.MAX_SEGMENTS:
            raise BadFrame(
                f"forged segment count {nsegs} "
                f"(bound {codec.MAX_SEGMENTS})"
            )
        structural = 4 * nsegs + skeleton_len
        if structural > body_len:
            raise BadFrame(
                f"forged spec header: table ({4 * nsegs}) + skeleton "
                f"({skeleton_len}) overrun the {body_len}-byte body"
            )
        lease = codec.POOL.acquire(body_len)
        ok = False
        try:
            view = memoryview(lease.buf)[:body_len]
            got = _recv_into_exact(
                sock, view, deadline, checksum=zlib.adler32, seed=1
            )
            if got != adler:
                raise BadFrame(
                    f"spec frame of {body_len} bytes failed its "
                    "adler32 body check"
                )
            if zlib.crc32(view[:structural]) & 0xFFFFFFFF != crc:
                raise BadFrame(
                    "spec frame structural region failed its CRC32 "
                    "check"
                )
            try:
                message = codec.decode_spec_body(
                    view, nsegs, skeleton_len, lease
                )
            except codec.CodecError as err:
                raise BadFrame(f"spec frame refused: {err}") from err
            ok = True  # decode_spec_body now owns the lease
            return message
        finally:
            if not ok:
                lease.release()
    raise BadFrame(f"bad frame magic {magic:#010x}")


# IOV_MAX bound for one sendmsg; Linux allows 1024, stay under it.
_SENDMSG_MAX_BUFFERS = min(getattr(socket, "IOV_MAX", 1024), 1024)


def _sendmsg_all(sock: socket.socket, buffers: List[Any]) -> None:
    """Scatter-gather `sendmsg` with partial-send resume and IOV_MAX
    chunking — the whole frame leaves the process without ever being
    concatenated in user space."""
    views = [memoryview(b).cast("B") for b in buffers if len(b)]
    total = sum(len(v) for v in views)
    idx = 0
    off = 0
    sent_total = 0
    while sent_total < total:
        iov = []
        i, o = idx, off
        while i < len(views) and len(iov) < _SENDMSG_MAX_BUFFERS:
            iov.append(views[i][o:] if o else views[i])
            o = 0
            i += 1
        try:
            sent = sock.sendmsg(iov)
        except OSError as err:
            raise TransportError(
                f"transport write failed: {err}"
            ) from err
        sent_total += sent
        while sent:
            remaining = len(views[idx]) - off
            if sent >= remaining:
                sent -= remaining
                idx += 1
                off = 0
            else:
                off += sent
                sent = 0


def write_frame(
    sock: socket.socket, message: Any, peer: Optional[str] = None
) -> bool:
    """Sends one frame; returns False when a chaos clause dropped it on
    the floor (the caller proceeds to wait — and time out — exactly as
    it would on a real lost packet). `T2R_WIRE` picks the codec:
    `pickle` (default) is byte-identical to the pre-spec wire, `spec`
    sends the scatter-gather segment frame."""
    if codec.wire_mode() == "spec":
        return _write_frame_spec(sock, message, peer)
    t0 = time.perf_counter()
    frame = encode_frame(message)
    codec.WIRE.time("serialize_ms", time.perf_counter() - t0)
    codec.WIRE.count("frames_pickle_tx")
    codec.WIRE.count("bytes_pickle", len(frame))
    hit = chaos.maybe_fire("net_send", peer=peer)
    if hit is not None:
        if hit.action in ("drop", "partition"):
            return False
        if hit.action == "corrupt":
            # Flip a payload byte AFTER the CRC was computed: the
            # receiver must reject the frame, whole.
            corrupted = bytearray(frame)
            corrupted[FRAME_HEADER.size] ^= 0xFF
            frame = bytes(corrupted)
    t0 = time.perf_counter()
    try:
        sock.sendall(frame)
    except OSError as err:
        raise TransportError(f"transport write failed: {err}") from err
    codec.WIRE.time("send_ms", time.perf_counter() - t0)
    return True


def _write_frame_spec(
    sock: socket.socket, message: Any, peer: Optional[str]
) -> bool:
    """Spec-codec send: same chaos contract as the pickle path — drop
    and partition discard the frame, corrupt flips a body byte after
    the CRC was computed (in a COPY of the small table/skeleton buffer,
    never in the caller's arrays)."""
    try:
        buffers, _body_len = codec.encode_spec_frame(
            message, MAX_FRAME_BYTES
        )
    except codec.CodecError as err:
        raise TransportError(str(err)) from err
    hit = chaos.maybe_fire("net_send", peer=peer)
    if hit is not None:
        if hit.action in ("drop", "partition"):
            return False
        if hit.action == "corrupt":
            for i in range(1, len(buffers)):
                if len(buffers[i]):
                    corrupted = bytearray(buffers[i])
                    corrupted[0] ^= 0xFF
                    buffers[i] = bytes(corrupted)
                    break
    t0 = time.perf_counter()
    _sendmsg_all(sock, buffers)
    codec.WIRE.time("send_ms", time.perf_counter() - t0)
    return True


def wire_snapshot() -> dict:
    """Per-process wire observability: stage timings, per-segment-class
    byte counters, and the receive-pool allocation audit."""
    return codec.wire_snapshot()


# -- address discovery ---------------------------------------------------------


def publish_address(
    root: str, port: int, incarnation: int = 0, host: str = "127.0.0.1"
) -> None:
    """Atomically publishes this incarnation's listen address under the
    service's own directory (tmp+replace, the manifest discipline)."""
    payload = {
        "host": host,
        "port": int(port),
        "pid": os.getpid(),
        "incarnation": int(incarnation),
    }
    path = os.path.join(root, ADDRESS_FILENAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_address_info(root: str) -> Optional[dict]:
    """The full published address payload ({host, port, pid,
    incarnation}), or None when nothing has published yet (bring-up) /
    the file is torn (retry re-reads). Supervisors use `incarnation` to
    tell a FRESH publication from the dead predecessor's stale file."""
    path = os.path.join(root, ADDRESS_FILENAME)
    try:
        with open(path) as f:
            payload = json.load(f)
        return {
            "host": str(payload["host"]),
            "port": int(payload["port"]),
            "pid": int(payload.get("pid", 0)),
            "incarnation": int(payload.get("incarnation", 0)),
        }
    except (OSError, ValueError, KeyError) as err:
        _log.debug("no readable transport address at %s (%s)", path, err)
        return None


def read_address(root: str) -> Optional[Tuple[str, int]]:
    """(host, port) of the latest publication (see read_address_info)."""
    info = read_address_info(root)
    return (info["host"], info["port"]) if info is not None else None


# -- the server side -----------------------------------------------------------


class FrameServer:
    """Accept loop + one thread per connection, request/response framing.

    Two handler shapes, one connection loop:

      * request/reply (default): `handler(request) -> Optional[reply]`
        gets every whole decoded request frame; its reply (None = no
        reply, e.g. lifecycle ops) is framed back on the same
        connection. This is the replay fabric's shape.
      * duplex (`duplex=True`): `handler(request, send)` gets the frame
        plus a thread-safe `send(message)` that frames messages back on
        the same connection at any time, from any thread — the serving
        fabric's shape, where replies complete asynchronously (done
        callbacks, pending swaps) and health traffic interleaves.

    Either way, a BadFrame tears the connection down — the client's
    retry reopens a clean one; the handler never sees bytes the framing
    did not fully validate.
    """

    def __init__(
        self,
        handler: Callable,
        host: str = "127.0.0.1",
        port: int = 0,
        duplex: bool = False,
        idle_tick: Optional[Callable[[], None]] = None,
    ):
        self._handler = handler
        self._duplex = duplex
        self._idle_tick = idle_tick
        self._listener = socket.create_server(
            (host, port), reuse_port=False
        )
        self._listener.settimeout(0.2)
        self.host, self.port = self._listener.getsockname()[:2]
        self._closed = False
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._lock = locksmith.make_lock("FrameServer._lock")
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> "FrameServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: stopping
            thread = threading.Thread(
                target=self._serve_connection, args=(conn,), daemon=True
            )
            with self._lock:
                if self._closed:
                    best_effort(conn.close)
                    return
                self._conns.append(conn)
                # Prune finished handlers here, not in a finalizer:
                # clients reconnect on every retry, so a chaos-heavy
                # multi-day service would otherwise accumulate one dead
                # Thread object per reconnect, unboundedly.
                self._threads = [
                    t for t in self._threads if t.is_alive()
                ]
                self._threads.append(thread)
            thread.start()

    def _serve_connection(self, conn: socket.socket) -> None:
        import select

        send_lock = locksmith.make_lock("FrameServer._send_lock")

        def send(message: Any) -> bool:
            """Duplex-mode outbound: frames may be written from any
            thread (done callbacks, swap ticks), so writes serialize on
            a per-connection lock — interleaved sendall calls would
            shear two frames into garbage the peer must tear down."""
            with send_lock:
                return write_frame(conn, message)

        try:
            while not self._closed:
                # Poll for readability BEFORE starting a frame read: a
                # bounded read_frame alone could time out with the
                # header consumed and the payload in flight, and
                # resuming the loop would then decode mid-frame bytes
                # as a header — stream desync. The poll carries the
                # stop-responsiveness; the frame read, once begun, gets
                # a real deadline and any timeout inside it is fatal to
                # the connection (whole-frame-or-nothing).
                try:
                    readable, _, _ = select.select([conn], [], [], 0.2)
                except (OSError, ValueError):
                    return  # connection torn down under us
                if not readable:
                    if self._idle_tick is not None:
                        try:
                            self._idle_tick()
                        except Exception:
                            _log.exception("idle tick failed")
                    continue
                try:
                    request = read_frame(
                        conn, deadline=time.monotonic() + 10.0
                    )
                except TransportError as err:
                    if isinstance(err, ConnectionClosed):
                        return
                    # BadFrame, mid-frame timeout, reset: the stream
                    # position is untrustworthy — kill the connection,
                    # the client retries on a fresh one.
                    if isinstance(err, BadFrame):
                        _log.warning("torn request frame (%s); "
                                     "closing connection", err)
                    return
                # The receiver does not know who is calling, so the
                # peer it reports is its OWN scope: a receive-side
                # partition plan (`net_recv:1:partition:s1`) cuts
                # everything shard s1 hears, the mirror of the sender
                # side cutting everything said TO s1.
                hit = chaos.maybe_fire("net_recv", peer=chaos.get_scope())
                if hit is not None and hit.action in ("drop", "partition"):
                    continue  # request vanishes; the client times out
                if hit is not None and hit.action == "corrupt":
                    _log.warning("chaos corrupt at net_recv; "
                                 "closing connection")
                    return
                try:
                    if self._duplex:
                        self._handler(request, send)
                        continue
                    reply = self._handler(request)
                except Exception:
                    # The service handler has its own error protocol; a
                    # raise through it is a server bug — log it loudly
                    # and drop the connection rather than hang the peer.
                    _log.exception("transport handler raised; "
                                   "closing connection")
                    return
                if reply is None:
                    continue
                try:
                    write_frame(conn, reply)
                except TransportError as err:
                    _log.warning("reply write failed (%s); "
                                 "closing connection", err)
                    return
        finally:
            best_effort(conn.close)
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def stop(self) -> None:
        self._closed = True
        best_effort(self._listener.close)
        with self._lock:
            conns = list(self._conns)
            threads = list(self._threads)
        for conn in conns:
            best_effort(conn.shutdown, socket.SHUT_RDWR)
            best_effort(conn.close)
        if self._accept_thread is not None:
            self._accept_thread.join(2.0)
        for thread in threads:
            thread.join(2.0)


# -- the client side -----------------------------------------------------------


class SocketChannel:
    """One client's connection to a service root (lazy, self-healing).

    `call(request, req_id, timeout_s)` sends one frame and reads frames
    until the reply whose first element equals `req_id` arrives (stale
    replies from a timed-out earlier attempt on the same connection are
    dropped, same discipline as the queue client). ANY failure —
    resolve, connect, send, torn frame, timeout — closes the connection
    (so stale state dies with it) and raises a retryable
    TransportError; the caller owns retry/backoff policy.

    `peer` is the remote end's chaos scope (shard `s<k>`, serving
    replica `z<zone>.r<i>`), threaded to the `net_send` site so
    `partition:<peers>` plans can cut this specific link.

    `min_incarnation` refuses addresses published by an incarnation
    older than the given one: after a supervisor respawns the service,
    the dead predecessor's stale `transport.json` must read as
    "not up yet" (retry), never as a connectable address — the
    incarnation stamp is what makes respawn re-resolution exact.
    """

    def __init__(
        self,
        root: str,
        peer: Optional[str] = None,
        connect_timeout_s: float = 2.0,
        min_incarnation: int = 0,
    ):
        self.root = root
        self.peer = peer
        self._connect_timeout_s = connect_timeout_s
        self.min_incarnation = int(min_incarnation)
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        info = read_address_info(self.root)
        if info is None:
            raise TransportError(
                f"no transport address published under {self.root} "
                "(service not up yet, or respawning)"
            )
        if info["incarnation"] < self.min_incarnation:
            raise TransportError(
                f"stale transport address under {self.root}: published by "
                f"incarnation {info['incarnation']}, expecting >= "
                f"{self.min_incarnation} (predecessor's file; respawn "
                "has not published yet)"
            )
        address = (info["host"], info["port"])
        try:
            sock = socket.create_connection(
                address, timeout=self._connect_timeout_s
            )
        except OSError as err:
            raise TransportError(
                f"connect to {address} failed: {err}"
            ) from err
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        return sock

    def call(self, request: Any, req_id: Any, timeout_s: float) -> Any:
        deadline = time.monotonic() + timeout_s
        try:
            sock = self._connect()
            write_frame(sock, request, peer=self.peer)
            while True:
                reply = read_frame(sock, deadline=deadline)
                if (
                    isinstance(reply, tuple)
                    and reply
                    and reply[0] == req_id
                ):
                    return reply
                # Stale reply from an attempt this client already gave
                # up on: drop and keep reading within the deadline.
        except TransportError:
            self.close()
            raise

    def send_only(self, request: Any) -> None:
        """Fire-and-forget (lifecycle ops like stop): best effort by
        contract, but failures still raise so callers can log them."""
        sock = self._connect()
        write_frame(sock, request, peer=self.peer)

    def close(self) -> None:
        if self._sock is not None:
            best_effort(self._sock.close)
            self._sock = None


class _Pending:
    """One in-flight request on a PipelinedChannel."""

    __slots__ = ("req_id", "event", "reply", "error")

    def __init__(self, req_id: Any):
        self.req_id = req_id
        self.event = threading.Event()
        self.reply: Any = None
        self.error: Optional[TransportError] = None


class PipelinedChannel:
    """Multiple in-flight requests multiplexed on ONE connection.

    `SocketChannel.call` is lockstep — send, then read until the reply
    arrives — so N sequential fetches pay N round trips even when the
    server could overlap them. This channel keeps a reader thread and
    a pending map keyed by request id: `submit` frames the request and
    returns immediately; `result` blocks on that request alone; frames
    arriving out of order complete whichever request they answer.
    Replies are correlated by the server contract SocketChannel already
    relies on (reply[0] == req_id), so any FrameServer handler that
    echoes req_ids is pipelinable unchanged.

    Failure semantics stay whole-connection, like SocketChannel: any
    transport error fails EVERY in-flight request (the stream is
    untrustworthy past the tear) and closes the socket; the next
    submit reconnects via the published address."""

    def __init__(
        self,
        root: str,
        peer: Optional[str] = None,
        connect_timeout_s: float = 2.0,
        min_incarnation: int = 0,
    ):
        self._channel = SocketChannel(
            root,
            peer=peer,
            connect_timeout_s=connect_timeout_s,
            min_incarnation=min_incarnation,
        )
        self._lock = locksmith.make_lock("PipelinedChannel._lock")
        self._pending: dict = {}
        self._reader: Optional[threading.Thread] = None
        self._closed = False

    def _ensure_reader(self, sock: socket.socket) -> None:
        if self._reader is None or not self._reader.is_alive():
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock,), daemon=True,
                name="t2r-pipelined-reader",
            )
            self._reader.start()

    def _read_loop(self, sock: socket.socket) -> None:
        while True:
            try:
                reply = read_frame(sock)
            except TransportError as err:
                self._fail_all(err)
                return
            if not (isinstance(reply, tuple) and reply):
                continue
            with self._lock:
                pending = self._pending.pop(reply[0], None)
            if pending is None:
                continue  # stale reply for an abandoned request
            pending.reply = reply
            pending.event.set()

    def _fail_all(self, err: Exception) -> None:
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        failure = err if isinstance(err, TransportError) else (
            TransportError(str(err))
        )
        for entry in pending:
            entry.error = failure
            entry.event.set()
        self._channel.close()

    def submit(self, request: Any, req_id: Any) -> _Pending:
        pending = _Pending(req_id)
        with self._lock:
            if self._closed:
                raise TransportError("pipelined channel closed")
            if req_id in self._pending:
                raise TransportError(
                    f"request id {req_id!r} already in flight"
                )
            self._pending[req_id] = pending
            try:
                sock = self._channel._connect()
                self._ensure_reader(sock)
                write_frame(sock, request, peer=self._channel.peer)
            except TransportError:
                self._pending.pop(req_id, None)
                self._channel.close()
                raise
        return pending

    def result(self, pending: _Pending, timeout_s: float) -> Any:
        if not pending.event.wait(timeout_s):
            with self._lock:
                self._pending.pop(pending.req_id, None)
            raise TransportError(
                f"pipelined request {pending.req_id!r} timed out "
                f"after {timeout_s}s"
            )
        if pending.error is not None:
            raise pending.error
        return pending.reply

    def call(self, request: Any, req_id: Any, timeout_s: float) -> Any:
        return self.result(self.submit(request, req_id), timeout_s)

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._fail_all(TransportError("pipelined channel closed"))
