"""Jit-native cross-entropy method: the whole CEM loop as ONE XLA program.

The reference's CEM (utils/cross_entropy.py:31-155, rebuilt in
utils/cross_entropy.py here) runs numpy on the robot host, crossing the
host<->accelerator boundary once per iteration for the batched critic
call. Because this framework's exported artifacts rehydrate as jax
callables (export/saved_model.py ExportedModel), the objective can be
TRACED — sampling, scoring, elite refit, and the iteration loop fuse into
one jitted program with a single dispatch per action selection
(policies.JitCEMPolicy). Same proposal family and elite-refit math as the
numpy engine; keep them in sync. (One deliberate difference: the numpy
engine's early_termination_stddev has no analogue here — a fixed
iteration count keeps the program static, and at one dispatch per
selection there is no per-iteration round-trip to save.)
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def cross_entropy_maximize(
    objective_fn: Callable[[jax.Array], jax.Array],
    mean: jax.Array,
    stddev: jax.Array,
    rng: jax.Array,
    *,
    num_samples: int,
    num_iterations: int,
    elite_fraction: float = 0.1,
    low: Optional[float] = None,
    high: Optional[float] = None,
    min_stddev: float = 1e-6,
    smoothing: float = 0.3,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Maximizes objective_fn over a diagonal-Gaussian proposal.

    Args:
      objective_fn: [num_samples, *action] -> [num_samples] scores; traced
        (may contain an exported-model call).
      mean/stddev: initial proposal, shape [*action].
      rng: PRNG key.
      num_samples: population per iteration (static).
      num_iterations: refit rounds (static; the loop is lax.fori_loop).
      elite_fraction: top fraction refit each round (>= 1 elite).
      low/high: optional box bounds; samples clip BEFORE scoring so elites
        refit on the actions actually scored (the numpy engine's rule).
      min_stddev: floor keeping later iterations samplable.
      smoothing: exponential smoothing of the refit (new = (1-a)*elite +
        a*old). At QT-Opt population sizes the elite set is a handful of
        samples, so the moment-matched stddev is a high-variance UNDER-
        estimate (std over ~3 points); unsmoothed, the proposal can
        collapse around an early suboptimal mean before any sample lands
        near the optimum. Smoothed refit (Kobilarov 2012's fix) keeps
        exploration alive: at 32 samples/3 elites/8 iterations it cuts
        the miss rate (best-ever > 0.12 off the optimum) from ~25% of
        seeds to <1%. Keep in sync with utils/cross_entropy.py.

    Returns (mean, stddev, best_action, best_score) — best over ALL
    iterations' populations, not just the final mean.
    """
    num_elites = max(1, int(num_samples * elite_fraction))

    def body(index, carry):
        mean, stddev, best_action, best_score, rng = carry
        rng, key = jax.random.split(rng)
        samples = mean[None, ...] + stddev[None, ...] * jax.random.normal(
            key, (num_samples,) + mean.shape, mean.dtype
        )
        if low is not None or high is not None:
            samples = jnp.clip(samples, low, high)
        scores = objective_fn(samples)
        top_scores, top_idx = lax.top_k(scores, num_elites)
        elites = samples[top_idx]
        new_mean = (1.0 - smoothing) * jnp.mean(elites, axis=0) + (
            smoothing * mean
        )
        new_stddev = jnp.maximum(
            (1.0 - smoothing) * jnp.std(elites, axis=0) + smoothing * stddev,
            min_stddev,
        )
        improved = top_scores[0] > best_score
        best_action = jnp.where(improved, elites[0], best_action)
        best_score = jnp.where(improved, top_scores[0], best_score)
        return new_mean, new_stddev, best_action, best_score, rng

    init = (
        mean,
        stddev,
        # Parity with the numpy engine: if no iteration ever improves
        # (e.g. all-NaN scores from a broken critic), return the initial
        # proposal mean, not zeros (which may sit outside the action box).
        mean,
        jnp.asarray(-jnp.inf, mean.dtype),
        rng,
    )
    mean, stddev, best_action, best_score, _ = lax.fori_loop(
        0, num_iterations, body, init
    )
    return mean, stddev, best_action, best_score
