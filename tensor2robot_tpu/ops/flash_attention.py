"""Pallas TPU flash attention: the per-device attention hot op.

The online-softmax (flash) recurrence computed in a single Pallas kernel:
Q stays resident in VMEM per grid step while K/V are consumed block by
block with running (output, row-sum, row-max) accumulators — the S×S logit
matrix never exists in HBM, so HBM traffic is O(S·D) instead of O(S²)
(the usual bandwidth bound for attention on TPU). Used standalone and as
the per-hop tile kernel of parallel/ring_attention.py, which adds the
sequence-parallel ring on top.

Positions are GLOBAL: q_offset/k_offset shift the causal mask so a kernel
invocation can compute one (q-shard × k-shard) tile of a longer sequence
(exactly what each ring hop needs).

Dispatch: the Pallas path runs on TPU (or anywhere with interpret=True,
which tests use); other backends and non-divisible block shapes fall back
to the einsum reference. Gradients: jax.custom_vjp with a FLASH backward —
two Pallas kernels (dq; dk+dv) recompute attention probabilities tile by
tile from the forward's saved row statistics L = m + log(l) and
D = rowsum(dO*O), so the backward is also O(S·D) HBM (the
FlashAttention-2 scheme); the S×S logit matrix never materializes in
either direction.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30

# -- contraction override (low-precision serving hook) -------------------------
# The einsum path's two contractions (QK^T logits, PV mix) are the only
# attention FLOPs a serving export can re-lower onto int8/fp8 operands
# (export/serve_quant.py attention lowering). Rather than have the
# serving layer re-implement attention (masking, windows, offsets), the
# reference path exposes exactly those two ops as an override point:
# inside `attention_contraction_override(impl)`, logits come from
# `impl.qk(q, k, scale)` and the mixed output from `impl.pv(probs, v)`;
# everything else (mask construction, softmax, dtypes) is unchanged.
# The flash/ring/ulysses kernels never consult the hook — their tiled
# recurrences have no materialized contraction to swap — which is why
# attention-head eligibility is einsum-path-only.
_CONTRACTION_OVERRIDE: contextvars.ContextVar = contextvars.ContextVar(
    "t2r_attention_contraction_override", default=None
)


@contextlib.contextmanager
def attention_contraction_override(impl):
    """Installs `impl` (with .qk(q, k, scale) and .pv(probs, v)) as the
    reference path's contraction implementation for the context."""
    token = _CONTRACTION_OVERRIDE.set(impl)
    try:
        yield
    finally:
        _CONTRACTION_OVERRIDE.reset(token)

try:  # jax with varying-manual-axes tracking accepts vma annotations
    jax.ShapeDtypeStruct((1,), jnp.float32, vma=frozenset())
    _SDS_HAS_VMA = True
except TypeError:  # older jax: no tracking, the annotation is a no-op
    _SDS_HAS_VMA = False

# Row statistics (l, m, lse, delta) cross the pallas_call boundary stored
# with a trailing broadcast dim of _STATS_LANES so their blocks satisfy
# Mosaic's (8, 128) tile constraint; a [block_q]-shaped block would need a
# sublane dim divisible by 8, which a per-row vector cannot provide. This
# mirrors the upstream jax.experimental.pallas TPU flash kernel's own l/m
# layout. It costs 128x HBM on the stat tensors (still O(S) vs the O(S^2)
# logits the kernel avoids); a [bh, 1, s_q] stats-in-lanes layout would be
# 128x slimmer but constrains partial q-blocks to multiples of 128 and
# needs an in-kernel sublane->lane transpose — worth exploring only after
# this layout is validated on hardware.
_STATS_LANES = 128


# Auto-dispatch crossover shared by every attention entry point
# (layers/transformer.py single-device, parallel/ring_attention.py per-hop
# local length, parallel/ulysses_attention.py full length): below this
# per-device attended length the XLA einsum path wins on measured speed
# (BENCH_FLASH_r03); at/above it the einsum path's O(S^2) logits OOM
# where the flash kernel's O(S) tiles still fit (the r4 A/B's expected
# einsum OOM at S=4096). Re-evaluated by each BENCH_FLASH capture.
FLASH_AUTO_SEQ = 4096


def _check_window(window: Optional[int], causal: bool) -> None:
    """Shared entry-point validation: a window needs causal semantics, and
    window < 1 would mask EVERYTHING — in the reference path the finite
    _NEG_INF cap then normalizes to uniform attention over all positions
    (a silent future-information leak), so it must be rejected, not
    computed."""
    if window is None:
        return
    if not causal:
        raise ValueError("window requires causal=True (causal sliding window)")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def _k_block_bounds(q0, block_q, block_k, num_kb, k_off, causal, window):
    """[j_lo, j_hi) over k blocks visible to the q block starting at GLOBAL
    position q0. A k block j covers global [k_off + j*bk, k_off + (j+1)*bk).
    Causal keeps blocks whose min k <= the block's max q; the window keeps
    blocks whose max k > q0 - W — both exact (floor division on possibly
    negative numerators). Shared by the forward recurrence and the dq
    backward so their visibility can never desynchronize."""
    j_lo = 0
    j_hi = num_kb
    if causal:
        j_hi = jnp.maximum(
            0,
            jnp.minimum(num_kb, (q0 + block_q - 1 - k_off) // block_k + 1),
        )
    if window is not None:
        j_lo = jnp.maximum(0, (q0 - window + 1 - k_off) // block_k)
    return j_lo, j_hi


def _dot_precision(dtype) -> Optional[lax.Precision]:
    """Matmul precision for kernel dots computing in f32 from `dtype` inputs.

    The TPU MXU natively multiplies bf16; at DEFAULT precision an f32
    matmul is decomposed into a single bf16 pass (~2^-8 relative error).
    For f32 inputs that silently downgrades the kernel below f32 accuracy,
    so request HIGHEST (the multi-pass bf16 decomposition, true-f32
    accurate). For bf16 inputs the operands are exactly representable and
    DEFAULT is both exact-enough and the fast path.
    """
    return lax.Precision.HIGHEST if dtype == jnp.float32 else None


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    precision: Optional[lax.Precision] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Materialized-logits attention over [B, S, H, D] — numerics oracle
    and non-TPU fallback. Offsets shift global positions for tiled use.
    window=W restricts each query to the last W keys (q-W < k <= q, the
    causal sliding window); requires causal=True."""
    _check_window(window, causal)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    override = _CONTRACTION_OVERRIDE.get()
    if override is not None:
        logits = override.qk(q, k, scale)
    else:
        logits = (
            jnp.einsum("bqhd,bkhd->bhqk", q, k, precision=precision) * scale
        )
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    # Fully-masked rows normalize against the -inf cap instead of NaN-ing.
    probs = jax.nn.softmax(logits, axis=-1)
    if override is not None:
        return override.pv(probs, v).astype(q.dtype)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v, precision=precision
    ).astype(q.dtype)


def _flash_body(
    offsets_ref, q_ref, k_ref, v_ref, block_k, scale, causal, precision,
    window=None,
):
    """The shared online-softmax recurrence over k blocks; returns the raw
    accumulator triple (o_unnormalized, row_sum, row_max).

    window=W (causal sliding window, q-W < k <= q) masks per element AND
    tightens the k-block loop bounds, so compute is O(S*W) instead of
    O(S^2) — the whole point of local attention at long context.
    """
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    dim = q_ref.shape[2]
    s_k = k_ref.shape[1]
    num_kb = s_k // block_k

    q = q_ref[0].astype(jnp.float32) * scale
    q_pos = (
        offsets_ref[0]
        + qi * block_q
        + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    )

    q0 = offsets_ref[0] + qi * block_q
    j_lo, j_hi = _k_block_bounds(
        q0, block_q, block_k, num_kb, offsets_ref[1], causal, window
    )

    def body(j, carry):
        o_acc, l_acc, m_acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q,
            k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )  # [block_q, block_k]
        if causal:
            k_pos = (
                offsets_ref[1]
                + j * block_k
                + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            )
            visible = q_pos >= k_pos
            if window is not None:
                visible = visible & (q_pos - k_pos < window)
            s = jnp.where(visible, s, _NEG_INF)
        # Row stats stay [block_q, 1] (keepdims) — 2D shapes lower cleanly
        # on Mosaic where 1D per-row vectors may not.
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_acc, m_blk)
        alpha = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new)
        # Fully-masked tiles contribute nothing (not exp(0)=1 garbage).
        p = jnp.where(m_new == _NEG_INF, 0.0, p)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o_acc * alpha + jax.lax.dot_general(
            p,
            v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        return o_new, l_new, m_new

    o_acc = jnp.zeros((block_q, dim), jnp.float32)
    l_acc = jnp.zeros((block_q, 1), jnp.float32)
    m_acc = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    return lax.fori_loop(j_lo, j_hi, body, (o_acc, l_acc, m_acc))


def _flash_kernel(
    offsets_ref,  # SMEM [2] int32: (q_offset, k_offset) global shifts
    q_ref,  # VMEM [1, block_q, D]
    k_ref,  # VMEM [1, S_k, D]
    v_ref,  # VMEM [1, S_k, D]
    o_ref,  # VMEM [1, block_q, D]
    *,
    block_k: int,
    scale: float,
    causal: bool,
    precision: Optional[lax.Precision] = None,
    window: Optional[int] = None,
):
    o_acc, l_acc, _ = _flash_body(
        offsets_ref, q_ref, k_ref, v_ref, block_k, scale, causal, precision,
        window,
    )
    l_acc = jnp.maximum(l_acc, 1e-30)
    o_ref[0] = (o_acc / l_acc).astype(o_ref.dtype)


def _flash_tile_kernel(
    offsets_ref, q_ref, k_ref, v_ref, o_ref, l_ref, m_ref,
    *, block_k, scale, causal, precision=None, window=None,
):
    """Like _flash_kernel but emits the UNNORMALIZED accumulator triple
    (o_partial, row_sum, row_max) — the online-softmax residuals a ring hop
    merges across devices (parallel/ring_attention.py). l/m blocks are
    [1, block_q, _STATS_LANES] with the stat broadcast along the lane dim."""
    o_acc, l_acc, m_acc = _flash_body(
        offsets_ref, q_ref, k_ref, v_ref, block_k, scale, causal, precision,
        window,
    )
    o_ref[0] = o_acc
    l_ref[0] = jnp.broadcast_to(l_acc, l_ref.shape[1:])
    m_ref[0] = jnp.broadcast_to(m_acc, m_ref.shape[1:])


def flash_attention_tile(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    vma=None,
    window: Optional[int] = None,
):
    """One (q-shard × k-shard) flash tile over [B, S, H, D].

    Returns (o_partial [B,Sq,H,D] f32 unnormalized, l [B,H,Sq], m [B,H,Sq])
    — the same contract as ring_attention's reference _block_attend, so a
    ring hop can merge tiles across devices without renormalizing twice.

    vma: mesh axis names the outputs vary over — required when called
    inside shard_map (the ring passes its sequence axis).
    window: causal sliding window W (q-W < k <= q) in GLOBAL positions.
    """
    _check_window(window, causal)
    if not interpret and jax.default_backend() != "tpu":
        raise ValueError(
            "flash_attention_tile compiles only on TPU; pass interpret=True "
            "to run the kernel in interpreter mode on this backend."
        )
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    batch, s_q, heads, dim = q.shape
    s_k = k.shape[1]
    bh = batch * heads
    scale = scale if scale is not None else dim ** -0.5
    bq = _pick_block(s_q, block_q)
    bk = _pick_block(s_k, block_k)
    if bq is None or bk is None:
        raise ValueError(
            f"No MXU-viable block divides shard lengths (q={s_q}, k={s_k}); "
            "use the reference path (ring_attention use_flash=False) for "
            "these shapes."
        )
    offsets = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )

    def out_struct(shape):
        if vma is not None and _SDS_HAS_VMA:
            return jax.ShapeDtypeStruct(shape, jnp.float32, vma=frozenset(vma))
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, x.shape[1], dim)

    o, l, m = pl.pallas_call(
        functools.partial(
            _flash_tile_kernel, block_k=bk, scale=scale, causal=causal,
            precision=_dot_precision(q.dtype), window=window,
        ),
        out_shape=(
            out_struct((bh, s_q, dim)),
            out_struct((bh, s_q, _STATS_LANES)),
            out_struct((bh, s_q, _STATS_LANES)),
        ),
        grid=(bh, s_q // bq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _STATS_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _STATS_LANES), lambda b, i: (b, i, 0)),
        ),
        interpret=interpret,
    )(offsets, fold(q), fold(k), fold(v))
    o = jnp.transpose(o.reshape(batch, heads, s_q, dim), (0, 2, 1, 3))
    l = l[..., 0].reshape(batch, heads, s_q)
    m = m[..., 0].reshape(batch, heads, s_q)
    return o, l, m


def _pick_block(size: int, preferred: int) -> Optional[int]:
    """Usable kernel block size for a sequence dim: the whole dim when it
    fits one block, else the largest divisor <= preferred that is still
    MXU/VPU-viable. A partial block must be a multiple of 8 (Mosaic's
    sublane tile — checked at lowering on real TPU, not by the CPU
    interpreter); the full dim is always legal regardless of size. None ->
    no viable blocking (prime-ish lengths); callers fall back to the
    einsum reference rather than run a degenerate (1, D)-block grid."""
    if size <= 0:
        return None
    if size <= preferred:
        return size
    for block in range(preferred - preferred % 8, 7, -8):
        if size % block == 0:
            return block
    return None


def _flash_attention_fwd_impl(
    q, k, v, offsets, causal, scale, block_q, block_k, interpret,
    window=None,
):
    from jax.experimental.pallas import tpu as pltpu

    batch, s_q, heads, dim = q.shape
    s_k = k.shape[1]
    bh = batch * heads

    # [B, S, H, D] -> [B*H, S, D]
    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, x.shape[1], dim)

    qf, kf, vf = fold(q), fold(k), fold(v)
    grid = (bh, s_q // block_q)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=block_k, scale=scale, causal=causal,
            precision=_dot_precision(q.dtype), window=window,
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, dim), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dim), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(offsets, qf, kf, vf)
    return jnp.transpose(out.reshape(batch, heads, s_q, dim), (0, 2, 1, 3))


def _bwd_tile(q_scaled, k_blk, v_blk, do_blk, lse, delta, q_pos, k_pos,
              causal, precision=None, window=None):
    """Shared backward-tile recompute: probabilities and dS for one
    (q-tile x k-tile) pair, from the saved row stats.

    q_scaled must already carry the softmax scale (s = q_scaled @ k^T), so
    ds @ k (for dQ) and ds^T @ q_scaled (for dK) each carry exactly one
    factor of scale — dQ multiplies its own factor afterwards.
    lse/delta are [block_q, 1] columns. Returns (p, ds), both
    [block_q, block_k] f32.
    """
    s = jax.lax.dot_general(
        q_scaled, k_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )
    p = jnp.exp(s - lse)
    if causal:
        visible = q_pos >= k_pos
        if window is not None:
            visible = visible & (q_pos - k_pos < window)
        p = jnp.where(visible, p, 0.0)
    dp = jax.lax.dot_general(
        do_blk, v_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )
    ds = p * (dp - delta)
    return p, ds


def _flash_bwd_dq_kernel(
    offsets_ref,  # SMEM [2] int32
    q_ref,  # VMEM [1, block_q, D]
    k_ref,  # VMEM [1, S_k, D]
    v_ref,  # VMEM [1, S_k, D]
    do_ref,  # VMEM [1, block_q, D]
    lse_ref,  # VMEM [1, block_q, _STATS_LANES]  L = m + log(l), lane-bcast
    delta_ref,  # VMEM [1, block_q, _STATS_LANES]  D = rowsum(dO*O), bcast
    dq_ref,  # VMEM [1, block_q, D]
    *,
    block_k: int,
    scale: float,
    causal: bool,
    precision: Optional[lax.Precision] = None,
    window: Optional[int] = None,
):
    """dQ_i = scale * sum_j dS_ij K_j, with P recomputed per k-tile from
    the saved row stats (FlashAttention-2 backward, query-parallel half)."""
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    dim = q_ref.shape[2]
    s_k = k_ref.shape[1]
    num_kb = s_k // block_k

    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0:1]
    delta = delta_ref[0][:, 0:1]
    q_pos = (
        offsets_ref[0]
        + qi * block_q
        + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    )

    # Same k-block visibility bounds as the forward (shared helper).
    q0 = offsets_ref[0] + qi * block_q
    j_lo, j_hi = _k_block_bounds(
        q0, block_q, block_k, num_kb, offsets_ref[1], causal, window
    )

    def body(j, acc):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        k_pos = (
            offsets_ref[1]
            + j * block_k
            + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        )
        _, ds = _bwd_tile(q, k_blk, v_blk, do, lse, delta, q_pos, k_pos,
                          causal, precision, window)
        return acc + jax.lax.dot_general(
            ds, k_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )

    acc = lax.fori_loop(
        j_lo, j_hi, body, jnp.zeros((block_q, dim), jnp.float32)
    )
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    offsets_ref,  # SMEM [2] int32
    q_ref,  # VMEM [1, S_q, D]
    k_ref,  # VMEM [1, block_k, D]
    v_ref,  # VMEM [1, block_k, D]
    do_ref,  # VMEM [1, S_q, D]
    lse_ref,  # VMEM [1, S_q, _STATS_LANES]
    delta_ref,  # VMEM [1, S_q, _STATS_LANES]
    dk_ref,  # VMEM [1, block_k, D]
    dv_ref,  # VMEM [1, block_k, D]
    *,
    block_q: int,
    scale: float,
    causal: bool,
    precision: Optional[lax.Precision] = None,
    window: Optional[int] = None,
):
    """dK_j = scale * sum_i dS_ij^T Q_i; dV_j = sum_i P_ij^T dO_i (the
    key-parallel half: each grid step owns one k-tile, loops q-tiles)."""
    ki = pl.program_id(1)
    block_k = k_ref.shape[1]
    dim = k_ref.shape[2]
    s_q = q_ref.shape[1]
    num_qb = s_q // block_q

    k_blk = k_ref[0].astype(jnp.float32)
    v_blk = v_ref[0].astype(jnp.float32)
    k_pos = (
        offsets_ref[1]
        + ki * block_k
        + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    )

    # q-block visibility bounds for this k block (the forward's relation
    # transposed): causal keeps q blocks whose max q >= the block's min k;
    # the window keeps q blocks whose min q <= max k + W - 1.
    k0 = offsets_ref[1] + ki * block_k
    i_lo = 0
    i_hi = num_qb
    if causal:
        i_lo = jnp.maximum(0, (k0 - offsets_ref[0]) // block_q)
    if window is not None:
        i_hi = jnp.maximum(
            0,
            jnp.minimum(
                num_qb,
                (k0 + block_k - 1 + window - 1 - offsets_ref[0]) // block_q
                + 1,
            ),
        )

    def body(i, carry):
        dk_acc, dv_acc = carry
        q_blk = (
            q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
            * scale
        )
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :][:, 0:1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :][:, 0:1]
        q_pos = (
            offsets_ref[0]
            + i * block_q
            + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        )
        p, ds = _bwd_tile(q_blk, k_blk, v_blk, do_blk, lse, delta, q_pos,
                          k_pos, causal, precision, window)
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        return dk_acc, dv_acc

    dk_acc, dv_acc = lax.fori_loop(
        i_lo,
        i_hi,
        body,
        (
            jnp.zeros((block_k, dim), jnp.float32),
            jnp.zeros((block_k, dim), jnp.float32),
        ),
    )
    # q was pre-scaled, so ds @ q already carries one factor of scale; dk
    # needs exactly one (dS/dK_j = scale * q_i), which it therefore has.
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def flash_attention_bwd_delta(dout: jax.Array, out: jax.Array) -> jax.Array:
    """delta = rowsum(dO * O) in [B, H, Sq] layout — the O(S*D) precompute
    both backward entry points (single-device _bwd, ring hop) feed to the
    backward kernels."""
    return jnp.transpose(
        jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1),
        (0, 2, 1),
    )


def flash_attention_bwd_tile(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    do: jax.Array,
    lse: jax.Array,
    delta: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    vma=None,
    window: Optional[int] = None,
):
    """Backward of one (q-shard x k-shard) tile: (dq, dk, dv).

    The ring-hop counterpart of flash_attention_tile: given the GLOBAL row
    stats lse = m + log(l) and delta = rowsum(dO*O) (both [B, H, Sq]),
    recomputes this tile's probabilities in the two backward kernels and
    returns its additive contributions — a ring hop accumulates dq locally
    and sends dk/dv around with the k/v blocks. All outputs f32.

    vma: mesh axis names the outputs vary over (shard_map callers).
    window: causal sliding window W in GLOBAL positions.
    """
    _check_window(window, causal)
    if not interpret and jax.default_backend() != "tpu":
        raise ValueError(
            "flash_attention_bwd_tile compiles only on TPU; pass "
            "interpret=True to run in interpreter mode on this backend."
        )
    from jax.experimental.pallas import tpu as pltpu

    batch, s_q, heads, dim = q.shape
    s_k = k.shape[1]
    bh = batch * heads
    scale = scale if scale is not None else dim ** -0.5
    bq = _pick_block(s_q, block_q)
    bk = _pick_block(s_k, block_k)
    if bq is None or bk is None:
        raise ValueError(
            f"No MXU-viable block divides shard lengths (q={s_q}, k={s_k})."
        )
    offsets = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )

    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, x.shape[1], dim)

    def out_struct(shape, dtype=jnp.float32):
        if vma is not None and _SDS_HAS_VMA:
            return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
        return jax.ShapeDtypeStruct(shape, dtype)

    qf, kf, vf, dof = fold(q), fold(k), fold(v), fold(do)
    # Row stats enter the kernels lane-broadcast (see _STATS_LANES).
    lsef = jnp.broadcast_to(
        lse.reshape(bh, s_q)[..., None], (bh, s_q, _STATS_LANES)
    )
    deltaf = jnp.broadcast_to(
        delta.reshape(bh, s_q)[..., None], (bh, s_q, _STATS_LANES)
    )

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_k=bk, scale=scale, causal=causal,
            precision=_dot_precision(q.dtype), window=window,
        ),
        out_shape=out_struct((bh, s_q, dim)),
        grid=(bh, s_q // bq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _STATS_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _STATS_LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, dim), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(offsets, qf, kf, vf, dof, lsef, deltaf)

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=bq, scale=scale, causal=causal,
            precision=_dot_precision(q.dtype), window=window,
        ),
        out_shape=(
            out_struct((bh, s_k, dim)),
            out_struct((bh, s_k, dim)),
        ),
        grid=(bh, s_k // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, s_q, dim), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, dim), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, dim), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, s_q, dim), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, s_q, _STATS_LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, s_q, _STATS_LANES), lambda b, j: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bk, dim), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, dim), lambda b, j: (b, j, 0)),
        ),
        interpret=interpret,
    )(offsets, qf, kf, vf, dof, lsef, deltaf)

    def unfold(x, s):
        return jnp.transpose(x.reshape(batch, heads, s, dim), (0, 2, 1, 3))

    return unfold(dq, s_q), unfold(dk, s_k), unfold(dv, s_k)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10)
)
def _flash_attention(
    q, k, v, q_offset, k_offset, causal, scale, block_q, block_k, interpret,
    window,
):
    offsets = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )
    return _flash_attention_fwd_impl(
        q, k, v, offsets, causal, scale, block_q, block_k, interpret, window
    )


def _fwd(
    q, k, v, q_offset, k_offset, causal, scale, block_q, block_k, interpret,
    window,
):
    # Forward via the tile kernel so the row stats (l, m) come out as
    # residuals; normalization happens here (one O(S*D) elementwise pass).
    o, l, m = flash_attention_tile(
        q, k, v, causal=causal, scale=scale,
        q_offset=q_offset, k_offset=k_offset,
        block_q=block_q, block_k=block_k, interpret=interpret,
        window=window,
    )
    l_safe = jnp.maximum(l, 1e-30)
    out = (o / jnp.transpose(l_safe, (0, 2, 1))[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)  # [B, H, Sq]
    return out, (q, k, v, out, lse, q_offset, k_offset)


def _bwd(causal, scale, block_q, block_k, interpret, window, residuals, g):
    q, k, v, out, lse, q_offset, k_offset = residuals
    dq, dk, dv = flash_attention_bwd_tile(
        q, k, v, g,
        lse,
        flash_attention_bwd_delta(g, out),
        causal=causal, scale=scale,
        q_offset=q_offset, k_offset=k_offset,
        block_q=block_q, block_k=block_k, interpret=interpret,
        window=window,
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), None, None


_flash_attention.defvjp(_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Attention over [B, S, H, D] with the flash recurrence on TPU.

    Falls back to reference_attention off-TPU (unless interpret=True, the
    test path) and for sequence lengths with no usable block divisor.
    q_offset/k_offset shift the global positions of the q/k shards for the
    causal mask (ring-attention tiles).

    window=W restricts each query to the last W keys (causal sliding
    window, q-W < k <= q): the kernel skips k blocks wholly outside the
    window, so long-context compute drops from O(S^2) to O(S*W).
    """
    if q.ndim != 4:
        raise ValueError(f"Expected [B, S, H, D], got {q.shape}")
    _check_window(window, causal)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if interpret is None:
        interpret = False
    # Pallas compiles natively only on TPU; elsewhere the kernel runs in
    # interpreter mode (tests) or falls back to the reference — including
    # when a caller explicitly passes interpret=False off-TPU.
    # Both fallbacks SUPPRESS the serving contraction override: a
    # flash-configured head must compute what the Pallas kernel would
    # (f32), not silently pick up quantized contractions — otherwise
    # the exported program's attention numerics would depend on the
    # export HOST (off-TPU trace = reference fallback) or on the
    # sequence's block divisibility, while T2R_SERVE_NATIVE_ATTN
    # promises flash heads never lower.
    if jax.default_backend() != "tpu" and not interpret:
        with attention_contraction_override(None):
            return reference_attention(
                q, k, v, causal=causal, scale=scale,
                q_offset=q_offset, k_offset=k_offset, window=window,
            )
    bq = _pick_block(q.shape[1], block_q)
    bk = _pick_block(k.shape[1], block_k)
    if bq is None or bk is None:
        with attention_contraction_override(None):
            return reference_attention(
                q, k, v, causal=causal, scale=scale,
                q_offset=q_offset, k_offset=k_offset, window=window,
            )
    return _flash_attention(
        q, k, v, q_offset, k_offset, causal, scale, bq, bk, interpret, window
    )
