"""Pallas TPU flash attention: the per-device attention hot op.

The online-softmax (flash) recurrence computed in a single Pallas kernel:
Q stays resident in VMEM per grid step while K/V are consumed block by
block with running (output, row-sum, row-max) accumulators — the S×S logit
matrix never exists in HBM, so HBM traffic is O(S·D) instead of O(S²)
(the usual bandwidth bound for attention on TPU). Used standalone and as
the per-hop tile kernel of parallel/ring_attention.py, which adds the
sequence-parallel ring on top.

Positions are GLOBAL: q_offset/k_offset shift the causal mask so a kernel
invocation can compute one (q-shard × k-shard) tile of a longer sequence
(exactly what each ring hop needs).

Dispatch: the Pallas path runs on TPU (or anywhere with interpret=True,
which tests use); other backends and non-divisible block shapes fall back
to the einsum reference. Gradients: jax.custom_vjp with the reference
backward — forward pass is flash, backward recomputes attention the plain
way (adequate at robotics sequence lengths; a flash backward kernel is a
further optimization).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
) -> jax.Array:
    """Materialized-logits attention over [B, S, H, D] — numerics oracle
    and non-TPU fallback. Offsets shift global positions for tiled use."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    # Fully-masked rows normalize against the -inf cap instead of NaN-ing.
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


def _flash_body(offsets_ref, q_ref, k_ref, v_ref, block_k, scale, causal):
    """The shared online-softmax recurrence over k blocks; returns the raw
    accumulator triple (o_unnormalized, row_sum, row_max)."""
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    dim = q_ref.shape[2]
    s_k = k_ref.shape[1]
    num_kb = s_k // block_k

    q = q_ref[0].astype(jnp.float32) * scale
    q_pos = (
        offsets_ref[0]
        + qi * block_q
        + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    )

    def body(j, carry):
        o_acc, l_acc, m_acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q,
            k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            k_pos = (
                offsets_ref[1]
                + j * block_k
                + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_acc, m_blk)
        alpha = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new[:, None])
        # Fully-masked tiles contribute nothing (not exp(0)=1 garbage).
        p = jnp.where((m_new == _NEG_INF)[:, None], 0.0, p)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1)
        o_new = o_acc * alpha[:, None] + jax.lax.dot_general(
            p,
            v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o_new, l_new, m_new

    o_acc = jnp.zeros((block_q, dim), jnp.float32)
    l_acc = jnp.zeros((block_q,), jnp.float32)
    m_acc = jnp.full((block_q,), _NEG_INF, jnp.float32)
    return lax.fori_loop(0, num_kb, body, (o_acc, l_acc, m_acc))


def _flash_kernel(
    offsets_ref,  # SMEM [2] int32: (q_offset, k_offset) global shifts
    q_ref,  # VMEM [1, block_q, D]
    k_ref,  # VMEM [1, S_k, D]
    v_ref,  # VMEM [1, S_k, D]
    o_ref,  # VMEM [1, block_q, D]
    *,
    block_k: int,
    scale: float,
    causal: bool,
):
    o_acc, l_acc, _ = _flash_body(
        offsets_ref, q_ref, k_ref, v_ref, block_k, scale, causal
    )
    l_acc = jnp.maximum(l_acc, 1e-30)
    o_ref[0] = (o_acc / l_acc[:, None]).astype(o_ref.dtype)


def _flash_tile_kernel(
    offsets_ref, q_ref, k_ref, v_ref, o_ref, l_ref, m_ref, *, block_k, scale, causal
):
    """Like _flash_kernel but emits the UNNORMALIZED accumulator triple
    (o_partial, row_sum, row_max) — the online-softmax residuals a ring hop
    merges across devices (parallel/ring_attention.py)."""
    o_acc, l_acc, m_acc = _flash_body(
        offsets_ref, q_ref, k_ref, v_ref, block_k, scale, causal
    )
    o_ref[0] = o_acc
    l_ref[0] = l_acc
    m_ref[0] = m_acc


def flash_attention_tile(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    vma=None,
):
    """One (q-shard × k-shard) flash tile over [B, S, H, D].

    Returns (o_partial [B,Sq,H,D] f32 unnormalized, l [B,H,Sq], m [B,H,Sq])
    — the same contract as ring_attention's reference _block_attend, so a
    ring hop can merge tiles across devices without renormalizing twice.

    vma: mesh axis names the outputs vary over — required when called
    inside shard_map (the ring passes its sequence axis).
    """
    if not interpret and jax.default_backend() != "tpu":
        raise ValueError(
            "flash_attention_tile compiles only on TPU; pass interpret=True "
            "to run the kernel in interpreter mode on this backend."
        )
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    batch, s_q, heads, dim = q.shape
    s_k = k.shape[1]
    bh = batch * heads
    scale = scale if scale is not None else dim ** -0.5
    bq = _pick_block(s_q, block_q)
    bk = _pick_block(s_k, block_k)
    if bq is None or bk is None:
        raise ValueError(
            f"No MXU-viable block divides shard lengths (q={s_q}, k={s_k}); "
            "use the reference path (ring_attention use_flash=False) for "
            "these shapes."
        )
    offsets = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )

    def out_struct(shape):
        if vma is not None:
            return jax.ShapeDtypeStruct(shape, jnp.float32, vma=frozenset(vma))
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, x.shape[1], dim)

    o, l, m = pl.pallas_call(
        functools.partial(
            _flash_tile_kernel, block_k=bk, scale=scale, causal=causal
        ),
        out_shape=(
            out_struct((bh, s_q, dim)),
            out_struct((bh, s_q)),
            out_struct((bh, s_q)),
        ),
        grid=(bh, s_q // bq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, i: (b, i)),
            pl.BlockSpec((1, bq), lambda b, i: (b, i)),
        ),
        interpret=interpret,
    )(offsets, fold(q), fold(k), fold(v))
    o = jnp.transpose(o.reshape(batch, heads, s_q, dim), (0, 2, 1, 3))
    return o, l.reshape(batch, heads, s_q), m.reshape(batch, heads, s_q)


def _pick_block(size: int, preferred: int) -> Optional[int]:
    """Usable kernel block size for a sequence dim: the whole dim when it
    fits one block, else the largest divisor <= preferred that is still
    MXU/VPU-viable (>= 8 rows). None -> no viable blocking (prime-ish
    lengths); callers fall back to the einsum reference rather than run a
    degenerate (1, D)-block grid."""
    if size <= 0:
        return None
    if size <= preferred:
        return size
    for block in range(preferred, 7, -1):
        if size % block == 0:
            return block
    return None


def _flash_attention_fwd_impl(
    q, k, v, offsets, causal, scale, block_q, block_k, interpret
):
    from jax.experimental.pallas import tpu as pltpu

    batch, s_q, heads, dim = q.shape
    s_k = k.shape[1]
    bh = batch * heads

    # [B, S, H, D] -> [B*H, S, D]
    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, x.shape[1], dim)

    qf, kf, vf = fold(q), fold(k), fold(v)
    grid = (bh, s_q // block_q)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=block_k, scale=scale, causal=causal
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, dim), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_k, dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dim), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(offsets, qf, kf, vf)
    return jnp.transpose(out.reshape(batch, heads, s_q, dim), (0, 2, 1, 3))


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9)
)
def _flash_attention(
    q, k, v, q_offset, k_offset, causal, scale, block_q, block_k, interpret
):
    offsets = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )
    return _flash_attention_fwd_impl(
        q, k, v, offsets, causal, scale, block_q, block_k, interpret
    )


def _fwd(q, k, v, q_offset, k_offset, causal, scale, block_q, block_k, interpret):
    out = _flash_attention(
        q, k, v, q_offset, k_offset, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, q_offset, k_offset)


def _bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    # Reference backward: recompute attention the materialized way and let
    # autodiff produce exact grads (flash fwd and reference fwd agree to
    # fp tolerance, so these are the true gradients at robotics scales).
    del block_q, block_k, interpret
    q, k, v, q_offset, k_offset = residuals

    def ref(q, k, v):
        return reference_attention(
            q, k, v, causal=causal, scale=scale,
            q_offset=q_offset, k_offset=k_offset,
        )

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None


_flash_attention.defvjp(_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Attention over [B, S, H, D] with the flash recurrence on TPU.

    Falls back to reference_attention off-TPU (unless interpret=True, the
    test path) and for sequence lengths with no usable block divisor.
    q_offset/k_offset shift the global positions of the q/k shards for the
    causal mask (ring-attention tiles).
    """
    if q.ndim != 4:
        raise ValueError(f"Expected [B, S, H, D], got {q.shape}")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if interpret is None:
        interpret = False
    # Pallas compiles natively only on TPU; elsewhere the kernel runs in
    # interpreter mode (tests) or falls back to the reference — including
    # when a caller explicitly passes interpret=False off-TPU.
    if jax.default_backend() != "tpu" and not interpret:
        return reference_attention(
            q, k, v, causal=causal, scale=scale,
            q_offset=q_offset, k_offset=k_offset,
        )
    bq = _pick_block(q.shape[1], block_q)
    bk = _pick_block(k.shape[1], block_k)
    if bq is None or bk is None:
        return reference_attention(
            q, k, v, causal=causal, scale=scale,
            q_offset=q_offset, k_offset=k_offset,
        )
    return _flash_attention(
        q, k, v, q_offset, k_offset, causal, scale, bq, bk, interpret
    )
