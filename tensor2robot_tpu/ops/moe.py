"""Mixture-of-Experts with expert parallelism (GShard-style dense dispatch).

Beyond the reference (SURVEY §2.7 lists expert parallelism as ABSENT
there): a top-k routed expert MLP whose dispatch/combine are dense einsums
over a [tokens, experts, capacity] one-hot tensor — the TPU-native MoE
formulation (GShard / Switch Transformer): static shapes, no gather/
scatter, everything lands on the MXU, and when the expert dimension of the
weights is sharded over the `expert` mesh axis GSPMD lowers the dispatch
einsum to an all_to_all over ICI. Tokens beyond an expert's capacity are
dropped (contribute zero), the standard capacity-factor contract.

Pure functions here; `layers.moe.MoEBlock` is the flax wrapper.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from tensor2robot_tpu.parallel.mesh import EXPERT_AXIS


class Routing(NamedTuple):
    """Dense dispatch/combine for [T] tokens, [E] experts, [C] capacity."""

    dispatch: jax.Array  # [T, E, C] 0/1 — token t occupies slot c of expert e
    combine: jax.Array  # [T, E, C] gate-weighted dispatch
    aux_loss: jax.Array  # scalar load-balance loss (Switch eq. 4 style)


def top_k_routing(
    router_logits: jax.Array,
    num_selected: int,
    capacity: int,
) -> Routing:
    """Builds dispatch/combine tensors from router logits [T, E].

    Top-k gating with renormalized softmax gates; per-expert slots assigned
    in token order (cumsum ranking); tokens ranked past `capacity` are
    dropped. The aux loss is E * sum_e(load_e * importance_e) where load is
    the fraction of top-1 assignments and importance the mean router
    probability — minimized by uniform routing.
    """
    tokens, num_experts = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)
    gate_values, expert_ids = jax.lax.top_k(probs, num_selected)
    if num_selected > 1:
        # Renormalize the selected gates so they sum to 1 per token.
        gate_values = gate_values / jnp.maximum(
            jnp.sum(gate_values, axis=-1, keepdims=True), 1e-9
        )
    # Top-1 keeps the RAW probability as the gate (Switch Transformer):
    # renormalizing would pin it to 1.0 and cut the router out of the task
    # loss's gradient entirely.

    dispatch = jnp.zeros((tokens, num_experts, capacity), probs.dtype)
    combine = jnp.zeros((tokens, num_experts, capacity), probs.dtype)
    # Slots fill selection-major: all k=0 picks rank before any k=1 pick,
    # so a token's primary expert wins capacity over another's secondary.
    slots_used = jnp.zeros((num_experts,), jnp.int32)
    for k in range(num_selected):
        onehot = jax.nn.one_hot(
            expert_ids[:, k], num_experts, dtype=jnp.int32
        )  # [T, E]
        rank = jnp.cumsum(onehot, axis=0) - 1 + slots_used[None, :]  # [T, E]
        position = jnp.sum(rank * onehot, axis=1)  # [T] slot within expert
        kept = position < capacity
        # slots_used counts KEPT assignments, so it is a true slots-filled
        # count (saturates at capacity). Note this does not change which
        # tokens are kept vs the naive all-assignments count: a round can
        # only drop once the expert is full, and a full expert drops every
        # later-k candidate under either accounting.
        slots_used = slots_used + jnp.sum(onehot * kept[:, None], axis=0)
        slot_onehot = jax.nn.one_hot(position, capacity, dtype=probs.dtype)
        contribution = (
            onehot.astype(probs.dtype)[:, :, None] * slot_onehot[:, None, :]
        )
        contribution = contribution * kept.astype(probs.dtype)[:, None, None]
        dispatch = dispatch + contribution
        combine = combine + contribution * gate_values[:, k][:, None, None]

    # Load-balance: fraction of tokens whose TOP-1 pick is e, dotted with
    # mean router prob for e, scaled by E (1.0 at perfect uniformity).
    top1 = jax.nn.one_hot(expert_ids[:, 0], num_experts, dtype=probs.dtype)
    load = jnp.mean(top1, axis=0)
    importance = jnp.mean(probs, axis=0)
    aux_loss = num_experts * jnp.sum(load * importance)
    return Routing(dispatch=dispatch, combine=combine, aux_loss=aux_loss)


def expert_capacity(
    tokens: int,
    num_experts: int,
    num_selected: int,
    capacity_factor: float,
) -> int:
    """Slots per expert: ceil(k*T/E * factor), floored at num_selected so
    toy shapes keep at least one slot per selection."""
    raw = num_selected * tokens * capacity_factor / num_experts
    return max(int(-(-raw // 1)), num_selected)


def moe_mlp(
    x: jax.Array,
    router_kernel: jax.Array,
    w_in: jax.Array,
    w_out: jax.Array,
    *,
    num_selected: int = 2,
    capacity_factor: float = 2.0,
    group_size: Optional[int] = None,
    mesh: Optional[object] = None,
):
    """Expert-routed MLP over [T, F] tokens.

    Args:
      x: [T, F] tokens (flatten batch/seq upstream).
      router_kernel: [F, E].
      w_in: [E, F, H] per-expert up-projection; w_out: [E, H, F].
      group_size: tokens are routed in independent groups of this size
        (must divide T), with capacity computed PER GROUP — the GShard
        grouping that keeps the dense dispatch tensors linear in T
        ([G, g, E, C_g] with C_g ∝ g/E) instead of quadratic (a single
        global group's capacity grows with T, making [T, E, C] ~ T^2).
        None = one global group (fine for small T).
      mesh: when given with an `expert` axis > 1, expert-dim sharding
        constraints are applied so GSPMD inserts the token all_to_all and
        each device computes only its resident experts' FFNs.

    Returns (y [T, F], aux_loss scalar — mean over groups).
    """
    tokens, features = x.shape
    num_experts = w_in.shape[0]
    if group_size is None:
        group_size = tokens
    if tokens % group_size != 0:
        raise ValueError(
            f"group_size {group_size} does not divide token count {tokens}"
        )
    groups = tokens // group_size
    capacity = expert_capacity(
        group_size, num_experts, num_selected, capacity_factor
    )

    xg = x.reshape(groups, group_size, features)
    logits = jnp.einsum("gtf,fe->gte", xg, router_kernel)
    routing = jax.vmap(
        lambda lg: top_k_routing(lg, num_selected, capacity)
    )(logits)

    expert_inputs = jnp.einsum("gtec,gtf->gecf", routing.dispatch, xg)
    if mesh is not None and dict(mesh.shape).get(EXPERT_AXIS, 1) > 1:
        expert_inputs = jax.lax.with_sharding_constraint(
            expert_inputs,
            jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(None, EXPERT_AXIS)
            ),
        )
    hidden = jax.nn.gelu(jnp.einsum("gecf,efh->gech", expert_inputs, w_in))
    expert_outputs = jnp.einsum("gech,ehf->gecf", hidden, w_out)
    y = jnp.einsum("gtec,gecf->gtf", routing.combine, expert_outputs)
    return y.reshape(tokens, features), jnp.mean(routing.aux_loss)
