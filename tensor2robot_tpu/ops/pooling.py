"""Non-overlapping max pooling with a backend-dispatched backward.

Two backward formulations exist for a non-overlapping (window == stride)
max pool — every pool in the Grasping44 tower is of this form (reference
research/qtopt/networks.py:446,460,540):

* XLA-native: `lax.reduce_window`'s registered gradient, which lowers to
  SelectAndScatter.
* Scatter-free (`max_pool_nonoverlap` below): reshape the input into its
  disjoint windows, compare against the broadcast pooled maximum, and
  split the incoming gradient over the mask — pure elementwise/reduce
  work.

Which one wins is a HARDWARE question, and the two measurements disagree:
on CPU the scatter-free VJP removed the top non-gather op of the step
(round-4 HLO census), but the round-5 on-chip A/B at the stem activation
size (DIAG_STEP_r05.json, TPU v5e, bs64 236x236x64: scatterfree 55.7 ms
vs SelectAndScatter 41.7 ms against a shared ~34 ms readback floor, i.e.
~22 ms vs ~8 ms of compute) shows TPU's native SelectAndScatter pool
gradient beating the reshape/mask formulation ~3x. `max_pool` therefore
dispatches on the backend at trace time: native on TPU, scatter-free
elsewhere; `T2R_POOL_BACKWARD=scatterfree|native` forces either path
(the bench A/B uses this).

The forward stays `lax.reduce_window` (already optimal on TPU); only the
VJP is replaced via `jax.custom_vjp`.

Gradient tie-breaking: where a window holds several elements equal to the
maximum (common after relu: exact zeros), the incoming gradient is split
EQUALLY among them, whereas SelectAndScatter routes it all to the first.
Both are valid subgradients of the same function; the equal split is the
same choice `jnp.max`'s native gradient makes.

Known limitation: `jax.custom_vjp` forecloses FORWARD-mode autodiff —
`jax.jvp`/`jax.jacfwd` through any model containing these pools raises
TypeError, a capability `nn.max_pool` had. No in-repo caller uses
forward mode; if one ever does, the equal-split rule has a natural
linear JVP (mask-weighted tangent average) and the op can be
restructured as `jax.custom_jvp` to support both modes.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tensor2robot_tpu import flags


def resolve_backward_mode() -> str:
    """Resolves T2R_POOL_BACKWARD to the concrete VJP path.

    Returns "native" or "scatterfree"; unknown values fail fast (a typo
    silently selecting the slow backward would poison a benchmark round).

    "auto" reports the path the CURRENT DEFAULT BACKEND would run — a
    provenance answer (bench payloads), not a promise about every
    execution: `max_pool`'s auto mode dispatches via
    `lax.platform_dependent`, so the VJP is selected by each lowering's
    actual platform and an AOT export compiled for a different backend
    gets THAT backend's path, not this process's (ADVICE round-5). The
    forced modes bake the named path in at trace time on every platform.
    """
    mode = flags.get_enum("T2R_POOL_BACKWARD")
    if mode == "auto":
        return "native" if jax.default_backend() == "tpu" else "scatterfree"
    return mode


def _native_pool(
    x: jax.Array, window: Tuple[int, int], padding: str
) -> jax.Array:
    dims = (1, window[0], window[1], 1)
    # Init must be the -inf LITERAL: jax's reverse-mode rule for max
    # pooling pattern-matches (literal init, lax.max) — a device-array
    # init turns this into a general reduce_window with no transpose.
    return lax.reduce_window(
        x, -jnp.inf, lax.max, dims, dims, padding.upper()
    )


def max_pool(
    x: jax.Array, window: Tuple[int, int], padding: str = "SAME"
) -> jax.Array:
    """Non-overlapping max pool with the fastest backward for the backend.

    Forward is `lax.reduce_window` on every path (bit-identical results);
    the paths differ only in the VJP (and in subgradient tie-breaking:
    native SelectAndScatter routes tied gradients to the first maximal
    element, scatter-free splits them equally — both valid subgradients).

    Auto mode binds at LOWERING, not trace: `lax.platform_dependent`
    embeds both formulations and selects by the platform each lowering
    actually targets, so a computation traced on one backend but compiled
    for another (AOT export, explicit backend= jit) runs the VJP that is
    fast THERE. Forced modes (T2R_POOL_BACKWARD=native|scatterfree) stay
    trace-time on purpose — they exist for A/B benches that must pin one
    path everywhere.
    """
    mode = flags.get_enum("T2R_POOL_BACKWARD")
    if mode == "auto" and hasattr(lax, "platform_dependent"):
        return lax.platform_dependent(
            x,
            tpu=lambda x: _native_pool(x, window, padding),
            default=lambda x: max_pool_nonoverlap(x, window, padding),
        )
    if resolve_backward_mode() == "native":
        return _native_pool(x, window, padding)
    return max_pool_nonoverlap(x, window, padding)


def _pool_pads(shape, window: Tuple[int, int], padding: str):
    """Per-dimension (low, high) pads on an NHWC input, matching
    lax.reduce_window's padtype_to_pads for stride == window."""
    dims = (1, window[0], window[1], 1)
    return lax.padtype_to_pads(shape, dims, dims, padding)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def max_pool_nonoverlap(
    x: jax.Array, window: Tuple[int, int], padding: str = "SAME"
) -> jax.Array:
    """Max pool over NHWC with stride == window, SAME or VALID padding."""
    dims = (1, window[0], window[1], 1)
    init = jnp.asarray(-jnp.inf, x.dtype)
    return lax.reduce_window(x, init, lax.max, dims, dims, padding)


def _fwd(x, window, padding):
    return max_pool_nonoverlap(x, window, padding), x


def _bwd(window, padding, x, g):
    # The window maximum is RECOMPUTED here from the same reshaped-window
    # tensor the mask compares against, rather than reusing the forward's
    # output: inside a large fused program XLA may rematerialize the
    # forward max with different intermediate numerics (e.g. a different
    # relu/cast fusion upstream), and an equality test against a
    # not-bit-identical max can match zero elements in a window —
    # turning the g/count split into inf. Self-consistency by
    # construction guarantees count >= 1. (It also shrinks the residual
    # to just x.)
    #
    # SAME pads with -inf so partial windows align; VALID instead DROPS
    # the trailing remainder (those inputs get zero gradient, matching
    # reduce_window's VALID semantics).
    wh, ww = window
    b, h, w, c = x.shape
    # reduce_window uppercases padding strings in the forward; match it,
    # or a lowercase "valid" would take the SAME branch here.
    padding = padding.upper()
    if padding == "VALID":
        oh, ow = h // wh, w // ww
        xp = x[:, : oh * wh, : ow * ww, :]
        hp, wp = oh * wh, ow * ww
        pads = None
    else:
        pads = _pool_pads(x.shape, window, padding)
        xp = jnp.pad(x, pads, constant_values=-jnp.inf)
        hp, wp = xp.shape[1], xp.shape[2]
        oh, ow = hp // wh, wp // ww
    windows = xp.reshape(b, oh, wh, ow, ww, c)
    mask = windows == jnp.max(windows, axis=(2, 4), keepdims=True)
    count = jnp.sum(mask, axis=(2, 4), keepdims=True)
    share = (g[:, :, None, :, None, :] / count.astype(g.dtype)) * mask
    gx = share.reshape(b, hp, wp, c)
    if padding == "VALID":
        gx = jnp.pad(gx, ((0, 0), (0, h - hp), (0, w - wp), (0, 0)))
    else:
        gx = gx[
            :,
            pads[1][0] : hp - pads[1][1],
            pads[2][0] : wp - pads[2][1],
            :,
        ]
    return (gx.astype(x.dtype),)


max_pool_nonoverlap.defvjp(_fwd, _bwd)
