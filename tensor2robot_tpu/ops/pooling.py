"""Non-overlapping max pooling with a scatter-free backward.

XLA lowers the gradient of window max pooling to SelectAndScatter, which
on TPU executes as a slow, poorly-fusible per-window scatter — the round-3
profile showed the Grasping44 stem pool's select-and-scatter as the single
most expensive non-gather op in the train step. Every pool in the
Grasping44 tower (reference research/qtopt/networks.py:446,460,540) is
NON-overlapping (window == stride), where the backward has a much better
formulation: reshape the input into its disjoint windows, compare against
the broadcast pooled maximum, and split the incoming gradient over the
mask — pure elementwise/reduce work that XLA fuses.

The forward stays `lax.reduce_window` (already optimal on TPU); only the
VJP is replaced via `jax.custom_vjp`.

Gradient tie-breaking: where a window holds several elements equal to the
maximum (common after relu: exact zeros), the incoming gradient is split
EQUALLY among them, whereas SelectAndScatter routes it all to the first.
Both are valid subgradients of the same function; the equal split is the
same choice `jnp.max`'s native gradient makes.

Known limitation: `jax.custom_vjp` forecloses FORWARD-mode autodiff —
`jax.jvp`/`jax.jacfwd` through any model containing these pools raises
TypeError, a capability `nn.max_pool` had. No in-repo caller uses
forward mode; if one ever does, the equal-split rule has a natural
linear JVP (mask-weighted tangent average) and the op can be
restructured as `jax.custom_jvp` to support both modes.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _pool_pads(shape, window: Tuple[int, int], padding: str):
    """Per-dimension (low, high) pads on an NHWC input, matching
    lax.reduce_window's padtype_to_pads for stride == window."""
    dims = (1, window[0], window[1], 1)
    return lax.padtype_to_pads(shape, dims, dims, padding)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def max_pool_nonoverlap(
    x: jax.Array, window: Tuple[int, int], padding: str = "SAME"
) -> jax.Array:
    """Max pool over NHWC with stride == window, SAME or VALID padding."""
    dims = (1, window[0], window[1], 1)
    init = jnp.asarray(-jnp.inf, x.dtype)
    return lax.reduce_window(x, init, lax.max, dims, dims, padding)


def _fwd(x, window, padding):
    return max_pool_nonoverlap(x, window, padding), x


def _bwd(window, padding, x, g):
    # The window maximum is RECOMPUTED here from the same reshaped-window
    # tensor the mask compares against, rather than reusing the forward's
    # output: inside a large fused program XLA may rematerialize the
    # forward max with different intermediate numerics (e.g. a different
    # relu/cast fusion upstream), and an equality test against a
    # not-bit-identical max can match zero elements in a window —
    # turning the g/count split into inf. Self-consistency by
    # construction guarantees count >= 1. (It also shrinks the residual
    # to just x.)
    #
    # SAME pads with -inf so partial windows align; VALID instead DROPS
    # the trailing remainder (those inputs get zero gradient, matching
    # reduce_window's VALID semantics).
    wh, ww = window
    b, h, w, c = x.shape
    # reduce_window uppercases padding strings in the forward; match it,
    # or a lowercase "valid" would take the SAME branch here.
    padding = padding.upper()
    if padding == "VALID":
        oh, ow = h // wh, w // ww
        xp = x[:, : oh * wh, : ow * ww, :]
        hp, wp = oh * wh, ow * ww
        pads = None
    else:
        pads = _pool_pads(x.shape, window, padding)
        xp = jnp.pad(x, pads, constant_values=-jnp.inf)
        hp, wp = xp.shape[1], xp.shape[2]
        oh, ow = hp // wh, wp // ww
    windows = xp.reshape(b, oh, wh, ow, ww, c)
    mask = windows == jnp.max(windows, axis=(2, 4), keepdims=True)
    count = jnp.sum(mask, axis=(2, 4), keepdims=True)
    share = (g[:, :, None, :, None, :] / count.astype(g.dtype)) * mask
    gx = share.reshape(b, hp, wp, c)
    if padding == "VALID":
        gx = jnp.pad(gx, ((0, 0), (0, h - hp), (0, w - wp), (0, 0)))
    else:
        gx = gx[
            :,
            pads[1][0] : hp - pads[1][1],
            pads[2][0] : wp - pads[2][1],
            :,
        ]
    return (gx.astype(x.dtype),)


max_pool_nonoverlap.defvjp(_fwd, _bwd)
