"""Parallelism over the device mesh: axes, collectives, schedules.

Six named mesh axes (parallel/mesh.py) cover every regime the framework
ships: data/fsdp (batch + ZeRO-3 parameter sharding), model (tensor
parallelism), sequence (ring attention), pipe (GPipe pipeline schedule),
expert (MoE dispatch). See docs/PARALLELISM.md.
"""

from tensor2robot_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQUENCE_AXIS,
    data_sharding,
    initialize_distributed,
    make_mesh,
    param_sharding,
    replicated,
    shard_batch,
)
from tensor2robot_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    stage_sharding,
)

# NOTE: ring_attention is NOT re-exported as a function here — the package
# attribute `parallel.ring_attention` must stay the submodule (callers use
# `from tensor2robot_tpu.parallel import ring_attention` then
# `ring_attention.ring_attention(...)`; rebinding it breaks them).
