"""The gradient-collective registry: quantized reduce-scatter/all-gather.

After PR's cross-replica weight-update sharding (ZeRO-2, arXiv:2004.13336)
the per-step cost on the data axis is COMMS, not FLOPs: every step moves
the full fp32 gradient through a reduce-scatter and the full update back
through an all-gather. EQuARX (arXiv:2506.17615) shows blockwise-quantized
all-reduce recovers most of that bandwidth at negligible quality cost.
This module is the single home for that machinery:

  * a registry of `GradientCollective`s — `none` (exact fp32, lowering to
    the same psum_scatter/all_gather GSPMD emits), `fp16`, `int8`,
    `fp8_e4m3` and `fp8_e5m2` (blockwise per-block scales) — selected by
    the central `T2R_COLLECTIVE_QUANT` / `T2R_COLLECTIVE_BLOCK` flags;
  * error feedback: both quantized collectives return the dequantized
    copy of what was actually transmitted, so the caller can carry
    `sent - intended` as a residual and re-inject it next step (the
    EF-SGD contract that preserves convergence under biased compression);
  * `FlatShardLayout`: the pad-to-block bookkeeping that maps a raveled
    gradient vector onto equal per-device shards;
  * the SANCTIONED spellings of jax's manual collectives (`psum`,
    `pmean`, `ppermute`, `all_to_all`, `all_gather`, `psum_scatter`,
    `axis_index`) and of `shard_map` itself. The
    `collective-outside-registry` lint (analysis/lints.py) errors on raw
    `jax.lax.p*` / `shard_map` use anywhere else in `train/` and
    `parallel/`, so every byte that crosses the data axis is visible —
    and quantizable — from this one file.

Wire-format accounting is analytic (`wire_bytes`): XLA does not expose
per-collective byte counters, but the payload is exactly the arrays we
hand to `all_to_all`/`all_gather`, so bytes = sum of payload sizes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

try:  # jax >= 0.7 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from tensor2robot_tpu import flags

__all__ = [
    "GradientCollective",
    "FlatShardLayout",
    "available_collectives",
    "get_collective",
    "register_collective",
    "smap",
    "wire_summary",
    # sanctioned manual-collective spellings (lint: collective-outside-
    # registry bans the raw jax.lax forms outside this file):
    "all_gather",
    "all_to_all",
    "axis_index",
    "pmean",
    "ppermute",
    "psum",
    "psum_scatter",
    "shard_map",
]


# -- sanctioned primitive spellings -------------------------------------------
# Thin passthroughs, not abstractions: their value is that every manual
# collective in train/ + parallel/ routes through ONE importable, greppable,
# lintable module. They accept pytrees wherever jax.lax does.

# jax renamed shard_map's replication-checking knob check_rep -> check_vma;
# the registry translates whichever spelling the caller used to whatever
# the installed jax accepts, so callers never version-guard it themselves.
_SHARD_MAP_PARAMS = frozenset(
    __import__("inspect").signature(_shard_map).parameters
)


def shard_map(fn, *args, **kwargs):
    for ours, theirs in (("check_vma", "check_rep"), ("check_rep", "check_vma")):
        if ours in kwargs and ours not in _SHARD_MAP_PARAMS:
            if theirs in _SHARD_MAP_PARAMS:
                kwargs[theirs] = kwargs.pop(ours)
            else:  # pragma: no cover - jax without the knob
                kwargs.pop(ours)
    return _shard_map(fn, *args, **kwargs)


def smap(fn, mesh, in_specs, out_specs, check_rep: bool = False):
    """`shard_map` with the trainer's defaults (replication checking off:
    the quantized update produces replicated outputs by construction —
    psum'd metrics, identically-computed params — which the static
    checker cannot always prove through all_to_all/gather chains)."""
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_rep,
    )


def psum(x, axis_name):
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm=perm)


def all_to_all(x, axis_name, split_axis, concat_axis, *, tiled=False):
    return lax.all_to_all(
        x, axis_name, split_axis, concat_axis, tiled=tiled
    )


def all_gather(x, axis_name, *, axis=0, tiled=False):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def psum_scatter(x, axis_name, *, scatter_dimension=0, tiled=False):
    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled
    )


def axis_index(axis_name):
    return lax.axis_index(axis_name)


# -- blockwise quantization ----------------------------------------------------


def _block_view(x: jax.Array, block: int) -> jax.Array:
    """[..., L] -> [..., L//block, block]; L must divide by block (the
    FlatShardLayout guarantees it for trainer payloads)."""
    if x.shape[-1] % block != 0:
        raise ValueError(
            f"last dim {x.shape[-1]} not divisible by block {block}"
        )
    return x.reshape(x.shape[:-1] + (x.shape[-1] // block, block))


def _block_scales(blocks: jax.Array) -> jax.Array:
    """Per-block max-abs scale with zero blocks mapped to scale 1 (their
    quantized payload is all zeros either way; 1 keeps decode NaN-free)."""
    scale = jnp.max(jnp.abs(blocks), axis=-1)
    return jnp.where(scale > 0, scale, jnp.ones_like(scale))


@dataclasses.dataclass(frozen=True)
class GradientCollective:
    """One wire format for the data-axis gradient collectives.

    encode/decode are exact inverses of the TRANSMITTED value (not of the
    input): `decode(encode(x))` is the dequantized copy the receivers
    reconstruct, and `x - decode(encode(x))` is the error-feedback
    residual. Subclasses override `encode`/`decode`/`bits` (and may
    override the collectives themselves — the exact path uses the fused
    psum_scatter lowering instead of quantize+all_to_all).
    """

    name: str
    block: int

    # - wire format -
    def encode(self, x: jax.Array):
        raise NotImplementedError

    def decode(self, payload) -> jax.Array:
        raise NotImplementedError

    def wire_bytes(self, n_elements: int) -> int:
        """Payload bytes for n fp32 elements (values + per-block scales)."""
        raise NotImplementedError

    # - collectives -
    def reduce_scatter(
        self, rows: jax.Array, axis_name: str
    ) -> Tuple[jax.Array, jax.Array]:
        """Quantized reduce-scatter over `axis_name`.

        `rows` is the device's local gradient split into one [L] chunk
        per peer: shape [N, L] with N the axis size. Chunk j is encoded
        and shipped to peer j (all_to_all); each device decodes the N
        chunks it receives and sums them exactly in fp32.

        Returns (reduced [L], sent [N, L]): `reduced` is this device's
        shard of the SUM over peers of their dequantized chunks; `sent`
        is the dequantized copy of what this device transmitted —
        `rows - sent` is the error-feedback residual.
        """
        payload = self.encode(rows)
        received = jax.tree_util.tree_map(
            lambda t: all_to_all(t, axis_name, 0, 0, tiled=True), payload
        )
        reduced = self.decode(received).astype(jnp.float32).sum(axis=0)
        return reduced, self.decode(payload).astype(jnp.float32)

    def all_gather_shard(
        self, shard: jax.Array, axis_name: str
    ) -> Tuple[jax.Array, jax.Array]:
        """Quantized all-gather of a per-device [L] shard.

        Returns (full [N*L], sent [L]): `full` concatenates every peer's
        dequantized shard in axis order (identical on all devices);
        `sent` is the dequantized copy of this device's own contribution
        — `shard - sent` is the error-feedback residual.
        """
        payload = self.encode(shard)
        gathered = jax.tree_util.tree_map(
            lambda t: all_gather(t, axis_name, tiled=True), payload
        )
        full = self.decode(gathered).astype(jnp.float32)
        return full, self.decode(payload).astype(jnp.float32)


class ExactCollective(GradientCollective):
    """fp32 passthrough: byte-for-byte the collectives GSPMD emits for the
    ZeRO-2 step (psum_scatter + all_gather), with a no-op error channel."""

    def encode(self, x):
        return {"v": x}

    def decode(self, payload):
        return payload["v"]

    def wire_bytes(self, n_elements: int) -> int:
        return 4 * n_elements

    def reduce_scatter(self, rows, axis_name):
        reduced = psum_scatter(rows, axis_name, scatter_dimension=0)
        return reduced, rows

    def all_gather_shard(self, shard, axis_name):
        return all_gather(shard, axis_name, tiled=True), shard


class BlockScaledCollective(GradientCollective):
    """Shared decode for the `{'q': values, 's': per-block scales}` wire
    format: cast to fp32, multiply each block by its scale. One body so
    the two quantized formats cannot silently diverge."""

    def decode(self, payload):
        q, scales = payload["q"], payload["s"]
        blocks = _block_view(q.astype(jnp.float32), self.block)
        return (blocks * scales[..., None]).reshape(q.shape)


class Fp16Collective(BlockScaledCollective):
    """Blockwise-scaled fp16: each block is normalized by its max-abs to
    [-1, 1] before the cast, so no block can overflow fp16 range and small
    blocks keep full relative precision. 2 bytes/element + 4/block."""

    def encode(self, x):
        blocks = _block_view(x, self.block)
        scales = _block_scales(blocks)
        values = (blocks / scales[..., None]).astype(jnp.float16)
        return {"q": values.reshape(x.shape), "s": scales}

    def wire_bytes(self, n_elements: int) -> int:
        return 2 * n_elements + 4 * (n_elements // self.block)


class Int8Collective(BlockScaledCollective):
    """Blockwise symmetric int8: scale = max|block| / 127, round-to-
    nearest. 1 byte/element + 4/block — 3.94x fewer wire bytes than fp32
    at the default block of 512."""

    def encode(self, x):
        blocks = _block_view(x, self.block)
        scales = _block_scales(blocks) / 127.0
        values = jnp.clip(
            jnp.round(blocks / scales[..., None]), -127, 127
        ).astype(jnp.int8)
        return {"q": values.reshape(x.shape), "s": scales}

    def wire_bytes(self, n_elements: int) -> int:
        return n_elements + 4 * (n_elements // self.block)


class Fp8Collective(BlockScaledCollective):
    """Blockwise-scaled fp8: each block is normalized so its max-abs maps
    to the format's largest finite value (the full exponent range earns
    its keep, unlike a [-1, 1] normalization), clipped, then cast. The
    clip is load-bearing: jax fp8 casts do NOT saturate — an overflow
    becomes NaN, and one NaN would poison the whole reduced shard. Same
    wire cost as int8 (1 byte/element + 4/block); the trade is rounding
    that is RELATIVE per element (floating mantissa) instead of absolute
    per block, which favors gradients whose blocks mix magnitudes.
    `decode` is the shared BlockScaledCollective body — fp8 payloads are
    bit-compatible with the rest of the registry's q/s wire format.
    """

    _DTYPE = None  # subclass: the ml_dtypes fp8 storage dtype
    _MAX = 0.0  # subclass: largest finite value of the format

    def encode(self, x):
        blocks = _block_view(x, self.block)
        scales = _block_scales(blocks) / self._MAX
        values = jnp.clip(
            blocks / scales[..., None], -self._MAX, self._MAX
        ).astype(self._DTYPE)
        return {"q": values.reshape(x.shape), "s": scales}

    def wire_bytes(self, n_elements: int) -> int:
        return n_elements + 4 * (n_elements // self.block)


class Fp8E4M3Collective(Fp8Collective):
    """fp8 e4m3 (3 mantissa bits, max 448): ~2^-4 relative rounding —
    the precision-leaning fp8 format."""

    _DTYPE = jnp.float8_e4m3fn
    _MAX = 448.0


class Fp8E5M2Collective(Fp8Collective):
    """fp8 e5m2 (2 mantissa bits, max 57344): ~2^-3 relative rounding —
    the range-leaning fp8 format (bfloat16's dynamic range, halved)."""

    _DTYPE = jnp.float8_e5m2
    _MAX = 57344.0


# -- the registry --------------------------------------------------------------

_REGISTRY: Dict[str, Callable[[int], GradientCollective]] = {}


def register_collective(name: str):
    """Registers a factory(block) -> GradientCollective under `name`."""

    def deco(factory):
        if name in _REGISTRY:
            raise ValueError(f"collective {name!r} registered twice")
        _REGISTRY[name] = factory
        return factory

    return deco


register_collective("none")(lambda block: ExactCollective("none", block))
register_collective("fp16")(lambda block: Fp16Collective("fp16", block))
register_collective("int8")(lambda block: Int8Collective("int8", block))
register_collective("fp8_e4m3")(
    lambda block: Fp8E4M3Collective("fp8_e4m3", block)
)
register_collective("fp8_e5m2")(
    lambda block: Fp8E5M2Collective("fp8_e5m2", block)
)


def available_collectives() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def get_collective(
    name: Optional[str] = None, block: Optional[int] = None
) -> GradientCollective:
    """Resolves a collective; None args read the central flag registry
    (T2R_COLLECTIVE_QUANT / T2R_COLLECTIVE_BLOCK)."""
    if name is None:
        name = flags.get_enum("T2R_COLLECTIVE_QUANT")
    if block is None:
        block = flags.get_int("T2R_COLLECTIVE_BLOCK")
    factory = _REGISTRY.get(name)
    if factory is None:
        # Name the selector AND the menu: a typo'd regime must tell the
        # operator what values exist and which flag picks one (the same
        # name-the-flag discipline as the flags.py getters).
        raise KeyError(
            f"unknown collective {name!r}; available regimes: "
            f"{', '.join(available_collectives())} "
            "(selected by T2R_COLLECTIVE_QUANT, block size by "
            "T2R_COLLECTIVE_BLOCK)"
        )
    return factory(block)


# -- flat shard layout ---------------------------------------------------------


class FlatShardLayout:
    """Pad-to-block bookkeeping for the flat sharded weight update.

    The quantized ZeRO-2 step works on the RAVELED gradient/parameter
    vector so every device owns one contiguous [shard_len] shard whose
    length divides by the quantization block. num_params elements pad
    with zeros up to padded = num_shards * shard_len; zero-padded tail
    elements carry zero gradient forever, so standard elementwise
    optimizers (Adam & friends) keep their tail params at exactly zero.
    """

    def __init__(self, num_params: int, num_shards: int, block: int):
        if num_params < 1:
            raise ValueError("empty parameter vector")
        if num_shards < 1 or block < 1:
            raise ValueError(
                f"bad layout: shards={num_shards} block={block}"
            )
        shard_len = -(-num_params // num_shards)
        shard_len = -(-shard_len // block) * block
        self.num_params = num_params
        self.num_shards = num_shards
        self.block = block
        self.shard_len = shard_len
        self.padded = shard_len * num_shards

    def pad(self, flat: jax.Array) -> jax.Array:
        if flat.shape != (self.num_params,):
            raise ValueError(
                f"expected [{self.num_params}] vector, got {flat.shape}"
            )
        return jnp.pad(flat, (0, self.padded - self.num_params))

    def rows(self, flat_padded: jax.Array) -> jax.Array:
        return flat_padded.reshape(self.num_shards, self.shard_len)

    def unpad(self, flat_padded: jax.Array) -> jax.Array:
        return flat_padded[: self.num_params]


def wire_summary(
    collective: GradientCollective, n_elements: int
) -> Tuple[int, int]:
    """(fp32_bytes, wire_bytes) per device-step for the ZeRO-2 exchange:
    one reduce-scatter of the gradient plus one all-gather of the update,
    each moving n_elements through the collective's wire format. Callers
    format these through train.metrics.collective_record so the trainer's
    log stream and the bench payload share key names."""
    return 2 * 4 * n_elements, 2 * collective.wire_bytes(n_elements)
