"""Device mesh construction and sharding rules.

The trainer compiles every step against a `jax.sharding.Mesh` with named
axes; parallelism is data-parallel by default (the reference's TPUEstimator
batch-sharding + CrossShardOptimizer all-reduce, which GSPMD reproduces as
psum over the 'data' axis), with optional fsdp/model/sequence axes available
for larger networks — the axes slot into the same mesh without touching
model code.

Multi-host: `initialize_distributed()` wires jax.distributed so each host
contributes its local devices to one global mesh over ICI/DCN; the
file-based learner<->robot bus is unchanged (see export/predictors).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
SEQUENCE_AXIS = "sequence"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"

#: Param-tree key under which a pipelined module stores its stacked
#: [S, ...] per-stage parameters (layers/transformer.py pipelined
#: encoder); pipe_stage_param_rule shards that subtree's dim 0 over pipe.
PIPE_STAGES_KEY = "pipe_stages"


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up over DCN. No-ops on single-process runs.

    Args default from the standard env (JAX_COORDINATOR_ADDRESS etc.), the
    JAX-native analogue of the reference's TF_CONFIG cluster plumbing
    (input_generators/default_input_generator.py:32-44).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(
    data: Optional[int] = None,
    fsdp: int = 1,
    model: int = 1,
    sequence: int = 1,
    pipe: int = 1,
    expert: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Builds a mesh over (data, fsdp, model, sequence, pipe, expert) axes.

    `data=None` absorbs all remaining devices. Axis sizes must multiply to
    the device count. Device order follows jax.devices(), which enumerates
    ICI-contiguous chips first — so the fastest-varying (model/sequence/
    pipe/expert) axes land on ICI neighbors and data-parallel all-reduce
    rides the slower links, the standard TPU layout.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = fsdp * model * sequence * pipe * expert
    if data is None:
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by "
                f"fsdp*model*sequence*pipe*expert={fixed}"
            )
        data = n // fixed
    if data * fixed != n:
        raise ValueError(
            f"Mesh {data}x{fsdp}x{model}x{sequence}x{pipe}x{expert} "
            f"!= {n} devices"
        )
    array = np.asarray(devices).reshape(
        data, fsdp, model, sequence, pipe, expert
    )
    return Mesh(
        array,
        (DATA_AXIS, FSDP_AXIS, MODEL_AXIS, SEQUENCE_AXIS, PIPE_AXIS,
         EXPERT_AXIS),
    )


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch sharding: leading dim split over data (and fsdp, which acts as
    extra data parallelism for the input batch in fsdp regimes)."""
    return NamedSharding(mesh, PartitionSpec((DATA_AXIS, FSDP_AXIS)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_batch(batch, mesh: Mesh):
    """Places a host batch onto the mesh, leading axis split across data.

    Training batches (drop_remainder upstream) divide evenly and shard; a
    leaf whose leading dim does not divide the data axis (small predict
    batches, scalars) is replicated instead — correct, at the cost of
    redundant compute, which only ever happens off the training hot path.
    """
    sharding = data_sharding(mesh)
    replicated_sharding = replicated(mesh)
    divisor = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]

    def put(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1 and shape[0] % divisor == 0:
            return jax.device_put(leaf, sharding)
        return jax.device_put(leaf, replicated_sharding)

    return jax.tree_util.tree_map(put, batch)


def _assign_largest_divisible_dim(spec, shape, axis_size, axis_name) -> None:
    """Marks the largest still-unsharded dim divisible by axis_size with
    axis_name (in place); leaves spec untouched when none divides."""
    dims = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for dim in dims:
        if spec[dim] is None and shape[dim] % axis_size == 0:
            spec[dim] = axis_name
            return


def weight_update_sharding(mesh: Mesh, min_weight_size: int = 2 ** 14):
    """Sharding rule for OPTIMIZER-SIDE state in pure data parallelism
    (cross-replica weight-update sharding, Xu et al. arXiv:2004.13336 —
    the ZeRO-2 layout): parameters stay replicated for the forward/
    backward, but optimizer moments and the EMA mirror shard their
    largest divisible dim over the data axis; GSPMD turns the gradient
    all-reduce into reduce-scatter + sharded update + all-gather. Cuts
    the optimizer-state footprint by the data-axis size with no model-
    side change. Leaves with no dim divisible by the data-axis size stay
    replicated (no padding is introduced).
    """
    data_size = mesh.shape[DATA_AXIS]

    def rule(leaf):
        shape = getattr(leaf, "shape", None)
        if (
            shape is None
            or data_size == 1
            or np.prod(shape) < min_weight_size
        ):
            return NamedSharding(mesh, PartitionSpec())
        spec = [None] * len(shape)
        _assign_largest_divisible_dim(spec, shape, data_size, DATA_AXIS)
        return NamedSharding(mesh, PartitionSpec(*spec))

    return rule


def pipe_stage_param_rule(mesh: Mesh, base_rule):
    """Path-aware sharding rule layering pipeline-stage placement over a
    per-leaf base rule: any leaf under a PIPE_STAGES_KEY tree key whose
    leading dim equals the pipe-axis size shards dim 0 over `pipe` (the
    layout pipeline_apply consumes); every other leaf falls through to
    base_rule. Optimizer moments and the EMA mirror the param tree's
    keys, so the same rule places them without special cases.
    """
    pipe_size = mesh.shape[PIPE_AXIS]
    stage_sharding = NamedSharding(mesh, PartitionSpec(PIPE_AXIS))

    def rule(path, leaf):
        shape = getattr(leaf, "shape", None)
        if (
            pipe_size > 1
            and shape
            and shape[0] == pipe_size
            and any(
                getattr(entry, "key", None) == PIPE_STAGES_KEY
                for entry in path
            )
        ):
            return stage_sharding
        return base_rule(leaf)

    return rule


def param_sharding(mesh: Mesh, min_weight_size: int = 2 ** 14):
    """Tree-map-able parameter sharding rule over the fsdp and model axes.

    Tensor parallelism: matrix/conv-kernel leaves shard their OUTPUT dim
    (last axis — flax dense kernels are [in, out], conv kernels HWIO) over
    the `model` axis; GSPMD then propagates the sharding through the
    matmul and inserts the per-layer collectives (the Megatron column
    split). FSDP: the largest remaining divisible dim shards over `fsdp`
    (ZeRO-3-style parameter sharding; gathered on use). Small leaves stay
    replicated — sharding a bias buys nothing and costs collectives.
    """
    model_size = mesh.shape[MODEL_AXIS]
    fsdp_size = mesh.shape[FSDP_AXIS]

    def rule(leaf):
        shape = getattr(leaf, "shape", None)
        if (
            shape is None
            or (model_size == 1 and fsdp_size == 1)
            or np.prod(shape) < min_weight_size
        ):
            return NamedSharding(mesh, PartitionSpec())
        spec = [None] * len(shape)
        if model_size > 1 and len(shape) >= 2 and shape[-1] % model_size == 0:
            spec[-1] = MODEL_AXIS
        if fsdp_size > 1:
            _assign_largest_divisible_dim(spec, shape, fsdp_size, FSDP_AXIS)
        return NamedSharding(mesh, PartitionSpec(*spec))

    return rule


