"""Device mesh construction and sharding rules.

The trainer compiles every step against a `jax.sharding.Mesh` with named
axes; parallelism is data-parallel by default (the reference's TPUEstimator
batch-sharding + CrossShardOptimizer all-reduce, which GSPMD reproduces as
psum over the 'data' axis), with optional fsdp/model/sequence axes available
for larger networks — the axes slot into the same mesh without touching
model code.

Multi-host: `initialize_distributed()` wires jax.distributed so each host
contributes its local devices to one global mesh over ICI/DCN; the
file-based learner<->robot bus is unchanged (see export/predictors).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
SEQUENCE_AXIS = "sequence"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"

#: Minimum leaf size (elements) that earns a sharded layout. Below it a
#: leaf stays replicated: sharding a bias buys nothing and costs
#: collectives. ONE constant shared by every rule here and by the
#: planner's memory/scoring model (parallel/planner.py) — it used to be
#: repeated inline in weight_update_sharding and param_sharding, which is
#: exactly the kind of drift the planner exists to end.
MIN_WEIGHT_SIZE = 2 ** 14

#: Param-tree key under which a pipelined module stores its stacked
#: [S, ...] per-stage parameters (layers/transformer.py pipelined
#: encoder); pipe_stage_param_rule shards that subtree's dim 0 over pipe.
PIPE_STAGES_KEY = "pipe_stages"


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up over DCN. No-ops on single-process runs.

    Args default from the standard env (JAX_COORDINATOR_ADDRESS etc.), the
    JAX-native analogue of the reference's TF_CONFIG cluster plumbing
    (input_generators/default_input_generator.py:32-44).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(
    data: Optional[int] = None,
    fsdp: int = 1,
    model: int = 1,
    sequence: int = 1,
    pipe: int = 1,
    expert: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Builds a mesh over (data, fsdp, model, sequence, pipe, expert) axes.

    `data=None` absorbs all remaining devices. Axis sizes must multiply to
    the device count. Device order follows jax.devices(), which enumerates
    ICI-contiguous chips first — so the fastest-varying (model/sequence/
    pipe/expert) axes land on ICI neighbors and data-parallel all-reduce
    rides the slower links, the standard TPU layout.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = fsdp * model * sequence * pipe * expert
    if data is None:
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by "
                f"fsdp*model*sequence*pipe*expert={fixed}"
            )
        data = n // fixed
    if data * fixed != n:
        raise ValueError(
            f"Mesh {data}x{fsdp}x{model}x{sequence}x{pipe}x{expert} "
            f"!= {n} devices"
        )
    array = np.asarray(devices).reshape(
        data, fsdp, model, sequence, pipe, expert
    )
    return Mesh(
        array,
        (DATA_AXIS, FSDP_AXIS, MODEL_AXIS, SEQUENCE_AXIS, PIPE_AXIS,
         EXPERT_AXIS),
    )


#: The PartitionSpec twins of the shardings below, for callers (the
#: quantized shard_map step, the planner) that speak specs rather than
#: placed shardings. train/ code must consume these instead of spelling
#: raw PartitionSpec(...) — the sharding-outside-planner lint
#: (analysis/lints.py) enforces it.
REPLICATED_SPEC = PartitionSpec()
BATCH_SPEC = PartitionSpec((DATA_AXIS, FSDP_AXIS))
FLAT_SHARD_SPEC = PartitionSpec(DATA_AXIS)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch sharding: leading dim split over data (and fsdp, which acts as
    extra data parallelism for the input batch in fsdp regimes)."""
    return NamedSharding(mesh, BATCH_SPEC)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, REPLICATED_SPEC)


def flat_shard_sharding(mesh: Mesh) -> NamedSharding:
    """Dim-0 sharding over the data axis: the flat block-padded mirror
    layout of the quantized ZeRO-2 regime (opt state, EMA, residual)."""
    return NamedSharding(mesh, FLAT_SHARD_SPEC)


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """[K, B, ...] scan-stacked batches: scan dim replicated, batch dim
    split over data/fsdp (train/infeed.shard_stacked_batch's layout)."""
    return NamedSharding(
        mesh, PartitionSpec(None, (DATA_AXIS, FSDP_AXIS))
    )


def batch_partition_spec(mesh: Mesh, shape) -> PartitionSpec:
    """Per-leaf batch spec mirroring shard_batch's tolerance: leading dim
    divisible by the data*fsdp extent shards, everything else replicates."""
    divisor = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
    if len(shape) >= 1 and shape[0] % divisor == 0:
        return BATCH_SPEC
    return REPLICATED_SPEC


def shard_batch(batch, mesh: Mesh):
    """Places a host batch onto the mesh, leading axis split across data.

    Training batches (drop_remainder upstream) divide evenly and shard; a
    leaf whose leading dim does not divide the data axis (small predict
    batches, scalars) is replicated instead — correct, at the cost of
    redundant compute, which only ever happens off the training hot path.
    """
    sharding = data_sharding(mesh)
    replicated_sharding = replicated(mesh)
    divisor = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]

    def put(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1 and shape[0] % divisor == 0:
            return jax.device_put(leaf, sharding)
        return jax.device_put(leaf, replicated_sharding)

    return jax.tree_util.tree_map(put, batch)


def _assign_largest_divisible_dim(spec, shape, axis_size, axis_name) -> None:
    """Marks the largest still-unsharded dim divisible by axis_size with
    axis_name (in place); leaves spec untouched when none divides."""
    dims = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for dim in dims:
        if spec[dim] is None and shape[dim] % axis_size == 0:
            spec[dim] = axis_name
            return


def weight_update_sharding(
    mesh: Mesh,
    min_weight_size: int = MIN_WEIGHT_SIZE,
    axes: Tuple[str, ...] = (DATA_AXIS,),
):
    """Sharding rule for OPTIMIZER-SIDE state under replicated parameters
    (cross-replica weight-update sharding, Xu et al. arXiv:2004.13336 —
    the ZeRO-2 layout): parameters stay replicated for the forward/
    backward, but optimizer moments and the EMA mirror shard their
    largest divisible dim over the replica axes; GSPMD turns the gradient
    all-reduce into reduce-scatter + sharded update + all-gather. Cuts
    the optimizer-state footprint by the replica-group size with no
    model-side change. Leaves with no dim divisible by the group size
    stay replicated (no padding is introduced).

    axes: the mesh axes parameters are replicated over that the update
    shards across. The classic pure-DP regime is ("data",) — a single
    bare axis name in the spec, byte-for-byte today's layout. A composed
    plan (parallel/planner.py) passes every replica axis, e.g.
    ("data", "sequence") on a DP x SP x PP mesh, sharding the update
    over the PRODUCT of the replica axes — the generalization no
    hand-wired regime could spell.
    """
    axes = tuple(axes)
    group_size = int(np.prod([mesh.shape[axis] for axis in axes]))
    # A single axis keeps the bare-name spec entry (PartitionSpec("data"),
    # not PartitionSpec(("data",))) so existing layouts compare equal.
    axis_entry = axes[0] if len(axes) == 1 else axes

    def rule(leaf):
        shape = getattr(leaf, "shape", None)
        if (
            shape is None
            or group_size == 1
            or np.prod(shape) < min_weight_size
        ):
            return NamedSharding(mesh, PartitionSpec())
        spec = [None] * len(shape)
        _assign_largest_divisible_dim(spec, shape, group_size, axis_entry)
        return NamedSharding(mesh, PartitionSpec(*spec))

    return rule


def pipe_stage_param_rule(mesh: Mesh, base_rule):
    """Path-aware sharding rule layering pipeline-stage placement over a
    per-leaf base rule: any leaf under a PIPE_STAGES_KEY tree key whose
    leading dim equals the pipe-axis size shards dim 0 over `pipe` (the
    layout pipeline_apply consumes); every other leaf falls through to
    base_rule. Optimizer moments and the EMA mirror the param tree's
    keys, so the same rule places them without special cases.
    """
    pipe_size = mesh.shape[PIPE_AXIS]
    stage_sharding = NamedSharding(mesh, PartitionSpec(PIPE_AXIS))

    def rule(path, leaf):
        shape = getattr(leaf, "shape", None)
        if (
            pipe_size > 1
            and shape
            and shape[0] == pipe_size
            and any(
                getattr(entry, "key", None) == PIPE_STAGES_KEY
                for entry in path
            )
        ):
            return stage_sharding
        return base_rule(leaf)

    return rule


def param_sharding(mesh: Mesh, min_weight_size: int = MIN_WEIGHT_SIZE):
    """Tree-map-able parameter sharding rule over the fsdp and model axes.

    Tensor parallelism: matrix/conv-kernel leaves shard their OUTPUT dim
    (last axis — flax dense kernels are [in, out], conv kernels HWIO) over
    the `model` axis; GSPMD then propagates the sharding through the
    matmul and inserts the per-layer collectives (the Megatron column
    split). FSDP: the largest remaining divisible dim shards over `fsdp`
    (ZeRO-3-style parameter sharding; gathered on use). Small leaves stay
    replicated — sharding a bias buys nothing and costs collectives.
    """
    model_size = mesh.shape[MODEL_AXIS]
    fsdp_size = mesh.shape[FSDP_AXIS]

    def rule(leaf):
        shape = getattr(leaf, "shape", None)
        if (
            shape is None
            or (model_size == 1 and fsdp_size == 1)
            or np.prod(shape) < min_weight_size
        ):
            return NamedSharding(mesh, PartitionSpec())
        spec = [None] * len(shape)
        if model_size > 1 and len(shape) >= 2 and shape[-1] % model_size == 0:
            spec[-1] = MODEL_AXIS
        if fsdp_size > 1:
            _assign_largest_divisible_dim(spec, shape, fsdp_size, FSDP_AXIS)
        return NamedSharding(mesh, PartitionSpec(*spec))

    return rule


