"""Pipeline parallelism: GPipe-style microbatch scheduling over a mesh axis.

Beyond the reference (SURVEY §2.7 lists pipeline parallelism as ABSENT
there): stages live on the `pipe` mesh axis, activations move stage-to-
stage with `lax.ppermute` over ICI, and a `lax.scan` over clock ticks runs
the classic GPipe schedule — with M microbatches and S stages the scan has
M + S - 1 ticks, each device computing its stage on the microbatch
currently resident. The whole schedule is ONE jitted SPMD program: no
host-side orchestration, no per-stage processes like GPU pipeline runtimes
use; the bubble (S-1 idle ticks per device) is the standard GPipe cost and
shrinks as M grows.

Everything is differentiable (ppermute's transpose is the reverse
ppermute), so `jax.grad` through `pipeline_apply` yields pipeline-parallel
training: the backward pass streams gradients through the ring in reverse
— exactly the behavior hand-written 1F1B schedules build manually.

Usage:
    params  = [stage_init(rng_i) for i in range(S)]   # same tree per stage
    stacked = stack_stage_params(params)              # leaves [S, ...]
    stacked = jax.device_put(stacked, stage_sharding(mesh, stacked))
    out     = pipeline_apply(stage_fn, stacked, x, mesh=mesh,
                             num_microbatches=M)
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from jax.sharding import NamedSharding, PartitionSpec

from tensor2robot_tpu.parallel import collectives
from tensor2robot_tpu.parallel.collectives import shard_map
from tensor2robot_tpu.parallel.mesh import PIPE_AXIS


def stack_stage_params(stage_params: Sequence[Any]):
    """Stacks S per-stage parameter trees into one tree of [S, ...] leaves
    (the layout `pipeline_apply` consumes; shard dim 0 over the pipe axis).
    All stages must share one tree structure — a pipeline is a chain of
    identical stage programs with different weights."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *stage_params
    )


def stage_sharding(mesh, stacked_params):
    """Shardings placing stacked [S, ...] stage params dim-0 over `pipe`."""
    sharding = NamedSharding(mesh, PartitionSpec(PIPE_AXIS))
    return jax.tree_util.tree_map(lambda _: sharding, stacked_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params,
    x: jax.Array,
    *,
    mesh,
    num_microbatches: int,
    axis_name: str = PIPE_AXIS,
    batch_axis: str | None = None,
    sequence_axis: str | None = None,
):
    """Runs x through S chained stages with GPipe microbatch overlap.

    Args:
      stage_fn: (stage_params, microbatch [mb, ...]) -> [mb, ...]; applied
        by every device to its resident microbatch each tick. Input and
        output shapes must match across stages (chainable).
      stacked_params: tree of [S, ...] leaves (see stack_stage_params),
        dim 0 sharded over the pipe axis.
      x: [batch, ...] with batch divisible by num_microbatches.
      mesh: mesh whose `axis_name` axis has size S.
      num_microbatches: M; the bubble fraction is (S-1)/(M+S-1).
      batch_axis: optional mesh axis the batch is data-sharded over
        (dp x pp composition): each microbatch's example dim shards over
        it, the schedule runs on local examples, and gradients psum over
        it via shard_map's transpose. The per-microbatch size must divide
        by that axis.
      sequence_axis: optional mesh axis x's dim 1 (the sequence) is
        sharded over (sp x pp composition, the 3D DP x SP x PP regime of
        parallel/planner.py): each microbatch carries only its local
        sequence shard and stage_fn is expected to run sequence-parallel
        attention in MANUAL mode over this axis
        (ring_attention.ring_attention_manual) — the axis is manual
        inside this shard_map, so ppermute over it composes with the
        pipeline's own rotation. The sequence length must divide by the
        axis size.

    Returns [batch, ...]: the composition stage_{S-1}(...stage_0(x)),
    replicated over the pipe axis (data-sharded over batch_axis /
    sequence-sharded over sequence_axis if given).
    """
    num_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by microbatches {num_microbatches}"
        )
    micro = jnp.reshape(x, (num_microbatches, batch // num_microbatches)
                        + x.shape[1:])
    batch_entry = None
    if batch_axis is not None:
        data_size = mesh.shape[batch_axis]
        if (batch // num_microbatches) % data_size != 0:
            raise ValueError(
                f"microbatch size {batch // num_microbatches} not divisible "
                f"by {batch_axis} axis size {data_size}"
            )
        batch_entry = batch_axis
    if sequence_axis is not None:
        seq_size = mesh.shape[sequence_axis]
        if x.ndim < 2 or x.shape[1] % seq_size != 0:
            raise ValueError(
                f"sequence dim {x.shape[1] if x.ndim > 1 else None} not "
                f"divisible by {sequence_axis} axis size {seq_size}"
            )
        x_spec = PartitionSpec(None, batch_entry, sequence_axis)
    elif batch_entry is not None:
        x_spec = PartitionSpec(None, batch_entry)
    else:
        x_spec = PartitionSpec()

    spec_params = jax.tree_util.tree_map(
        lambda _: PartitionSpec(axis_name), stacked_params
    )
    shard_mapped = shard_map(
        functools.partial(
            _pipeline_shard,
            stage_fn=stage_fn,
            num_stages=num_stages,
            num_microbatches=num_microbatches,
            axis_name=axis_name,
            varying_axes=(axis_name,)
            + ((batch_axis,) if batch_axis is not None else ())
            + ((sequence_axis,) if sequence_axis is not None else ()),
        ),
        mesh=mesh,
        in_specs=(spec_params, x_spec),
        out_specs=x_spec,
        # Replication checking OFF for the pipeline program: jax's
        # varying-manual-axes tracking loses the carry annotations when
        # this shard_map's inner scan is differentiated under
        # jax.checkpoint (partial-eval extends the scan carry with
        # residual/tangent slots whose initializers are born unvarying,
        # while the body emits them varying) — "Scan carry input and
        # output got mismatched replication types", and jax's own error
        # text prescribes check_rep=False as the workaround. Correctness
        # does not lean on the static check here: tests/test_pipeline.py
        # pins forward AND gradient equality against the sequential
        # model, and tests/test_transformer_models.py pins the composed
        # remat+grad_accum step. Minimal repro of the upstream bug:
        # tests/test_pipeline.py::TestShardMapRematScanVma.
        check_rep=False,
    )
    out = shard_mapped(stacked_params, micro)
    return jnp.reshape(out, (batch,) + out.shape[2:])


def _pipeline_shard(stacked_params, micro, *, stage_fn, num_stages,
                    num_microbatches, axis_name, varying_axes=None):
    """The per-device program: scan over M+S-1 clock ticks.

    Each device sees its own stage's params ([1, ...] leaves from the pipe
    sharding) and the full (replicated) microbatch stack. Tick t: stage 0
    injects microbatch min(t, M-1) (ticks past M recompute the last
    microbatch — garbage that never reaches the output window), every
    stage applies itself to its resident activation, and ppermute shifts
    results one stage down the chain. The last stage's activation at tick
    t is microbatch t-S+1 fully composed; a masked accumulate collects it.
    """
    stage_idx = lax.axis_index(axis_name)
    local_params = jax.tree_util.tree_map(
        lambda leaf: leaf[0], stacked_params
    )
    num_ticks = num_microbatches + num_stages - 1
    mb_shape = micro.shape[1:]

    def tick(carry, t):
        resident, out_acc = carry
        # Stage 0 picks up the next microbatch; other stages keep what the
        # previous tick's shift delivered.
        inject = lax.dynamic_index_in_dim(
            micro, jnp.minimum(t, num_microbatches - 1), keepdims=False
        )
        current = jnp.where(stage_idx == 0, inject, resident)
        y = stage_fn(local_params, current)
        # The final stage's result for this tick is a finished microbatch
        # (valid once the pipeline has filled: t >= S-1).
        out_t = jnp.where(stage_idx == num_stages - 1, y, jnp.zeros_like(y))
        out_slot = jnp.clip(t - (num_stages - 1), 0, num_microbatches - 1)
        valid = (t >= num_stages - 1).astype(y.dtype)
        out_acc = lax.dynamic_update_index_in_dim(
            out_acc,
            lax.dynamic_index_in_dim(out_acc, out_slot, keepdims=False)
            + valid * out_t,
            out_slot,
            axis=0,
        )
        # Shift activations one stage down the chain (last stage's output
        # falls off the end; stage 0 gets zeros it overwrites next tick).
        shifted = collectives.ppermute(
            y,
            axis_name,
            perm=[(i, i + 1) for i in range(num_stages - 1)],
        )
        return (shifted, out_acc), None

    resident0 = jnp.zeros(mb_shape, micro.dtype)
    out0 = jnp.zeros((num_microbatches,) + mb_shape, micro.dtype)
    # The body makes the carry vary over the pipe axis (stage_idx masks,
    # ppermute) and over the batch axis when the input is data-sharded;
    # mark the initial carry the same way for shard_map's varying-manual-
    # axes tracking (guarded like ring_attention's pvary: older jax has
    # neither the tracking nor the op).
    if hasattr(lax, "pcast"):
        axes = tuple(varying_axes or (axis_name,))
        resident0, out0 = jax.tree_util.tree_map(
            lambda leaf: lax.pcast(leaf, axes, to="varying"),
            (resident0, out0),
        )
    (_, out_acc), _ = lax.scan(
        tick, (resident0, out0), jnp.arange(num_ticks)
    )
    # Only the last stage holds real outputs; the masked psum replicates
    # them to every stage (out_specs is replicated), and routes cotangents
    # back to the last stage under differentiation.
    return collectives.psum(
        jnp.where(stage_idx == num_stages - 1, out_acc,
                  jnp.zeros_like(out_acc)),
        axis_name,
    )
