"""Persistent cache for measured sharding-plan search results.

The measured tier of the planner (parallel/planner.py, T2R_PLAN=auto
with T2R_PLAN_MEASURE) pays real XLA compiles to rank its shortlist —
work that changes only when the model, the topology, or the planner
itself changes. This module remembers the winner: the second auto run
on a known (model, topology) pair performs ZERO search compiles, it
deserializes the plan the first run measured (the same economics as
the serving AOT ladder in export/aot.py, applied to the search).

Cache key, all-or-nothing (any component differing is a miss):

  * model-spec fingerprint — sha256 over the param/opt/batch treedefs +
    every leaf's (path, shape, dtype) + the spec's geometry fields;
  * device topology — platform / device_kind / device_count
    (export/aot.py device_topology);
  * jax version — measured timings and memory_analysis are not stable
    across runtimes;
  * planner schema version (PLAN_CACHE_FORMAT_VERSION) — bumped when
    the search space or ShardingPlan schema changes, so stale winners
    from a narrower search can never shadow a wider one.

Envelope (one file per fingerprint, `plan_<fp>.bin` under
T2R_PLAN_CACHE_DIR):

    [0:4]   magic b"T2RP"
    [4:8]   u32 LE: byte length of REST
    [8:12]  u32 LE: crc32 of REST
    [12:]   REST = u32 LE header length + header JSON + payload JSON
            ({"plan": ShardingPlan.to_json(), "table": [...]})

The 12-byte magic/length/crc header is the same structural shape as the
AOT/replay frames, so `analysis/corpus.py corrupt_frame_variants`
drives the corruption tests with no new generator. Integrity (magic,
exact length, CRC) is verified before the header is parsed, the key
before the payload is decoded, and the payload is JSON — never pickle.
A corrupt or mismatched entry is a typed `PlanCacheCorrupt` /
`PlanCacheKeyMismatch`: `load()` logs it and returns None (fresh
search), it is never silently trusted.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import tempfile
import zlib
from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import numpy as np

from tensor2robot_tpu import flags

__all__ = [
    "PLAN_CACHE_FORMAT_VERSION",
    "PLAN_CACHE_MAGIC",
    "MAX_PLAN_ENTRY_BYTES",
    "PlanCacheError",
    "PlanCacheCorrupt",
    "PlanCacheKeyMismatch",
    "cache_dir",
    "entry_path",
    "load",
    "model_fingerprint",
    "pack_entry",
    "store",
    "unpack_entry",
]

PLAN_CACHE_MAGIC = b"T2RP"
#: The planner schema version: bump when the factorization space or the
#: ShardingPlan schema changes — a winner chosen from a narrower search
#: must not shadow the wider one.
PLAN_CACHE_FORMAT_VERSION = 1
_HEADER_SIZE = 12  # magic + length + crc32, the corpus frame shape

#: Hard bound on a single cache entry; a forged length field must be
#: rejected before any allocation happens (corpus frame_huge_length).
#: Plans + their measured tables are small JSON — 16 MiB is generous.
MAX_PLAN_ENTRY_BYTES = 1 << 24

_LOG = logging.getLogger(__name__)


class PlanCacheError(RuntimeError):
    """Base class for plan-cache failures."""


class PlanCacheCorrupt(PlanCacheError):
    """The envelope failed integrity (magic/length/CRC/JSON): a
    truncated or bitflipped file. The caller re-runs the search."""


class PlanCacheKeyMismatch(PlanCacheError):
    """The envelope is intact but keyed for a different model, topology,
    jax version, or planner schema — its winner was ranked under
    different rules. The caller re-runs the search LOUDLY."""


def cache_dir() -> Optional[str]:
    """The cache directory in effect (T2R_PLAN_CACHE_DIR), or None when
    the cache is disabled — the default, zero-IO path."""
    return flags.get_str("T2R_PLAN_CACHE_DIR") or None


def model_fingerprint(model_spec) -> str:
    """sha256 hex over everything the search's outcome depends on from
    the model side: tree structure, every leaf's (path, shape, dtype),
    and the geometry fields the feasibility gates consult."""

    def tree_signature(tree) -> Dict[str, Any]:
        if tree is None:
            return {"treedef": None, "leaves": []}
        leaves = []
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            leaves.append(
                [
                    jax.tree_util.keystr(path),
                    None if shape is None else [int(d) for d in shape],
                    None if dtype is None else np.dtype(dtype).name,
                ]
            )
        return {
            "treedef": str(jax.tree_util.tree_structure(tree)),
            "leaves": leaves,
        }

    doc = {
        "params": tree_signature(model_spec.param_shapes),
        "opt": tree_signature(model_spec.opt_shapes),
        "batch": tree_signature(model_spec.batch_shapes),
        "has_ema": bool(model_spec.has_ema),
        "batch_size": model_spec.batch_size,
        "seq_len": model_spec.seq_len,
        "num_heads": model_spec.num_heads,
        "head_dim": model_spec.head_dim,
        "num_layers": model_spec.num_layers,
        "d_model": model_spec.d_model,
        "pipeline_capable": bool(model_spec.pipeline_capable),
    }
    blob = json.dumps(doc, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def entry_path(directory: str, fingerprint: str) -> str:
    """One file per model fingerprint; topology/jax/schema live in the
    header key, so a topology change on the same model is a LOUD typed
    mismatch rather than a silent parallel file."""
    return os.path.join(directory, f"plan_{fingerprint[:16]}.bin")


def pack_entry(
    fingerprint: str,
    payload_doc: Mapping[str, Any],
    topology: Optional[Mapping[str, Any]] = None,
    jax_version: Optional[str] = None,
    format_version: int = PLAN_CACHE_FORMAT_VERSION,
) -> bytes:
    """payload_doc ({"plan": ..., "table": ...}) -> envelope bytes."""
    from tensor2robot_tpu.export import aot

    header = {
        "format_version": int(format_version),
        "fingerprint": str(fingerprint),
        "topology": dict(
            topology if topology is not None else aot.device_topology()
        ),
        "jax": jax_version if jax_version is not None else jax.__version__,
    }
    header_bytes = json.dumps(header, sort_keys=True).encode()
    payload = json.dumps(dict(payload_doc), sort_keys=True).encode()
    rest = struct.pack("<I", len(header_bytes)) + header_bytes + payload
    return (
        PLAN_CACHE_MAGIC
        + struct.pack("<I", len(rest))
        + struct.pack("<I", zlib.crc32(rest) & 0xFFFFFFFF)
        + rest
    )


def unpack_entry(
    blob: bytes,
    expect_fingerprint: Optional[str] = None,
    expect_topology: Optional[Mapping[str, Any]] = None,
    expect_jax: Optional[str] = None,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Envelope -> (header, payload doc). Integrity first (typed
    PlanCacheCorrupt), then the full key (typed PlanCacheKeyMismatch),
    then — and only then — the payload JSON is decoded."""
    if len(blob) < _HEADER_SIZE:
        raise PlanCacheCorrupt(
            f"plan-cache entry truncated at {len(blob)} bytes"
        )
    if blob[:4] != PLAN_CACHE_MAGIC:
        raise PlanCacheCorrupt(
            f"bad magic {blob[:4]!r} (want {PLAN_CACHE_MAGIC!r})"
        )
    (length,) = struct.unpack("<I", blob[4:8])
    (crc,) = struct.unpack("<I", blob[8:12])
    if length > MAX_PLAN_ENTRY_BYTES:
        raise PlanCacheCorrupt(
            f"forged length {length} exceeds the format bound"
        )
    rest = blob[_HEADER_SIZE:]
    if len(rest) != length:
        raise PlanCacheCorrupt(
            f"length field says {length} bytes, file carries {len(rest)}"
        )
    if zlib.crc32(rest) & 0xFFFFFFFF != crc:
        raise PlanCacheCorrupt("crc mismatch: plan-cache bytes are corrupt")
    if len(rest) < 4:
        raise PlanCacheCorrupt("envelope too short for a header")
    (hlen,) = struct.unpack("<I", rest[:4])
    if hlen > len(rest) - 4:
        raise PlanCacheCorrupt(f"header length {hlen} overruns the envelope")
    try:
        header = json.loads(rest[4 : 4 + hlen].decode())
    except (UnicodeDecodeError, ValueError) as err:
        raise PlanCacheCorrupt(f"header is not JSON: {err}") from err
    if not isinstance(header, dict):
        raise PlanCacheCorrupt(f"header is {type(header).__name__}, not dict")
    _check_key(header, expect_fingerprint, expect_topology, expect_jax)
    try:
        payload = json.loads(rest[4 + hlen :].decode())
    except (UnicodeDecodeError, ValueError) as err:
        raise PlanCacheCorrupt(f"payload is not JSON: {err}") from err
    if not isinstance(payload, dict) or "plan" not in payload:
        raise PlanCacheCorrupt("payload carries no plan document")
    return header, payload


def _check_key(
    header: Mapping[str, Any],
    expect_fingerprint: Optional[str],
    expect_topology: Optional[Mapping[str, Any]],
    expect_jax: Optional[str],
) -> None:
    if header.get("format_version") != PLAN_CACHE_FORMAT_VERSION:
        raise PlanCacheKeyMismatch(
            f"planner schema {header.get('format_version')} != "
            f"{PLAN_CACHE_FORMAT_VERSION}: the entry was ranked under a "
            "different search space"
        )
    expect_jax = expect_jax if expect_jax is not None else jax.__version__
    if header.get("jax") != expect_jax:
        raise PlanCacheKeyMismatch(
            f"plan was measured under jax {header.get('jax')}, this "
            f"process runs {expect_jax} — measured costs are not stable "
            "across runtimes"
        )
    if (
        expect_fingerprint is not None
        and header.get("fingerprint") != expect_fingerprint
    ):
        raise PlanCacheKeyMismatch(
            "model fingerprint mismatch: the cached winner was searched "
            "for a different model "
            f"({header.get('fingerprint')} != {expect_fingerprint})"
        )
    if expect_topology is not None:
        got = header.get("topology") or {}
        if dict(got) != dict(expect_topology):
            raise PlanCacheKeyMismatch(
                f"device topology mismatch: plan searched on {got}, "
                f"this host is {dict(expect_topology)}"
            )


def store(
    fingerprint: str,
    payload_doc: Mapping[str, Any],
    directory: Optional[str] = None,
    topology: Optional[Mapping[str, Any]] = None,
) -> Optional[str]:
    """Writes one entry atomically (tmp + rename — a reader never sees a
    half-written envelope; the CRC catches torn storage underneath).
    Returns the path, or None when the cache is disabled."""
    directory = directory if directory is not None else cache_dir()
    if not directory:
        return None
    os.makedirs(directory, exist_ok=True)
    path = entry_path(directory, fingerprint)
    blob = pack_entry(fingerprint, payload_doc, topology=topology)
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=os.path.basename(path) + ".tmp."
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load(
    fingerprint: str,
    directory: Optional[str] = None,
    topology: Optional[Mapping[str, Any]] = None,
) -> Optional[Dict[str, Any]]:
    """Tolerant read: the payload doc on a valid hit, None on a miss OR
    any typed failure (corrupt / key mismatch — logged, never trusted).
    Strict callers (tests) use `unpack_entry` directly."""
    directory = directory if directory is not None else cache_dir()
    if not directory:
        return None
    path = entry_path(directory, fingerprint)
    try:
        with open(path, "rb") as f:
            blob = f.read(MAX_PLAN_ENTRY_BYTES + _HEADER_SIZE + 1)
    except FileNotFoundError:
        return None
    except OSError as err:
        _LOG.warning("plan cache unreadable at %s: %s", path, err)
        return None
    if topology is not None:
        expect_topology = dict(topology)
    else:
        from tensor2robot_tpu.export import aot

        expect_topology = aot.device_topology()
    try:
        _, payload = unpack_entry(
            blob,
            expect_fingerprint=fingerprint,
            expect_topology=expect_topology,
        )
    except PlanCacheError as err:
        _LOG.warning(
            "plan cache entry %s rejected (%s): %s — falling back to a "
            "fresh search",
            path,
            type(err).__name__,
            err,
        )
        return None
    return payload
