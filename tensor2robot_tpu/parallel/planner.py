"""The unified 3D sharding planner: one mesh/layout oracle for DP x SP x PP.

Before this module, every parallel regime the trainer ran was hand-wired
per call site: the pure-DP ZeRO-2 flat shard, ring/ulysses sequence
parallelism, GPipe pipeline stages, and the 2D pairs each lived as a
bespoke (mesh kwargs, CompiledModel kwargs, placement rules) triple in a
test or a bench leg. This module inverts that, the way the reference
framework's spec machinery inverted input plumbing: a model declares
*what* it is (`ModelSpec`), the harness declares *where* it runs
(`Topology`) and *how much memory it may use*, and `plan()` derives the
execution plan — mesh axes, per-leaf PartitionSpecs for params /
opt-state / EMA / residual, batch specs, and the collective schedule with
its wire-byte costs (including the quantized int8/fp8 regimes' formats).
Grounded in the MLPerf TPU-pod scaling recipe as declarative config
(arXiv:1909.09756) and automatic cross-replica sharding of the weight
update (arXiv:2004.13336), which the planner generalizes across composed
replica axes (`weight_update_axes`) — the 3D DP x SP x PP regime no hand
wiring could spell.

Contracts (pinned by tests/test_planner.py and `bench.py plan`):

  * every named preset reproduces its hand-wired regime BYTE-FOR-BYTE:
    identical per-leaf shardings (audited leaf-wise), opt-state/EMA/
    residual born sharded exactly as today, checkpoint layout unchanged,
    and the `none`-regime train step bitwise;
  * `T2R_PLAN=off` (the default) is the pre-PR path byte-for-byte — the
    trainer then consults only its explicit kwargs;
  * `plan()` enumerates valid DP x SP x PP factorizations of the device
    count, scores memory fit FIRST (estimate from the model's
    `jax.eval_shape` trees; infeasible plans are rejected with the
    estimate in the error) and estimated comm bytes second (using the
    collectives' known wire formats, incl. the int8/fp8 1-byte ratios),
    and returns the winner plus the full ranked table for the bench
    artifact.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np

from tensor2robot_tpu import flags
from tensor2robot_tpu.parallel import collectives
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MIN_WEIGHT_SIZE,
    MODEL_AXIS,
    PIPE_AXIS,
    PIPE_STAGES_KEY,
    SEQUENCE_AXIS,
    _assign_largest_divisible_dim,
)

__all__ = [
    "Constraints",
    "ModelSpec",
    "PlanError",
    "PlanResult",
    "ShardingPlan",
    "Topology",
    "audit_state_layout",
    "estimate_comm_bytes",
    "estimate_memory",
    "hand_sharded",
    "last_search",
    "measured_rerank",
    "parse_measure_setting",
    "plan",
    "preset_names",
    "resolve_plan_from_flag",
    "resolve_preset",
]


def hand_sharded(fn):
    """Allowlist marker for the `sharding-outside-planner` lint: a
    function in `train/` that legitimately constructs a raw
    NamedSharding/PartitionSpec (instead of consuming the planner's or
    mesh.py's helpers) declares itself with this decorator so the
    exemption is grep-able. No runtime effect."""
    return fn


# -- inputs -------------------------------------------------------------------


def _tree_bytes(tree) -> int:
    return int(
        sum(
            int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves(tree)
            if hasattr(leaf, "shape")
        )
    )


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """What the planner needs to know about a model: its state shapes
    (from `jax.eval_shape` — nothing materialized) plus the transformer
    geometry that decides which axes are even legal (a model without a
    sequence dimension cannot shard one).
    """

    #: pytree of jax.ShapeDtypeStruct: the params subtree.
    param_shapes: Any
    #: pytree of jax.ShapeDtypeStruct: tree-layout optimizer state.
    opt_shapes: Any = None
    #: pytree of jax.ShapeDtypeStruct: one (preprocessed) feature batch.
    batch_shapes: Any = None
    has_ema: bool = False
    batch_size: Optional[int] = None
    seq_len: Optional[int] = None
    num_heads: Optional[int] = None
    head_dim: Optional[int] = None
    num_layers: Optional[int] = None
    d_model: Optional[int] = None
    #: True when the model family can be constructed with pipeline
    #: stages (plan.model_kwargs() carries the stage count the model
    #: must be built with — the planner plans, the caller constructs).
    pipeline_capable: bool = False

    @property
    def n_params(self) -> int:
        return int(
            sum(
                int(np.prod(leaf.shape))
                for leaf in jax.tree_util.tree_leaves(self.param_shapes)
                if hasattr(leaf, "shape")
            )
        )

    @property
    def param_bytes(self) -> int:
        return _tree_bytes(self.param_shapes)

    @property
    def batch_bytes(self) -> int:
        return _tree_bytes(self.batch_shapes)

    @classmethod
    def from_model(cls, model, example_batch) -> "ModelSpec":
        """Builds the spec from a T2R model + one raw host batch via
        eval_shape (shapes only; nothing large is materialized)."""
        features, _ = model.preprocessor.preprocess(
            example_batch["features"],
            example_batch.get("labels"),
            mode="train",
            rng=jax.random.PRNGKey(0),
        )
        var_shapes = jax.eval_shape(
            lambda rng: model.init_variables(rng, features),
            jax.random.PRNGKey(0),
        )
        param_shapes = var_shapes["params"]
        optimizer = model.create_optimizer()
        opt_shapes = jax.eval_shape(optimizer.init, param_shapes)
        batch_shapes = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                getattr(x, "shape", ()), getattr(x, "dtype", np.float32)
            ),
            features,
        )
        leading = [
            leaf.shape[0]
            for leaf in jax.tree_util.tree_leaves(batch_shapes)
            if len(leaf.shape) >= 1
        ]
        num_layers = getattr(model, "_num_layers", None)
        return cls(
            param_shapes=param_shapes,
            opt_shapes=opt_shapes,
            batch_shapes=batch_shapes,
            has_ema=bool(getattr(model, "use_avg_model_params", False)),
            batch_size=leading[0] if leading else None,
            seq_len=getattr(model, "_episode_length", None),
            num_heads=getattr(model, "_num_heads", None),
            head_dim=getattr(model, "_head_dim", None),
            num_layers=num_layers,
            d_model=getattr(model, "_d_model", None),
            pipeline_capable=num_layers is not None,
        )


@dataclasses.dataclass(frozen=True)
class Topology:
    """Where the plan runs: device count and the per-device HBM budget
    (None = unbounded; `plan()` also honors T2R_PLAN_MEM_BUDGET)."""

    num_devices: int
    memory_bytes: Optional[int] = None
    kind: str = "host"

    @classmethod
    def detect(cls) -> "Topology":
        devices = jax.devices()
        return cls(num_devices=len(devices), kind=devices[0].platform)


@dataclasses.dataclass(frozen=True)
class Constraints:
    """Knobs that narrow the factorization search. Defaults reproduce the
    trainer's standing conventions."""

    allow_sp: bool = True
    allow_pp: bool = True
    #: Tensor parallelism (the fsdp param-sharding axis). The search
    #: folds it into the factorization space (ROADMAP 5(d)); a candidate
    #: with tp > 1 is feasible only when some param leaf actually shards
    #: under param_min_shard_size — tiny models reject it with the
    #: reason recorded rather than paying collectives for nothing.
    allow_tp: bool = True
    #: None reads the central T2R_COLLECTIVE_QUANT / _BLOCK flags.
    collective_quant: Optional[str] = None
    collective_block: Optional[int] = None
    shard_weight_update: bool = True
    sequence_parallel_mode: str = "ring"
    param_min_shard_size: int = MIN_WEIGHT_SIZE
    #: Crude multiplier turning one batch's bytes into a peak-activation
    #: estimate (documented in docs/PARALLELISM.md's scoring model).
    activation_multiplier: float = 8.0
    #: Pin axis sizes, e.g. {"pipe": 2}; factorizations disagreeing with
    #: a pin are skipped.
    pinned: Optional[Mapping[str, int]] = None


# -- the plan -----------------------------------------------------------------


class PlanError(ValueError):
    """No factorization satisfies the constraints/memory budget; the
    message carries the closest candidate's estimate."""


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """One executable layout: mesh axes + regime + per-leaf spec rules.

    The plan is the single source of sharding truth for a plan-driven
    trainer (`CompiledModel(plan=...)` / `T2R_PLAN`): the mesh comes from
    `build_mesh()`, the trainer kwargs from `compiled_kwargs()`, the
    model-construction kwargs from `model_kwargs()`, and
    `state_shardings()` predicts every TrainState leaf's NamedSharding —
    which `audit_state_layout` checks leaf-for-leaf against what the
    trainer actually placed (the byte-equality contract).
    """

    name: str
    data: int = 1
    fsdp: int = 1
    model: int = 1
    sequence: int = 1
    pipe: int = 1
    expert: int = 1
    shard_weight_update: bool = False
    #: Replica axes the weight update shards across (arXiv:2004.13336
    #: generalized): ("data",) is the classic ZeRO-2 regime; a 3D plan
    #: passes every axis params are replicated over, e.g.
    #: ("data", "sequence").
    weight_update_axes: Tuple[str, ...] = (DATA_AXIS,)
    collective_quant: str = "none"
    collective_block: int = 512
    param_min_shard_size: int = MIN_WEIGHT_SIZE
    sequence_parallel_mode: str = "ring"
    #: Filled by plan(): the scoring estimates for the ranked table.
    memory_bytes: Optional[int] = None
    comm_bytes: Optional[int] = None

    # - shape -
    def axes_dict(self) -> Dict[str, int]:
        return {
            DATA_AXIS: self.data,
            FSDP_AXIS: self.fsdp,
            MODEL_AXIS: self.model,
            SEQUENCE_AXIS: self.sequence,
            PIPE_AXIS: self.pipe,
            EXPERT_AXIS: self.expert,
        }

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.axes_dict().values())))

    @property
    def weight_update_group(self) -> int:
        axes = self.axes_dict()
        return int(np.prod([axes[a] for a in self.weight_update_axes]))

    def regime(self) -> str:
        """Which of the trainer's four placement regimes this plan is:
        'quant_zero2' (explicit quantized collectives on the flat shard),
        'sharded_params' (fsdp/tensor parallelism), 'zero2' (replicated
        params, sharded weight update), or 'replicated'. Mirrors — and
        after the refactor, DRIVES — CompiledModel.init_state's branch."""
        if self.collective_quant != "none":
            return "quant_zero2"
        if self.fsdp > 1 or self.model > 1:
            return "sharded_params"
        if self.shard_weight_update and self.weight_update_group > 1:
            return "zero2"
        return "replicated"

    # - construction surfaces -
    def build_mesh(self, devices=None):
        if devices is None:
            devices = jax.devices()[: self.num_devices]
        return mesh_lib.make_mesh(
            data=self.data,
            fsdp=self.fsdp,
            model=self.model,
            sequence=self.sequence,
            pipe=self.pipe,
            expert=self.expert,
            devices=devices,
        )

    def matches_mesh(self, mesh) -> bool:
        shape = dict(mesh.shape)
        return all(
            shape.get(axis, 1) == size
            for axis, size in self.axes_dict().items()
        )

    def compiled_kwargs(self) -> Dict[str, Any]:
        """CompiledModel kwargs this plan pins (authoritative: a plan-
        driven trainer takes its regime from here, not the env flags)."""
        return {
            "shard_weight_update": self.shard_weight_update,
            "weight_update_axes": self.weight_update_axes,
            "collective_quant": self.collective_quant,
            "collective_block": self.collective_block,
            "param_min_shard_size": self.param_min_shard_size,
        }

    def model_kwargs(self) -> Dict[str, Any]:
        """Model-construction kwargs for mesh-aware model families (the
        transformer models): the model must be BUILT to match the plan —
        the planner cannot retrofit pipeline stages onto a constructed
        module."""
        out: Dict[str, Any] = {}
        if self.pipe > 1:
            out["pipeline_stages"] = self.pipe
        if self.sequence > 1:
            out["sequence_parallel_mode"] = self.sequence_parallel_mode
        return out

    # - layout rules (the consolidated mesh.py plumbing) -
    def base_param_rule(self, mesh):
        """Per-leaf rule for params/variables (pre pipe layering)."""
        if self.regime() == "sharded_params":
            return mesh_lib.param_sharding(
                mesh, min_weight_size=self.param_min_shard_size
            )
        replicated = mesh_lib.replicated(mesh)
        return lambda leaf: replicated

    def weight_update_rule(self, mesh):
        """Per-leaf rule for opt-state/EMA mirrors in the zero2 regime."""
        return mesh_lib.weight_update_sharding(
            mesh,
            min_weight_size=self.param_min_shard_size,
            axes=self.weight_update_axes,
        )

    def batch_spec(self, mesh, shape):
        return mesh_lib.batch_partition_spec(mesh, shape)

    # - predictions -
    def state_shardings(self, mesh, state):
        """Predicted NamedSharding for every leaf of a TrainState, in the
        state's own structure — the oracle `audit_state_layout` compares
        the trainer's actual placements against."""
        regime = self.regime()
        replicated = mesh_lib.replicated(mesh)

        def place(tree, base_rule):
            rule = mesh_lib.pipe_stage_param_rule(mesh, base_rule)
            return jax.tree_util.tree_map_with_path(
                lambda path, leaf: rule(path, leaf), tree
            )

        if regime == "quant_zero2":
            flat = mesh_lib.flat_shard_sharding(mesh)

            def mirror(leaf):
                return replicated if getattr(leaf, "ndim", 0) == 0 else flat

            return state.replace(
                step=replicated,
                variables=jax.tree_util.tree_map(
                    lambda _: replicated, state.variables
                ),
                opt_state=jax.tree_util.tree_map(mirror, state.opt_state),
                ema_params=None if state.ema_params is None else flat,
                collective_residual=(
                    None
                    if state.collective_residual is None
                    else jax.tree_util.tree_map(
                        lambda _: flat, state.collective_residual
                    )
                ),
            )
        if regime == "sharded_params":
            return place(state, self.base_param_rule(mesh))
        base = self.base_param_rule(mesh)
        if regime == "zero2":
            wu_rule = self.weight_update_rule(mesh)
            return state.replace(
                step=replicated,
                variables=place(state.variables, base),
                opt_state=place(state.opt_state, wu_rule),
                ema_params=(
                    None
                    if state.ema_params is None
                    else place(state.ema_params, wu_rule)
                ),
                collective_residual=None,
            )
        return place(state, base)

    def collective_schedule(
        self, model_spec: Optional[ModelSpec] = None
    ) -> List[Dict[str, Any]]:
        """Which registry collectives fire on which axis each train step,
        with analytic per-device wire bytes when a ModelSpec is given
        (None otherwise). This is the attribution surface `bench.py plan`
        records — the same accounting discipline as
        collectives.wire_summary."""
        entries: List[Dict[str, Any]] = []
        n = model_spec.n_params if model_spec is not None else None
        regime = self.regime()
        if self.data > 1 or (
            regime in ("zero2", "quant_zero2")
            and self.weight_update_group > 1
        ):
            if regime == "quant_zero2":
                coll = collectives.get_collective(
                    self.collective_quant, self.collective_block
                )
                layout = (
                    collectives.FlatShardLayout(
                        n, self.data, self.collective_block
                    )
                    if n
                    else None
                )
                pre, post = (
                    collectives.wire_summary(coll, layout.padded)
                    if layout
                    else (None, None)
                )
                entries.append(
                    {
                        "site": "zero2_gradient_exchange",
                        "ops": ["reduce_scatter", "all_gather"],
                        "axes": [DATA_AXIS],
                        "collective": self.collective_quant,
                        "bytes_per_device_step": post,
                        "bytes_fp32_equivalent": pre,
                    }
                )
            elif regime == "zero2":
                entries.append(
                    {
                        "site": "zero2_gradient_exchange",
                        "ops": ["psum_scatter", "all_gather"],
                        "axes": list(self.weight_update_axes),
                        "collective": "none",
                        "bytes_per_device_step": 8 * n if n else None,
                        "bytes_fp32_equivalent": 8 * n if n else None,
                    }
                )
            else:
                entries.append(
                    {
                        "site": "gradient_all_reduce",
                        "ops": ["psum"],
                        "axes": [DATA_AXIS],
                        "collective": "none",
                        "bytes_per_device_step": 8 * n if n else None,
                        "bytes_fp32_equivalent": 8 * n if n else None,
                    }
                )
        if self.sequence > 1:
            entries.append(
                {
                    "site": (
                        "ring_kv_rotation"
                        if self.sequence_parallel_mode == "ring"
                        else "ulysses_head_scatter"
                    ),
                    "ops": (
                        ["ppermute"]
                        if self.sequence_parallel_mode == "ring"
                        else ["all_to_all"]
                    ),
                    "axes": [SEQUENCE_AXIS],
                    "collective": "none",
                    "bytes_per_device_step": _sp_bytes(self, model_spec),
                    "bytes_fp32_equivalent": _sp_bytes(self, model_spec),
                }
            )
        if self.fsdp > 1:
            entries.append(
                {
                    "site": "fsdp_param_gather",
                    "ops": ["all_gather", "reduce_scatter"],
                    "axes": [FSDP_AXIS],
                    "collective": "none",
                    "bytes_per_device_step": _tp_bytes(self, model_spec),
                    "bytes_fp32_equivalent": _tp_bytes(self, model_spec),
                }
            )
        if self.pipe > 1:
            entries.append(
                {
                    "site": "pipeline_activation_shift",
                    "ops": ["ppermute", "psum"],
                    "axes": [PIPE_AXIS],
                    "collective": "none",
                    "bytes_per_device_step": _pp_bytes(self, model_spec),
                    "bytes_fp32_equivalent": _pp_bytes(self, model_spec),
                }
            )
        return entries

    def to_json(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out["weight_update_axes"] = list(self.weight_update_axes)
        out["regime"] = self.regime()
        out["num_devices"] = self.num_devices
        return out

    @classmethod
    def from_json(cls, doc: Mapping[str, Any]) -> "ShardingPlan":
        """Inverse of to_json (drops the derived regime/num_devices
        keys): the plan-cache round trip — a cached winner deserializes
        into a plan whose to_json is byte-identical to what was stored."""
        doc = dict(doc)
        doc.pop("regime", None)
        doc.pop("num_devices", None)
        axes = doc.get("weight_update_axes")
        if axes is not None:
            doc["weight_update_axes"] = tuple(axes)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(doc) - known
        if unknown:
            raise ValueError(
                f"plan document carries unknown fields {sorted(unknown)} "
                "— a newer planner schema; bump the cache format version"
            )
        return cls(**doc)


# -- scoring ------------------------------------------------------------------


def _shard_factor(shape, group_size: int, min_size: int) -> int:
    """The shard factor weight_update_sharding would achieve on a leaf:
    group_size when some dim divides, else 1 (replicated). The spec-level
    twin of the placed rule — same _assign_largest_divisible_dim
    plumbing, usable for topologies with no local mesh to build."""
    if group_size == 1 or int(np.prod(shape)) < min_size:
        return 1
    spec: List[Optional[str]] = [None] * len(shape)
    _assign_largest_divisible_dim(spec, shape, group_size, "_probe")
    return group_size if any(entry is not None for entry in spec) else 1


def _param_shard_factor(shape, sharding_plan: "ShardingPlan") -> int:
    """The divide factor param_sharding (mesh.py) achieves on one leaf
    under the plan's model/fsdp axes: the spec-level twin of the placed
    rule, so memory estimates for sharded_params plans track the layout
    the trainer will actually place."""
    if int(np.prod(shape)) < sharding_plan.param_min_shard_size:
        return 1
    factor = 1
    spec: List[Optional[str]] = [None] * len(shape)
    if (
        sharding_plan.model > 1
        and len(shape) >= 2
        and shape[-1] % sharding_plan.model == 0
    ):
        spec[-1] = MODEL_AXIS
        factor *= sharding_plan.model
    if sharding_plan.fsdp > 1:
        before = list(spec)
        _assign_largest_divisible_dim(
            spec, shape, sharding_plan.fsdp, FSDP_AXIS
        )
        if spec != before:
            factor *= sharding_plan.fsdp
    return factor


def _is_pipe_stage_path(path, shape, pipe: int) -> bool:
    return (
        pipe > 1
        and len(shape) >= 1
        and shape[0] == pipe
        and any(getattr(entry, "key", None) == PIPE_STAGES_KEY for entry in path)
    )


def _tree_bytes_per_device(tree, sharding_plan: "ShardingPlan",
                           shard_mirrors: bool) -> int:
    """Per-device bytes of a state tree under the plan's placement:
    pipe-stage leaves divide by the pipe axis; (when shard_mirrors) every
    other large-enough leaf divides by the weight-update group."""
    total = 0.0
    regime = sharding_plan.regime()
    group = (
        sharding_plan.weight_update_group
        if shard_mirrors and regime in ("zero2", "quant_zero2")
        else 1
    )
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        leaf_bytes = int(np.prod(shape)) * np.dtype(leaf.dtype).itemsize
        if _is_pipe_stage_path(path, shape, sharding_plan.pipe):
            total += leaf_bytes / sharding_plan.pipe
        elif regime == "sharded_params":
            # Params AND their opt/EMA mirrors follow base_param_rule
            # under this regime (CompiledModel.init_state places them
            # with the same per-leaf rule).
            total += leaf_bytes / _param_shard_factor(shape, sharding_plan)
        else:
            total += leaf_bytes / _shard_factor(
                shape, group, sharding_plan.param_min_shard_size
            )
    return int(total)


def estimate_memory(
    model_spec: ModelSpec,
    sharding_plan: ShardingPlan,
    activation_multiplier: float = 8.0,
) -> Dict[str, int]:
    """Analytic per-device memory estimate (bytes) from the eval_shape
    trees: replicated params + a transient gradient copy + the
    optimizer/EMA mirrors under the plan's sharding + an activation term
    (batch bytes scaled by `activation_multiplier`, divided across the
    batch/sequence shards). Deliberately coarse — its job is RANKING
    factorizations and rejecting clear non-fits, not byte-accurate HBM
    accounting."""
    params = _tree_bytes_per_device(
        model_spec.param_shapes, sharding_plan, shard_mirrors=False
    )
    grads = params
    if sharding_plan.regime() == "quant_zero2":
        layout = collectives.FlatShardLayout(
            max(model_spec.n_params, 1),
            sharding_plan.data,
            sharding_plan.collective_block,
        )
        # mu + nu on the flat padded shard, plus the grad/update residual.
        opt = 2 * 4 * layout.shard_len
        ema = 4 * layout.shard_len if model_spec.has_ema else 0
        opt += 2 * 4 * layout.shard_len  # collective residual entries
    else:
        opt = (
            _tree_bytes_per_device(
                model_spec.opt_shapes, sharding_plan, shard_mirrors=True
            )
            if model_spec.opt_shapes is not None
            else 2 * params
        )
        ema = (
            _tree_bytes_per_device(
                model_spec.param_shapes, sharding_plan, shard_mirrors=True
            )
            if model_spec.has_ema
            else 0
        )
    batch_shards = sharding_plan.data * sharding_plan.fsdp
    seq_shards = sharding_plan.sequence
    activations = int(
        model_spec.batch_bytes * activation_multiplier
        / max(batch_shards * seq_shards, 1)
    )
    total = params + grads + opt + ema + activations
    return {
        "params": params,
        "grads": grads,
        "opt_state": opt,
        "ema": ema,
        "activations": activations,
        "total": total,
    }


def _sp_bytes(sharding_plan: ShardingPlan,
              model_spec: Optional[ModelSpec]) -> Optional[int]:
    """Per-device per-step sequence-parallel bytes: the ring rotates K and
    V (4-byte elements) through sp hops per layer, forward + backward
    (~2x); ulysses moves Q/K/V + the output through one all_to_all round."""
    if model_spec is None or sharding_plan.sequence <= 1:
        return None
    ms = model_spec
    if None in (ms.batch_size, ms.seq_len, ms.num_heads, ms.head_dim,
                ms.num_layers):
        return None
    local_batch = max(ms.batch_size // max(sharding_plan.data, 1), 1)
    local_seq = ms.seq_len // sharding_plan.sequence
    tile = local_batch * local_seq * ms.num_heads * ms.head_dim * 4
    if sharding_plan.sequence_parallel_mode == "ulysses":
        # 4 tensors through one all_to_all each, fwd + bwd.
        return int(ms.num_layers * 2 * 4 * tile)
    hops = sharding_plan.sequence
    return int(ms.num_layers * 2 * 2 * tile * hops)


def _pp_bytes(sharding_plan: ShardingPlan,
              model_spec: Optional[ModelSpec]) -> Optional[int]:
    """Per-device per-step pipeline bytes: one activation microbatch
    shifted per tick over M + S - 1 ticks (M defaulting to 2S, the ~33%%
    bubble policy), forward + backward."""
    if model_spec is None or sharding_plan.pipe <= 1:
        return None
    ms = model_spec
    if None in (ms.batch_size, ms.seq_len, ms.d_model):
        return None
    stages = sharding_plan.pipe
    local_batch = max(ms.batch_size // max(sharding_plan.data, 1), 1)
    micro = min(2 * stages, local_batch)
    ticks = micro + stages - 1
    mb = max(local_batch // micro, 1)
    local_seq = ms.seq_len // max(sharding_plan.sequence, 1)
    act = mb * local_seq * ms.d_model * 4
    return int(2 * ticks * act)


def _tp_bytes(sharding_plan: ShardingPlan,
              model_spec: Optional[ModelSpec]) -> Optional[int]:
    """Per-device per-step tensor-parallel (fsdp param-sharding) bytes:
    the ZeRO-3 pattern pays an all-gather of the sharded params for the
    forward, another for the backward, and a reduce-scatter of the
    gradients — ~3 full param volumes scaled by the (tp-1)/tp ring
    fraction. Coarse on purpose: it ranks tp against dp's 8n gradient
    exchange, it does not model overlap."""
    if model_spec is None or sharding_plan.fsdp <= 1:
        return None
    n = model_spec.n_params
    tp = sharding_plan.fsdp
    return int(3 * 4 * n * (tp - 1) / tp)


def estimate_comm_bytes(
    model_spec: ModelSpec, sharding_plan: ShardingPlan
) -> Dict[str, Optional[int]]:
    """Per-device per-step comm estimate by axis, from the collectives'
    wire formats (the quantized regimes count their true 1-byte payloads
    + per-block scales via wire_summary)."""
    n = model_spec.n_params
    dp_bytes: Optional[int] = 0
    regime = sharding_plan.regime()
    if regime == "quant_zero2":
        coll = collectives.get_collective(
            sharding_plan.collective_quant, sharding_plan.collective_block
        )
        layout = collectives.FlatShardLayout(
            max(n, 1), sharding_plan.data, sharding_plan.collective_block
        )
        dp_bytes = collectives.wire_summary(coll, layout.padded)[1]
    elif regime == "zero2" or sharding_plan.data > 1:
        dp_bytes = 8 * n if sharding_plan.weight_update_group > 1 or \
            sharding_plan.data > 1 else 0
    sp = _sp_bytes(sharding_plan, model_spec) or 0
    pp = _pp_bytes(sharding_plan, model_spec) or 0
    tp = _tp_bytes(sharding_plan, model_spec) or 0
    total = (dp_bytes or 0) + sp + pp + tp
    return {
        "data": dp_bytes or 0,
        "sequence": sp,
        "pipe": pp,
        "fsdp": tp,
        "total": total,
    }


# -- the search ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanResult:
    best: ShardingPlan
    #: Every candidate factorization, ranked: feasible plans first by
    #: (comm bytes, memory), then infeasible ones with their rejection
    #: reasons — the table `bench.py plan` records.
    table: Tuple[Dict[str, Any], ...]

    def to_json(self) -> Dict[str, Any]:
        return {"best": self.best.to_json(), "table": list(self.table)}


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def plan(
    model_spec: ModelSpec,
    topology: Topology,
    memory_budget: Optional[int] = None,
    constraints: Optional[Constraints] = None,
) -> PlanResult:
    """Enumerates DP x SP x PP x TP factorizations of the device count,
    scores them (memory fit first, then estimated comm bytes), and
    returns the winner plus the ranked table. This is the ANALYTIC tier;
    `measured_rerank` re-ranks a shortlist on compiled/measured cost and
    `resolve_plan_from_flag` wires both behind T2R_PLAN=auto with the
    persistent plan cache (parallel/plan_cache.py) in front.

    memory_budget: per-device bytes; None falls back to
    topology.memory_bytes, then the T2R_PLAN_MEM_BUDGET flag (MB; 0 =
    unbounded). Raises PlanError — with the closest candidate's estimate
    in the message — when nothing fits.
    """
    constraints = constraints or Constraints()
    n = topology.num_devices
    budget = memory_budget
    if budget is None:
        budget = topology.memory_bytes
    if budget is None:
        budget_mb = flags.get_int("T2R_PLAN_MEM_BUDGET")
        budget = budget_mb << 20 if budget_mb > 0 else None
    quant = (
        constraints.collective_quant
        if constraints.collective_quant is not None
        else flags.get_enum("T2R_COLLECTIVE_QUANT")
    )
    block = (
        constraints.collective_block
        if constraints.collective_block is not None
        else flags.get_int("T2R_COLLECTIVE_BLOCK")
    )
    pinned = dict(constraints.pinned or {})

    tp_shardable = [
        leaf.shape
        for leaf in jax.tree_util.tree_leaves(model_spec.param_shapes)
        if hasattr(leaf, "shape")
    ]

    entries: List[Dict[str, Any]] = []
    candidates: List[Tuple[Tuple[int, int], ShardingPlan, Dict[str, Any]]] = []
    for tp in _divisors(n):
        for sp in _divisors(n // tp):
            for pp in _divisors(n // (tp * sp)):
                dp = n // (tp * sp * pp)
                axes = {
                    DATA_AXIS: dp,
                    FSDP_AXIS: tp,
                    SEQUENCE_AXIS: sp,
                    PIPE_AXIS: pp,
                }
                if any(axes.get(a, 1) != s for a, s in pinned.items()):
                    continue
                reasons: List[str] = []
                if sp > 1:
                    if not constraints.allow_sp:
                        reasons.append("sequence parallelism disallowed")
                    elif model_spec.seq_len is None:
                        reasons.append(
                            "model declares no sequence dimension"
                        )
                    elif model_spec.seq_len % sp:
                        reasons.append(
                            f"seq_len {model_spec.seq_len} % sp {sp} != 0"
                        )
                    elif (
                        constraints.sequence_parallel_mode == "ulysses"
                        and (model_spec.num_heads or 0) % sp
                    ):
                        reasons.append(
                            f"heads {model_spec.num_heads} % sp {sp} != 0"
                        )
                if pp > 1:
                    if not constraints.allow_pp:
                        reasons.append("pipeline parallelism disallowed")
                    elif not model_spec.pipeline_capable:
                        reasons.append("model is not pipeline-capable")
                    elif (model_spec.num_layers or 0) % pp:
                        reasons.append(
                            f"num_layers {model_spec.num_layers} % pp "
                            f"{pp} != 0"
                        )
                if tp > 1:
                    # The fsdp (tensor-parallel) axis: params shard via
                    # mesh.param_sharding. Probe the spec's leaves with
                    # the same rule the trainer will place — a model
                    # whose every leaf stays replicated under tp gains
                    # nothing and the point is rejected with the reason.
                    probe = dataclasses.replace(
                        ShardingPlan(name="_probe", fsdp=tp),
                        param_min_shard_size=(
                            constraints.param_min_shard_size
                        ),
                    )
                    if not constraints.allow_tp:
                        reasons.append("tensor parallelism disallowed")
                    elif pp > 1:
                        reasons.append(
                            "tp x pp does not compose (stacked pipeline "
                            "stage params under param_sharding is "
                            "unvalidated)"
                        )
                    elif not any(
                        _param_shard_factor(shape, probe) > 1
                        for shape in tp_shardable
                    ):
                        reasons.append(
                            f"no param leaf >= "
                            f"{constraints.param_min_shard_size} elements "
                            f"with a dim divisible by tp {tp}"
                        )
                batch_shards = dp * tp
                if (
                    batch_shards > 1
                    and model_spec.batch_size is not None
                    and model_spec.batch_size % batch_shards
                ):
                    reasons.append(
                        f"batch {model_spec.batch_size} % (dp {dp} x tp "
                        f"{tp}) != 0"
                        if tp > 1
                        else f"batch {model_spec.batch_size} % dp {dp} != 0"
                    )
                wu_axes = tuple(
                    axis
                    for axis, size in ((DATA_AXIS, dp), (SEQUENCE_AXIS, sp))
                    if size > 1
                ) or (DATA_AXIS,)
                pure_dp = sp == 1 and pp == 1 and tp == 1
                name = f"dp{dp}_sp{sp}_pp{pp}"
                if tp > 1:
                    name += f"_tp{tp}"
                candidate = ShardingPlan(
                    name=name,
                    data=dp,
                    fsdp=tp,
                    sequence=sp,
                    pipe=pp,
                    shard_weight_update=constraints.shard_weight_update,
                    weight_update_axes=wu_axes,
                    collective_quant=(
                        quant
                        if (
                            quant != "none"
                            and pure_dp
                            and dp > 1
                            and constraints.shard_weight_update
                        )
                        else "none"
                    ),
                    collective_block=block,
                    param_min_shard_size=constraints.param_min_shard_size,
                    sequence_parallel_mode=(
                        constraints.sequence_parallel_mode
                    ),
                )
                memory = estimate_memory(
                    model_spec, candidate,
                    activation_multiplier=constraints.activation_multiplier,
                )
                comm = estimate_comm_bytes(model_spec, candidate)
                if budget is not None and memory["total"] > budget:
                    reasons.append(
                        f"memory estimate {memory['total']} B/device "
                        f"exceeds budget {budget} B"
                    )
                candidate = dataclasses.replace(
                    candidate,
                    memory_bytes=memory["total"],
                    comm_bytes=comm["total"],
                )
                entry = {
                    "plan": candidate.to_json(),
                    "memory": memory,
                    "comm": comm,
                    "feasible": not reasons,
                    "reasons": reasons,
                }
                entries.append(entry)
                if not reasons:
                    candidates.append(
                        ((comm["total"], memory["total"]), candidate, entry)
                    )

    entries.sort(
        key=lambda e: (
            not e["feasible"],
            e["comm"]["total"],
            e["memory"]["total"],
        )
    )
    if not candidates:
        closest = min(entries, key=lambda e: e["memory"]["total"], default=None)
        detail = (
            f"; closest candidate {closest['plan']['name']} needs "
            f"{closest['memory']['total']} B/device "
            f"(budget {budget} B): {closest['reasons']}"
            if closest
            else ""
        )
        raise PlanError(
            f"no feasible DP x SP x PP x TP factorization of {n} devices "
            f"under the given constraints/memory budget{detail}"
        )
    candidates.sort(key=lambda item: item[0])
    return PlanResult(best=candidates[0][1], table=tuple(entries))


# -- presets: the hand-wired regimes, named ----------------------------------

# Each preset pins the EXACT configuration a hand-wired call site used
# before the planner existed (meshes from the tests/bench legs on the
# 8-device host mesh); the byte-equality suite holds planner output equal
# to the hand-wired layout leaf-for-leaf. DP-family presets scale their
# data axis to the device count; composed presets keep their pinned
# shapes.
_PRESETS: Dict[str, Dict[str, Any]] = {
    "dp": {},
    "dp_zero2": {"shard_weight_update": True},
    "dp_zero2_fp16": {
        "shard_weight_update": True, "collective_quant": "fp16",
    },
    "dp_zero2_int8": {
        "shard_weight_update": True, "collective_quant": "int8",
    },
    "dp_zero2_fp8_e4m3": {
        "shard_weight_update": True, "collective_quant": "fp8_e4m3",
    },
    "dp_zero2_fp8_e5m2": {
        "shard_weight_update": True, "collective_quant": "fp8_e5m2",
    },
    "sp_ring": {"data": 1, "sequence": 8},
    "sp_ulysses": {
        "data": 1, "sequence": 8, "sequence_parallel_mode": "ulysses",
    },
    "pp": {"data": 1, "pipe": 2},
    "dp_sp": {"data": 2, "sequence": 4},
    "dp_pp": {"data": 2, "pipe": 2},
    "dp_pp_zero2": {"data": 2, "pipe": 2, "shard_weight_update": True},
    # The 3D regime no hand-wired site could spell: DP x SP x PP with the
    # weight update sharded across BOTH replica axes.
    "dp_sp_pp": {
        "data": 2,
        "sequence": 2,
        "pipe": 2,
        "shard_weight_update": True,
        "weight_update_axes": (DATA_AXIS, SEQUENCE_AXIS),
    },
}


def preset_names() -> Tuple[str, ...]:
    return tuple(sorted(_PRESETS))


def resolve_preset(
    name: str, num_devices: Optional[int] = None
) -> ShardingPlan:
    """A named plan for one hand-wired regime. DP-family presets (no
    explicit axes) absorb the device count into `data`; composed presets
    keep their pinned shapes (their build_mesh takes a device prefix,
    exactly as the hand-wired tests did)."""
    spec = _PRESETS.get(name)
    if spec is None:
        raise KeyError(
            f"unknown plan preset {name!r}; available presets: "
            f"{', '.join(preset_names())} (selected by T2R_PLAN; 'auto' "
            "runs the factorization search, 'off' keeps the hand-wired "
            "path)"
        )
    spec = dict(spec)
    if "data" not in spec and "sequence" not in spec and "pipe" not in spec:
        spec["data"] = (
            num_devices if num_devices is not None else len(jax.devices())
        )
    return ShardingPlan(name=name, **spec)


def parse_measure_setting(setting: str) -> Optional[int]:
    """T2R_PLAN_MEASURE: 'off' -> None (analytic ranking only);
    'shortlist-N' -> N, the number of top analytic candidates the
    measured tier compiles and times. Anything else is a loud error —
    a typo must not silently fall back to the cheap tier."""
    setting = (setting or "off").strip()
    if setting == "off":
        return None
    if setting.startswith("shortlist-"):
        try:
            n = int(setting[len("shortlist-"):])
        except ValueError:
            n = 0
        if n >= 1:
            return n
    raise ValueError(
        f"T2R_PLAN_MEASURE={setting!r}: expected 'off' or 'shortlist-N' "
        "with N >= 1 (e.g. shortlist-4)"
    )


#: Stats of the most recent resolve_plan_from_flag search — the audit
#: surface bench/tests read to prove a warm cache run compiled nothing.
_LAST_SEARCH: Dict[str, Any] = {}


def last_search() -> Dict[str, Any]:
    """A copy of the most recent auto-search's stats: {'source':
    'cache'|'analytic'|'measured', 'probe_compiles': int, 'fingerprint',
    'plan', 'measured': [...]} (empty before any auto run)."""
    return dict(_LAST_SEARCH)


def measured_rerank(
    model,
    example_batch,
    result: PlanResult,
    *,
    shortlist: int,
    steps: int = 3,
    memory_budget: Optional[int] = None,
) -> Tuple[PlanResult, Dict[str, Any]]:
    """Tier 1 -> tier 2: compiles the top `shortlist` feasible analytic
    candidates' train steps (train_eval.measure_plan_candidate — compile
    cache bypassed, donated buffers, post-warmup median of `steps` real
    steps) and re-ranks on measured step time, with measured memory fit
    as a hard gate. Each probed table entry gains a 'measured' record
    including the analytic-vs-measured memory error (the pruning-quality
    audit). Plans the given model cannot run (pipe/sequence mismatch)
    are skipped with the reason recorded; when nothing measures, the
    analytic winner stands."""
    from tensor2robot_tpu.train import train_eval as train_eval_lib

    probed: List[Tuple[float, ShardingPlan, Dict[str, Any]]] = []
    shortlisted = [e for e in result.table if e["feasible"]][:shortlist]
    for rank, entry in enumerate(shortlisted):
        candidate = ShardingPlan.from_json(entry["plan"])
        probe = train_eval_lib.measure_plan_candidate(
            model, candidate, example_batch, steps=steps
        )
        probe["analytic_rank"] = rank
        measured_total = probe.get("memory_per_device_bytes")
        if measured_total:
            analytic_total = entry["memory"]["total"]
            probe["analytic_memory_error"] = {
                "analytic_total": analytic_total,
                "measured_total": measured_total,
                "ratio": analytic_total / measured_total,
            }
        if (
            memory_budget is not None
            and measured_total
            and measured_total > memory_budget
        ):
            probe["memory_fit"] = False
        else:
            probe["memory_fit"] = probe.get("step_time_ms") is not None
        entry["measured"] = probe
        if probe["memory_fit"] and probe.get("step_time_ms") is not None:
            probed.append((probe["step_time_ms"], candidate, entry))
    stats: Dict[str, Any] = {
        "shortlist": len(shortlisted),
        "measured": [
            {
                "name": entry["plan"]["name"],
                "step_time_ms": entry["measured"].get("step_time_ms"),
                "skipped": entry["measured"].get("skipped"),
                "analytic_rank": entry["measured"]["analytic_rank"],
            }
            for entry in shortlisted
        ],
    }
    if not probed:
        return result, stats
    probed.sort(key=lambda item: item[0])
    best = probed[0][1]
    for measured_rank, (_, _, entry) in enumerate(probed):
        entry["measured"]["measured_rank"] = measured_rank
    stats["winner"] = best.name
    return PlanResult(best=best, table=result.table), stats


def _auto_search(model, example_batch) -> ShardingPlan:
    """The three-tier T2R_PLAN=auto pipeline: persistent cache ->
    analytic enumeration -> optional measured re-rank, with the winner
    (and its table) written back to the cache so the NEXT run on this
    (model, topology, jax, schema) key performs zero search compiles."""
    from tensor2robot_tpu.parallel import plan_cache

    global _LAST_SEARCH
    model_spec = ModelSpec.from_model(model, example_batch)
    directory = plan_cache.cache_dir()
    stats: Dict[str, Any] = {
        "setting": "auto",
        "cache_dir": directory,
        "probe_compiles": 0,
        "fingerprint": None,
    }
    fingerprint = None
    if directory:
        fingerprint = plan_cache.model_fingerprint(model_spec)
        stats["fingerprint"] = fingerprint
        payload = plan_cache.load(fingerprint, directory)
        if payload is not None:
            best = ShardingPlan.from_json(payload["plan"])
            stats.update(source="cache", plan=best.name)
            _LAST_SEARCH = stats
            return best
    from tensor2robot_tpu.train import train_eval as train_eval_lib

    compiles_before = train_eval_lib.plan_probe_compile_count()
    result = plan(model_spec, Topology.detect())
    stats.update(source="analytic", plan=result.best.name)
    shortlist = parse_measure_setting(flags.get_str("T2R_PLAN_MEASURE"))
    if shortlist:
        steps = flags.get_int("T2R_PLAN_MEASURE_STEPS")
        budget_mb = flags.get_int("T2R_PLAN_MEM_BUDGET")
        result, measured_stats = measured_rerank(
            model,
            example_batch,
            result,
            shortlist=shortlist,
            steps=steps,
            memory_budget=budget_mb << 20 if budget_mb > 0 else None,
        )
        stats.update(
            source="measured",
            plan=result.best.name,
            measured=measured_stats,
        )
    stats["probe_compiles"] = (
        train_eval_lib.plan_probe_compile_count() - compiles_before
    )
    if directory and fingerprint:
        plan_cache.store(
            fingerprint,
            {"plan": result.best.to_json(), "table": list(result.table)},
            directory,
        )
        stats["stored"] = True
    _LAST_SEARCH = stats
    return result.best


def resolve_plan_from_flag(
    model=None, example_batch=None
) -> Optional[ShardingPlan]:
    """The T2R_PLAN gate: 'off' (default) -> None (the hand-wired path,
    byte-for-byte); a preset name -> that plan; 'auto' -> the three-tier
    search against the live device topology (requires model +
    example_batch for the ModelSpec): plan-cache hit -> analytic
    enumeration -> T2R_PLAN_MEASURE compiled/timed re-rank, winner
    persisted under T2R_PLAN_CACHE_DIR."""
    setting = flags.get_str("T2R_PLAN") or "off"
    if setting == "off":
        return None
    if setting == "auto":
        if model is None or example_batch is None:
            raise ValueError(
                "T2R_PLAN=auto needs a model and an example batch to "
                "build the ModelSpec the search scores against"
            )
        return _auto_search(model, example_batch)
    return resolve_preset(setting)


# -- the audit ----------------------------------------------------------------


def _path_str(path) -> str:
    return jax.tree_util.keystr(path)


def audit_state_layout(
    sharding_plan: ShardingPlan, mesh, state
) -> Dict[str, Any]:
    """Leaf-for-leaf byte-equality audit: every placed TrainState leaf's
    actual sharding must be equivalent to the plan's prediction. Returns
    {'leaves': N, 'mismatches': [...]}; an empty mismatch list IS the
    layout-equality certificate the presets/bench gate on."""
    predicted = sharding_plan.state_shardings(mesh, state)
    checked = 0
    mismatches: List[Dict[str, str]] = []

    def compare(path, leaf, expect):
        nonlocal checked
        actual = getattr(leaf, "sharding", None)
        if actual is None or expect is None:
            return
        checked += 1
        ndim = getattr(leaf, "ndim", 0)
        if not actual.is_equivalent_to(expect, ndim):
            mismatches.append(
                {
                    "path": _path_str(path),
                    "actual": str(actual),
                    "expected": str(expect),
                }
            )

    jax.tree_util.tree_map_with_path(compare, state, predicted)
    return {"leaves": checked, "mismatches": mismatches}
