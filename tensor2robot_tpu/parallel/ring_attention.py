"""Ring attention: sequence/context parallelism over the device mesh.

Long-context support beyond anything in the reference (SURVEY §5 notes the
reference's sequences are ~40 steps with no CP): Q/K/V are sharded along
the sequence axis of the mesh; each device keeps its Q shard resident while
K/V shards rotate around the ring via `ppermute` over ICI neighbors, and
attention accumulates with the online-softmax (flash) recurrence — memory
per device stays O(seq/devices), and the K/V transfer for step i+1 overlaps
the compute of step i (XLA schedules the ppermute DMA concurrently with the
einsums). Causal masking is block-structured: whole blocks strictly in the
future are skipped analytically via masking (Liu et al., arXiv:2310.01889).

Layout: [batch, seq, heads, dim], seq sharded over the mesh's 'sequence'
axis. With a single-device sequence axis this degrades to plain (flash)
attention — the sequence length lives in the specs, so a CP mesh axis
slots in without touching model code (SURVEY §5 long-context row).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensor2robot_tpu.parallel import collectives

from tensor2robot_tpu.ops.flash_attention import reference_attention
from tensor2robot_tpu.parallel.mesh import SEQUENCE_AXIS

_NEG_INF = -1e30


def _mark_varying(tree, axis_name):
    """Marks device-local accumulators varying over the ring axis for
    shard_map's vma tracking (no-op on jax without the tracking)."""
    if hasattr(lax, "pcast"):
        return jax.tree_util.tree_map(
            lambda leaf: lax.pcast(leaf, (axis_name,), to="varying"), tree
        )
    if hasattr(lax, "pvary"):  # pragma: no cover - pre-pcast jax
        return lax.pvary(tree, (axis_name,))
    return tree  # pragma: no cover - jax without vma tracking


def _ring_hops(axis_size: int, block: int, causal: bool,
               window: Optional[int]) -> int:
    """Compute hops the ring actually needs. Visibility of the block
    arriving at hop i depends only on i (src = me - i uniformly), so with
    a causal window W over per-device shards of length B, every hop past
    floor((W + B - 2) / B) delivers a fully-masked tile on EVERY device —
    the ring truncates to that many hops, device-uniformly."""
    if not causal or window is None:
        return axis_size
    return min(axis_size, (window + block - 2) // block + 1)


def _block_attend(q, k_blk, v_blk, q_offset, k_offset, scale, causal,
                  window=None):
    """One (q-shard x k-block) tile: returns (o_partial, row_sum, row_max)
    in the online-softmax decomposition."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k_blk.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [B, H, Sq]
    p = jnp.exp(s - m[..., None])
    # Fully-masked tiles: zero contribution, not exp(0)=1 garbage.
    p = jnp.where((m == _NEG_INF)[..., None], 0.0, p)
    l = jnp.sum(p, axis=-1)  # [B, H, Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk)
    return o, l, m


def _ring_shard_fn(
    q, k, v, *, axis_name: str, causal: bool, scale: float,
    axis_size: int, use_flash: bool = False, interpret: bool = False,
    return_lse: bool = False, window: Optional[int] = None,
):
    """Per-device body: q is resident; k/v circulate the ring.

    axis_size is static (the mesh is known at trace time), so the ring is
    unrolled: XLA schedules each hop's ppermute DMA against the next hop's
    compute without a loop counter in the way.
    """
    my_index = lax.axis_index(axis_name)
    block = q.shape[1]
    q_offset = my_index * block

    batch, _, heads, dim = q.shape
    o_acc = jnp.zeros(q.shape, jnp.float32)
    l_acc = jnp.zeros((batch, heads, block), jnp.float32)
    m_acc = jnp.full((batch, heads, block), _NEG_INF, jnp.float32)
    # Mark the device-local accumulators as varying over the ring axis:
    # shard_map's vma tracking (when check_vma is on, the reference path)
    # requires them to match the axis-index-dependent tile updates they
    # accumulate.
    o_acc, l_acc, m_acc = _mark_varying((o_acc, l_acc, m_acc), axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(i, carry):
        o_acc, l_acc, m_acc, k_blk, v_blk = carry
        # Block i arrived from the device i hops ring-upstream.
        src_index = lax.rem(my_index - i + axis_size, axis_size)
        if use_flash:
            # Pallas flash tile: the per-hop hot op, no [Sq, Sk] logits in
            # HBM (ops/flash_attention.py).
            from tensor2robot_tpu.ops.flash_attention import flash_attention_tile

            o_blk, l_blk, m_blk = flash_attention_tile(
                q, k_blk, v_blk, causal=causal, scale=scale,
                q_offset=q_offset, k_offset=src_index * block,
                interpret=interpret, vma=(axis_name,), window=window,
            )
        else:
            o_blk, l_blk, m_blk = _block_attend(
                q, k_blk, v_blk, q_offset, src_index * block, scale, causal,
                window,
            )
        # Online-softmax merge of the new tile into the running state.
        m_new = jnp.maximum(m_acc, m_blk)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_blk - m_new)
        l_new = l_acc * alpha + l_blk * beta
        o_new = (
            o_acc * jnp.transpose(alpha, (0, 2, 1))[..., None]
            + o_blk.astype(jnp.float32)
            * jnp.transpose(beta, (0, 2, 1))[..., None]
        )
        # Rotate K/V to the next device; XLA overlaps this DMA with the
        # next iteration's einsums.
        k_next = collectives.ppermute(k_blk, axis_name, perm)
        v_next = collectives.ppermute(v_blk, axis_name, perm)
        return o_new, l_new, m_new, k_next, v_next

    carry = (o_acc, l_acc, m_acc, k, v)
    # Static unroll — axis_size is mesh shape; a causal window truncates
    # the rotation to the hops whose tiles are not fully masked.
    for i in range(_ring_hops(axis_size, block, causal, window)):
        carry = body(i, carry)
    o_acc, l_acc, m_acc, _, _ = carry
    l_acc = jnp.maximum(l_acc, 1e-30)
    out = o_acc / jnp.transpose(l_acc, (0, 2, 1))[..., None]
    if return_lse:
        # Global log-sum-exp per row: the backward ring's residual.
        return out.astype(q.dtype), m_acc + jnp.log(l_acc)
    return out.astype(q.dtype)


def ring_attention_manual(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQUENCE_AXIS,
    axis_size: int,
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Ring attention INSIDE an enclosing shard_map (manual mode).

    `ring_attention` below builds its own shard_map; a caller already
    running under one — the pipelined encoder's per-device program, where
    the pipe axis owns the outer shard_map and the sequence axis is also
    manual — cannot nest another. This entry point runs the same
    per-device ring body directly on the LOCAL shards: q/k/v are
    [batch_local, seq/axis_size, heads, dim], the rotation rides
    collectives.ppermute over `axis_name`, and causal masking uses global
    positions derived from lax.axis_index. It is the piece that makes
    DP x SP x PP composable (parallel/planner.py's 3D plans); the XLA
    einsum tile is used per hop (the flash-kernel path stays on the
    shard_map-owning entry points).
    """
    if q.ndim != 4:
        raise ValueError(f"Expected [B, S_local, H, D], got {q.shape}")
    from tensor2robot_tpu.ops.flash_attention import _check_window

    _check_window(window, causal)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _ring_shard_fn(
        q, k, v, axis_name=axis_name, causal=causal, scale=scale,
        axis_size=axis_size, use_flash=False, interpret=False,
        window=window,
    )


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = SEQUENCE_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Sequence-parallel attention over `mesh`'s `axis_name`.

    Args:
      q, k, v: [batch, seq, heads, dim]; seq must divide evenly by the
        sequence-axis size.
      mesh: the device mesh (axes from parallel.mesh.make_mesh).
      axis_name: mesh axis carrying the sequence shards.
      causal: apply causal masking over *global* positions.
      scale: logit scale; defaults to dim ** -0.5.
      use_flash: per-hop tiles via the Pallas flash kernel
        (ops/flash_attention.py). Default (None): the XLA einsum path,
        matching the single-device dispatch policy (BENCH_FLASH_r03
        measured the Pallas kernel at 0.7% of peak vs the XLA path's
        win on-chip), auto-switching to flash when the per-hop LOCAL
        length S/N reaches ops.flash_attention.FLASH_AUTO_SEQ — past
        that the [S/N, S/N] logit shards are the O(S^2) memory hazard
        flash's O(S) tiles avoid. Pass True/False to force either path
        (tools/validate_flash_tpu.py re-evaluates the default).
      interpret: run the Pallas kernel in interpreter mode (tests on CPU).
      window: causal sliding window W in GLOBAL positions. Besides the
        per-tile masking, the ring itself truncates: only
        ceil((W + B - 2) / B) + 1-ish hops of the rotation carry visible
        tiles (B = per-device shard), so a bounded window makes ring cost
        independent of the TOTAL context length.

    Returns:
      [batch, seq, heads, dim] attention output, sequence-sharded like q.
    """
    if q.ndim != 4:
        raise ValueError(f"Expected [B, S, H, D], got {q.shape}")
    from tensor2robot_tpu.ops.flash_attention import _check_window

    _check_window(window, causal)
    axis_size = mesh.shape[axis_name]
    if q.shape[1] % axis_size != 0:
        raise ValueError(
            f"Sequence length {q.shape[1]} must be divisible by the "
            f"{axis_name!r} axis size {axis_size}."
        )
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if use_flash is None:
        # One dispatch policy everywhere (VERDICT r4 item 4): the XLA
        # einsum path by default exactly as in single-device attention
        # (layers/transformer.py), on the same r3 on-chip evidence —
        # switching to flash tiles when the per-hop LOCAL length crosses
        # FLASH_AUTO_SEQ, where the einsum path's [S/N, S/N] logit
        # shards become the same O(S^2) memory hazard the single-device
        # threshold guards. interpret=True still selects the
        # (interpreted) kernel so CPU tests exercise what an opt-in TPU
        # run compiles.
        from tensor2robot_tpu.ops.flash_attention import FLASH_AUTO_SEQ

        local_seq = q.shape[1] // axis_size
        use_flash = interpret or local_seq >= FLASH_AUTO_SEQ
        if use_flash:
            # Per-device shard lengths must admit a viable kernel block;
            # otherwise quietly keep the einsum path (an explicit
            # use_flash=True with bad shapes raises in the tile instead).
            from tensor2robot_tpu.ops.flash_attention import _pick_block

            local = q.shape[1] // axis_size
            if _pick_block(local, 128) is None:
                use_flash = False
    if use_flash:
        return _ring_flash(
            q, k, v, mesh, axis_name, causal, scale, interpret, window
        )
    return _ring_call(
        q, k, v, mesh, axis_name, causal, scale, False, False, window=window
    )


def _ring_call(q, k, v, mesh, axis_name, causal, scale, use_flash, interpret,
               return_lse=False, window=None):
    axis_size = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)
    extra = {}
    if use_flash:
        # Pallas kernels inside shard_map trip the varying-manual-axes
        # checker (jax recommends check_vma=False as the workaround); the
        # reference path keeps full checking.
        extra["check_vma"] = False
    fn = collectives.shard_map(
        functools.partial(
            _ring_shard_fn, axis_name=axis_name, causal=causal, scale=scale,
            axis_size=axis_size, use_flash=use_flash, interpret=interpret,
            return_lse=return_lse, window=window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, P(None, None, axis_name)) if return_lse else spec,
        **extra,
    )
    return fn(q, k, v)


def _ring_bwd_shard_fn(
    q, k, v, dout, out, lse, *, axis_name: str, causal: bool, scale: float,
    axis_size: int, interpret: bool, window: Optional[int] = None,
):
    """Backward ring: dq accumulates on the q-owner; dk/dv contributions
    RIDE THE RING with their k/v blocks, so after the full rotation each
    block arrives home carrying every device's contribution (the ring
    formulation of the FlashAttention-2 backward; per hop, the two Pallas
    backward kernels recompute this tile's probabilities from the global
    row stats)."""
    from tensor2robot_tpu.ops.flash_attention import (
        flash_attention_bwd_delta,
        flash_attention_bwd_tile,
    )

    my_index = lax.axis_index(axis_name)
    block = q.shape[1]
    q_offset = my_index * block
    delta = flash_attention_bwd_delta(dout, out)  # [B, H, Sq_local]

    dq_acc = jnp.zeros(q.shape, jnp.float32)
    dk_travel = jnp.zeros(k.shape, jnp.float32)
    dv_travel = jnp.zeros(v.shape, jnp.float32)
    dq_acc, dk_travel, dv_travel = _mark_varying(
        (dq_acc, dk_travel, dv_travel), axis_name
    )
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    hops = _ring_hops(axis_size, block, causal, window)
    carry = (dq_acc, dk_travel, dv_travel, k, v)
    for i in range(hops):  # static unroll, as in the forward ring
        dq_acc, dk_travel, dv_travel, k_blk, v_blk = carry
        src_index = lax.rem(my_index - i + axis_size, axis_size)
        dq_t, dk_t, dv_t = flash_attention_bwd_tile(
            q, k_blk, v_blk, dout, lse, delta,
            causal=causal, scale=scale,
            q_offset=q_offset, k_offset=src_index * block,
            interpret=interpret, vma=(axis_name,), window=window,
        )
        dq_acc = dq_acc + dq_t
        dk_travel = dk_travel + dk_t
        dv_travel = dv_travel + dv_t
        # Rotate the block AND its accumulated gradient together; the
        # final rotation delivers them back to the block's owner.
        k_blk, v_blk, dk_travel, dv_travel = (
            collectives.ppermute(t, axis_name, perm)
            for t in (k_blk, v_blk, dk_travel, dv_travel)
        )
        carry = (dq_acc, dk_travel, dv_travel, k_blk, v_blk)
    dq_acc, dk_travel, dv_travel, _, _ = carry
    if hops < axis_size:
        # A truncated rotation leaves each traveling gradient `hops` shifts
        # from home; one ppermute with the remaining shift delivers it.
        home = [(j, (j + axis_size - hops) % axis_size)
                for j in range(axis_size)]
        dk_travel = collectives.ppermute(dk_travel, axis_name, home)
        dv_travel = collectives.ppermute(dv_travel, axis_name, home)
    return (
        dq_acc.astype(q.dtype),
        dk_travel.astype(k.dtype),
        dv_travel.astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, mesh, axis_name, causal, scale, interpret, window):
    """Flash-tile ring forward with a flash ring BACKWARD: pallas_call has
    no autodiff rule, so the custom vjp runs a second ring whose hops are
    the FlashAttention-2 backward kernels (flash_attention_bwd_tile) —
    O(seq/devices * dim) memory in both directions."""
    return _ring_call(
        q, k, v, mesh, axis_name, causal, scale, True, interpret,
        window=window,
    )


def _ring_flash_fwd(q, k, v, mesh, axis_name, causal, scale, interpret,
                    window):
    out, lse = _ring_call(
        q, k, v, mesh, axis_name, causal, scale, True, interpret,
        return_lse=True, window=window,
    )
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(mesh, axis_name, causal, scale, interpret, window,
                    residuals, g):
    q, k, v, out, lse = residuals
    axis_size = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)
    lse_spec = P(None, None, axis_name)
    fn = collectives.shard_map(
        functools.partial(
            _ring_bwd_shard_fn, axis_name=axis_name, causal=causal,
            scale=scale, axis_size=axis_size, interpret=interpret,
            window=window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, lse_spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )
    return fn(q, k, v, g, out, lse)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)
