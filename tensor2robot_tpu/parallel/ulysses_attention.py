"""Ulysses-style all-to-all sequence parallelism (DeepSpeed-Ulysses).

The second context-parallel strategy next to parallel/ring_attention.py:
instead of rotating K/V around a ring (N hops, compute overlapped with
ppermute DMAs), TWO all_to_all collectives re-shard the problem so each
device computes FULL attention for a subset of heads:

    [B, S/N, H, D]  --all_to_all-->  [B, S, H/N, D]
    full (flash) attention per local head group
    [B, S, H/N, D]  --all_to_all-->  [B, S/N, H, D]

Trade-off vs the ring: one collective round instead of N hops (better
when the per-hop compute is too small to hide a ppermute), but it
requires heads % N == 0 and moves Q as well as K/V. Per-device memory is
O(S * H/N * D) — linear in global sequence length over the head shard,
vs the ring's O(S/N * H * D); both avoid S^2 logits via the flash
kernel. Gradients flow through all_to_all natively (its transpose is the
inverse all_to_all), so no custom vjp is needed — including through the
flash kernel path, whose custom vjp runs the Pallas backward per head
group.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensor2robot_tpu.parallel import collectives

from tensor2robot_tpu.ops.flash_attention import (
    flash_attention,
    reference_attention,
)
from tensor2robot_tpu.parallel.mesh import SEQUENCE_AXIS


def _ulysses_shard_fn(
    q, k, v, *, axis_name: str, causal: bool, scale: float,
    use_flash: bool, interpret: bool, window=None,
):
    """Per-device body: seq-sharded in, seq-sharded out.

    all_to_all splits the heads axis across devices and concatenates the
    sequence axis, giving each device the FULL sequence for H/N heads;
    attention is then entirely local (no masking subtleties — global
    positions are contiguous here, unlike ring hops).
    """
    # [B, S/N, H, D] -> [B, S, H/N, D]: scatter heads (axis 2), gather
    # sequence (axis 1).
    def scatter_heads(x):
        return collectives.all_to_all(
            x, axis_name, 2, 1, tiled=True
        )

    def gather_heads(x):
        return collectives.all_to_all(
            x, axis_name, 1, 2, tiled=True
        )

    q_local = scatter_heads(q)
    k_local = scatter_heads(k)
    v_local = scatter_heads(v)
    if use_flash:
        out = flash_attention(
            q_local, k_local, v_local, causal=causal, scale=scale,
            interpret=interpret, window=window,
        )
    else:
        # Sequence-parallel heads are never eligible for the serving
        # contraction override (export/serve_quant.py) — suppress it so
        # the local attention computes the exact reference contraction
        # regardless of any ambient lowering context.
        from tensor2robot_tpu.ops.flash_attention import (
            attention_contraction_override,
        )

        with attention_contraction_override(None):
            out = reference_attention(
                q_local, k_local, v_local, causal=causal, scale=scale,
                window=window,
            )
    return gather_heads(out)


def ulysses_attention_manual(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQUENCE_AXIS,
    axis_size: int,
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Ulysses attention INSIDE an enclosing shard_map (manual mode).

    `ulysses_attention` below builds its own shard_map; a caller already
    running under one — the pipelined encoder's per-device program, where
    the pipe axis owns the outer shard_map and the sequence axis is also
    manual — cannot nest another. This entry point runs the same
    per-device head-scatter body directly on the LOCAL shards: q/k/v are
    [batch_local, seq/axis_size, heads, dim], the two all_to_all rounds
    ride collectives.all_to_all over `axis_name`, and local attention is
    the exact reference contraction over the full gathered sequence.
    The ring twin is ring_attention.ring_attention_manual — together
    they make BOTH context-parallel strategies composable with pipeline
    parallelism (parallel/planner.py's widened factorization space); the
    XLA einsum tile is used locally (the flash-kernel path stays on the
    shard_map-owning entry points).
    """
    if q.ndim != 4:
        raise ValueError(f"Expected [B, S_local, H, D], got {q.shape}")
    from tensor2robot_tpu.ops.flash_attention import _check_window

    _check_window(window, causal)
    heads = q.shape[2]
    if heads % axis_size != 0:
        raise ValueError(
            f"Ulysses all-to-all needs heads ({heads}) divisible by the "
            f"{axis_name!r} axis size ({axis_size}); use "
            "ring_attention_manual for head counts that do not split."
        )
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _ulysses_shard_fn(
        q, k, v, axis_name=axis_name, causal=causal, scale=scale,
        use_flash=False, interpret=False, window=window,
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = SEQUENCE_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Sequence-parallel attention via head-scatter all_to_all.

    Same contract as ring_attention: q/k/v are [batch, seq, heads, dim]
    with seq sharded over `axis_name`; returns the seq-sharded output.
    Requires seq % axis_size == 0 AND heads % axis_size == 0 (each device
    owns whole heads after the scatter).
    """
    if q.ndim != 4:
        raise ValueError(f"Expected [B, S, H, D], got {q.shape}")
    from tensor2robot_tpu.ops.flash_attention import _check_window

    _check_window(window, causal)
    axis_size = mesh.shape[axis_name]
    _, seq, heads, _ = q.shape
    if seq % axis_size != 0:
        raise ValueError(
            f"Sequence length {seq} must be divisible by the "
            f"{axis_name!r} axis size {axis_size}."
        )
    if heads % axis_size != 0:
        raise ValueError(
            f"Ulysses all-to-all needs heads ({heads}) divisible by the "
            f"{axis_name!r} axis size ({axis_size}); use ring_attention "
            "for head counts that do not split."
        )
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if use_flash is None:
        # Same dispatch policy as ring_attention and single-device
        # attention (VERDICT r4 item 4): XLA einsum path by default on
        # the r3 on-chip evidence, flash above the auto threshold.
        # Ulysses' local attention runs over the FULL sequence (heads
        # are what the all_to_all splits), so the threshold compares
        # the full length; interpret=True keeps the kernel exercised
        # in CPU tests; True opts back in.
        from tensor2robot_tpu.ops.flash_attention import FLASH_AUTO_SEQ

        use_flash = interpret or seq >= FLASH_AUTO_SEQ
    spec = P(None, axis_name, None, None)
    extra = {}
    if use_flash:
        # Pallas kernels inside shard_map trip the varying-manual-axes
        # checker; the einsum path keeps full checking (as in
        # ring_attention._ring_call).
        extra["check_vma"] = False
    fn = collectives.shard_map(
        functools.partial(
            _ulysses_shard_fn, axis_name=axis_name, causal=causal,
            scale=scale, use_flash=use_flash, interpret=interpret,
            window=window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **extra,
    )
    return fn(q, k, v)
