"""Policies: predictor -> action glue for robot control loops."""

from tensor2robot_tpu.policies.policies import (
    CEMPolicy,
    JitCEMPolicy,
    LSTMCEMPolicy,
    OUExploreRegressionPolicy,
    PerEpisodeSwitchPolicy,
    Policy,
    RegressionPolicy,
    ScheduledExplorationRegressionPolicy,
    SequentialRegressionPolicy,
    default_pack_fn,
)
