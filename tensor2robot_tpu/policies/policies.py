"""Policy zoo: the on-robot glue between predictors and environments.

A Policy wraps a predictor (exported model or checkpoint) and turns
observations into actions at control rates. Parity with the reference
policies/policies.py:34-365:

  Policy                      restore/init delegation + sample_action
  CEMPolicy                   CEM argmax over a critic's q_predicted
  JitCEMPolicy                + the whole CEM loop jitted (beyond ref)
  LSTMCEMPolicy               + recurrent hidden-state carry
  RegressionPolicy            regression model's inference_output as action
  SequentialRegressionPolicy  + observation-history stacking
  OUExploreRegressionPolicy   + Ornstein-Uhlenbeck exploration noise
  ScheduledExplorationRegressionPolicy  + linearly-decayed Gaussian noise
  PerEpisodeSwitchPolicy      explore-vs-greedy choice per episode
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, Mapping, Optional

import numpy as np

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.specs import TensorSpecStruct, flatten_spec_structure
from tensor2robot_tpu.utils.cross_entropy import CrossEntropyMethod


def default_pack_fn(state, context, timestep) -> Dict[str, Any]:
    """Maps an observation onto predictor features: mappings pass through
    flattened; a bare array binds to the spec's single feature key."""
    del context, timestep
    if isinstance(state, (Mapping, TensorSpecStruct)):
        return {k: np.asarray(v) for k, v in flatten_spec_structure(state).items()}
    return {"__single__": np.asarray(state)}


class Policy(abc.ABC):
    """Base policy over a predictor (reference policies.py:34-103)."""

    def __init__(
        self,
        predictor: AbstractPredictor,
        pack_fn: Optional[Callable] = None,
    ):
        self._predictor = predictor
        self._pack_fn = pack_fn or default_pack_fn
        self._rng = np.random.RandomState()

    def seed(self, seed: int) -> None:
        self._rng = np.random.RandomState(seed)

    @property
    def predictor(self) -> AbstractPredictor:
        return self._predictor

    @property
    def global_step(self) -> int:
        return self._predictor.global_step

    def restore(self, is_async: bool = False) -> bool:
        return self._predictor.restore(is_async=is_async)

    def init_randomly(self) -> None:
        self._predictor.init_randomly()

    def close(self) -> None:
        self._predictor.close()

    def reset(self) -> None:
        """Per-episode reset hook (hidden state, noise processes, ...)."""

    def _pack(self, state, context, timestep) -> Dict[str, Any]:
        features = self._pack_fn(state, context, timestep)
        if "__single__" in features:
            spec = flatten_spec_structure(
                self._predictor.get_feature_specification()
            )
            keys = list(spec.keys())
            if len(keys) != 1:
                raise ValueError(
                    "A bare-array observation needs a single-feature spec or "
                    f"a custom pack_fn; spec has keys {keys}."
                )
            features = {keys[0]: features["__single__"]}
        return features

    @abc.abstractmethod
    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        """Returns the action for one (unbatched) observation."""

    def sample_action(self, obs, explore_prob: float = 0.0):
        """dql-compat interface returning (action, debug_dict). explore_prob
        is ignored here exactly as in the reference base policy
        (policies.py:88-103); exploration variants override."""
        del explore_prob
        return self.SelectAction(obs), {}


@configurable("CEMPolicy")
class CEMPolicy(Policy):
    """CEM argmax over a critic predictor's `q_predicted`
    (reference policies.py:107-185).

    The predictor was exported with an action-population dim
    (`action_batch_size`), so each CEM iteration is ONE batched forward
    pass over the whole population — the tiling contract of
    CriticModel.get_feature_specification(PREDICT).
    """

    def __init__(
        self,
        predictor: AbstractPredictor,
        action_size: int,
        cem_iterations: int = 3,
        cem_samples: int = 64,
        elite_fraction: float = 0.1,
        action_low: float = -1.0,
        action_high: float = 1.0,
        action_key: str = "action",
        q_key: str = "q_predicted",
        pack_fn: Optional[Callable] = None,
        seed: Optional[int] = None,
    ):
        super().__init__(predictor, pack_fn)
        self._action_size = action_size
        self._low, self._high = action_low, action_high
        self._action_key = action_key
        self._resolved_action_leaves = None
        self._q_key = q_key

        def sample_clipped(mean, stddev, n, rng):
            samples = rng.normal(
                loc=mean[None, ...],
                scale=stddev[None, ...],
                size=(n,) + mean.shape,
            )
            # Clip BEFORE scoring so elites are refit on the same actions the
            # critic scored; otherwise the proposal mean can drift outside
            # [low, high] and never recover.
            return np.clip(samples, action_low, action_high)

        self._cem_samples = cem_samples
        self._cem_iterations = cem_iterations
        self._elite_fraction = elite_fraction
        self._seed = seed
        self._cem = CrossEntropyMethod(
            sample_fn=sample_clipped,
            num_samples=cem_samples,
            num_iterations=cem_iterations,
            elite_fraction=elite_fraction,
            seed=seed,
        )

    def _resolve_action_leaves(self):
        """All action leaves under the action key, IN SPEC ORDER, with their
        trailing dims: [(leaf_key, size), ...]. A multi-part action spec
        (e.g. QT-Opt's 7 named components) is optimized as one flat
        [sum(sizes)] CEM vector that the objective splits back per leaf;
        SelectAction returns that flat vector in the same spec order.
        Cached — the spec is only available after the predictor restores."""
        if self._resolved_action_leaves is not None:
            return self._resolved_action_leaves
        spec = flatten_spec_structure(self._predictor.get_feature_specification())
        if self._action_key in list(spec.keys()):  # leaf keys only: `in spec`
            leaves = [self._action_key]
        else:
            prefix = self._action_key + "/"
            leaves = [k for k in spec.keys() if k.startswith(prefix)]
        if not leaves:
            raise ValueError(
                f"Cannot resolve action key {self._action_key!r} in spec "
                f"keys {sorted(spec.keys())}."
            )
        def leaf_size(key):
            # The trailing dim is the leaf's action size both with and
            # without the CEM population dim (tiling prepends it:
            # [size] -> [population, size]). The one ambiguous layout —
            # a SCALAR action leaf exported WITH a population (shape
            # [population]) — cannot be told apart from a vector leaf;
            # it has no in-repo producer and surfaces as the explicit
            # size-sum mismatch below rather than silent misbehavior.
            shape = tuple(spec[key].shape)
            return int(shape[-1]) if shape else 1

        resolved = [(key, leaf_size(key)) for key in leaves]
        total = sum(size for _, size in resolved)
        if total != self._action_size:
            raise ValueError(
                f"Action leaves {resolved} sum to {total} dims but "
                f"action_size={self._action_size}."
            )
        self._resolved_action_leaves = resolved
        return resolved

    @staticmethod
    def _split_action(xp, samples, leaves):
        """Splits a flat [..., sum(sizes)] action along its last dim into
        {leaf_key: [..., size]} in spec order (numpy or jnp via `xp`)."""
        parts = {}
        offset = 0
        for key, size in leaves:
            parts[key] = xp.asarray(samples[..., offset:offset + size])
            offset += size
        return parts

    def _objective_fn(self, features: Dict[str, Any]) -> Callable:
        leaves = self._resolve_action_leaves()

        def objective(samples: np.ndarray) -> np.ndarray:
            n = samples.shape[0]
            actions = np.clip(samples, self._low, self._high).astype(np.float32)
            batch = {
                key: np.asarray(value)[None, ...]
                for key, value in features.items()
            }
            for key, part in self._split_action(np, actions, leaves).items():
                batch[key] = part[None, ...]  # [1, n, leaf_size]
            out = self._predictor.predict(batch)
            q = np.asarray(out[self._q_key]).reshape(-1)
            if q.shape[0] != n:
                raise ValueError(
                    f"Critic returned {q.shape[0]} Q values for population {n}; "
                    "was the model exported with action_batch_size "
                    f"= {n}?"
                )
            return q

        return objective

    def get_cem_action(self, features: Dict[str, Any]) -> np.ndarray:
        # Seed the proposal at the center of the valid action box; mean=0 is
        # wrong for asymmetric [low, high] bounds.
        mean = np.full(
            (self._action_size,), (self._low + self._high) / 2.0, np.float64
        )
        stddev = np.full((self._action_size,), (self._high - self._low) / 2.0)
        _, _, best, _ = self._cem.run(self._objective_fn(features), mean, stddev)
        return np.clip(best, self._low, self._high).astype(np.float32)

    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        features = self._pack(state, context, timestep)
        return self.get_cem_action(features)


@configurable("JitCEMPolicy")
class JitCEMPolicy(CEMPolicy):
    """CEM with the ENTIRE sample/score/refit loop jitted around the
    exported model's traced StableHLO call (ops/cem.py): one program
    dispatch per action selection instead of one predictor round-trip per
    CEM iteration. Beyond the reference (its CEM is host numpy,
    policies.py:107-185) — possible here because exports rehydrate as jax
    callables. Falls back to the numpy engine for predictors without a
    loaded StableHLO export (checkpoint predictors, random-init serving).
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        import jax

        self._jit_key = jax.random.PRNGKey(
            0 if self._seed is None else self._seed
        )
        self._jit_select = None
        self._jit_source = None  # the ExportedModel the jit was built for

    def seed(self, seed: int) -> None:
        super().seed(seed)
        import jax

        self._jit_key = jax.random.PRNGKey(seed)
        # Keep the numpy fallback engine in the same seeding contract.
        self._cem._rng = np.random.RandomState(seed)

    def _maybe_build_jit(self, loaded) -> None:
        if self._jit_source is loaded:
            return
        import jax
        import jax.numpy as jnp

        from tensor2robot_tpu.ops import cem as cem_ops

        leaves = self._resolve_action_leaves()
        low, high = self._low, self._high
        action_size = self._action_size
        q_key = self._q_key

        num_samples = self._cem_samples
        # Fail fast with the deployment recipe instead of a rank-mismatch
        # error from deep inside the traced export: the jitted engine
        # scores the whole population in ONE critic call, so the export's
        # action leaves must carry the population dim.
        spec = flatten_spec_structure(
            self._predictor.get_feature_specification()
        )
        for leaf_key, _ in leaves:
            shape = tuple(spec[leaf_key].shape)
            if not shape or int(shape[0]) != num_samples:
                raise ValueError(
                    f"JitCEMPolicy needs the export's action leaf "
                    f"{leaf_key!r} to carry the CEM population as its "
                    f"leading dim: spec shape {shape}, expected "
                    f"({num_samples}, ...). Re-export the serving model "
                    f"with action_batch_size={num_samples} "
                    "(docs/SERVING.md), or use CEMPolicy (numpy engine)."
                )

        def select(flat_features, key):
            def objective(samples):
                batch = {
                    k: jnp.asarray(v)[None, ...]
                    for k, v in flat_features.items()
                }
                for leaf_key, part in self._split_action(
                    jnp, samples, leaves
                ).items():
                    batch[leaf_key] = part[None, ...]
                out = loaded.traced_predict(batch)
                q = jnp.reshape(out[q_key], (-1,))
                # Shapes are static at trace time: catch a critic/export
                # population mismatch exactly like the numpy objective
                # (an out-of-bounds top_k gather would silently clamp).
                if q.shape[0] != num_samples:
                    raise ValueError(
                        f"Critic returned {q.shape[0]} Q values for "
                        f"population {num_samples}; was the model exported "
                        f"with action_batch_size = {num_samples}?"
                    )
                return q

            mean = jnp.full((action_size,), (low + high) / 2.0, jnp.float32)
            stddev = jnp.full((action_size,), (high - low) / 2.0, jnp.float32)
            _, _, best, best_q = cem_ops.cross_entropy_maximize(
                objective,
                mean,
                stddev,
                key,
                num_samples=self._cem_samples,
                num_iterations=self._cem_iterations,
                elite_fraction=self._elite_fraction,
                low=low,
                high=high,
            )
            return jnp.clip(best, low, high), best_q

        self._jit_select = jax.jit(select)
        self._jit_source = loaded

    def get_cem_action(self, features: Dict[str, Any]) -> np.ndarray:
        import jax

        loaded = getattr(self._predictor, "loaded_model", None)
        if loaded is None or not getattr(loaded, "has_stablehlo", False):
            return super().get_cem_action(features)
        self._maybe_build_jit(loaded)
        self._jit_key, key = jax.random.split(self._jit_key)
        flat = {k: np.asarray(v) for k, v in features.items()}
        best, _ = self._jit_select(flat, key)
        return np.asarray(jax.device_get(best), np.float32)


@configurable("LSTMCEMPolicy")
class LSTMCEMPolicy(CEMPolicy):
    """CEM over a recurrent critic: carries hidden state between steps via
    the predictor's `state_output` -> `state_input` keys
    (reference policies.py:189-219)."""

    def __init__(self, *args, state_input_key: str = "state_input",
                 state_output_key: str = "state_output", **kwargs):
        super().__init__(*args, **kwargs)
        self._state_input_key = state_input_key
        self._state_output_key = state_output_key
        self._hidden = None

    def reset(self) -> None:
        self._hidden = None

    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        features = self._pack(state, context, timestep)
        if self._hidden is not None:
            features[self._state_input_key] = self._hidden
        action = self.get_cem_action(features)
        # One more pass to advance the recurrent state with the chosen action,
        # fed under the same per-leaf keys the CEM objective used.
        batch = {k: np.asarray(v)[None, ...] for k, v in features.items()}
        for key, part in self._split_action(
            np, action, self._resolve_action_leaves()
        ).items():
            batch[key] = part[None, None, ...]
        out = self._predictor.predict(batch)
        if self._state_output_key in out:
            self._hidden = np.asarray(out[self._state_output_key])[0]
        return action


@configurable("RegressionPolicy")
class RegressionPolicy(Policy):
    """Action = regression model's `inference_output`
    (reference policies.py:223-238)."""

    def __init__(
        self,
        predictor: AbstractPredictor,
        action_key: str = "inference_output",
        pack_fn: Optional[Callable] = None,
    ):
        super().__init__(predictor, pack_fn)
        self._action_key = action_key

    def _predict_action(self, features: Dict[str, Any]) -> np.ndarray:
        batch = {k: np.asarray(v)[None, ...] for k, v in features.items()}
        out = self._predictor.predict(batch)
        action = np.asarray(out[self._action_key])[0]
        return action

    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        return self._predict_action(self._pack(state, context, timestep))


@configurable("SequentialRegressionPolicy")
class SequentialRegressionPolicy(RegressionPolicy):
    """Stacks the last `history_length` observations into a leading time dim
    before prediction (reference policies.py:241-256)."""

    def __init__(self, *args, history_length: int = 4, **kwargs):
        super().__init__(*args, **kwargs)
        self._history_length = history_length
        self._history: list = []

    def reset(self) -> None:
        self._history = []

    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        features = self._pack(state, context, timestep)
        self._history.append(features)
        if len(self._history) > self._history_length:
            self._history.pop(0)
        padded = [self._history[0]] * (
            self._history_length - len(self._history)
        ) + self._history
        stacked = {
            key: np.stack([f[key] for f in padded], axis=0)
            for key in padded[0]
        }
        return self._predict_action(stacked)


@configurable("OUExploreRegressionPolicy")
class OUExploreRegressionPolicy(RegressionPolicy):
    """Adds Ornstein-Uhlenbeck temporally-correlated exploration noise
    (reference policies.py:259-292)."""

    def __init__(self, *args, theta: float = 0.15, sigma: float = 0.2,
                 action_low: float = -1.0, action_high: float = 1.0, **kwargs):
        super().__init__(*args, **kwargs)
        self._theta, self._sigma = theta, sigma
        self._low, self._high = action_low, action_high
        self._noise: Optional[np.ndarray] = None

    def reset(self) -> None:
        self._noise = None

    def _ou_step(self, shape) -> np.ndarray:
        if self._noise is None:
            self._noise = np.zeros(shape)
        self._noise = (
            self._noise
            - self._theta * self._noise
            + self._sigma * self._rng.normal(size=shape)
        )
        return self._noise

    def sample_action(self, obs, explore_prob: float = 0.0):
        action = self.SelectAction(obs)
        if self._rng.uniform() < explore_prob:
            action = np.clip(
                action + self._ou_step(action.shape), self._low, self._high
            ).astype(action.dtype)
        return action, {"ou_noise": self._noise}


@configurable("ScheduledExplorationRegressionPolicy")
class ScheduledExplorationRegressionPolicy(RegressionPolicy):
    """Gaussian exploration with stddev decayed linearly over global_step
    (reference policies.py:296-321)."""

    def __init__(self, *args, initial_stddev: float = 0.2,
                 final_stddev: float = 0.0, decay_steps: int = 10000,
                 action_low: float = -1.0, action_high: float = 1.0, **kwargs):
        super().__init__(*args, **kwargs)
        self._initial, self._final = initial_stddev, final_stddev
        self._decay_steps = decay_steps
        self._low, self._high = action_low, action_high

    def current_stddev(self) -> float:
        step = max(self.global_step, 0)
        frac = min(step / max(self._decay_steps, 1), 1.0)
        return self._initial + (self._final - self._initial) * frac

    def sample_action(self, obs, explore_prob: float = 0.0):
        del explore_prob  # The schedule, not the caller, owns exploration.
        action = self.SelectAction(obs)
        stddev = self.current_stddev()
        noisy = np.clip(
            action + self._rng.normal(scale=stddev, size=action.shape),
            self._low,
            self._high,
        ).astype(action.dtype)
        return noisy, {"stddev": stddev}


@configurable("PerEpisodeSwitchPolicy")
class PerEpisodeSwitchPolicy(Policy):
    """Chooses the explore or the greedy policy once per episode
    (reference policies.py:325-365)."""

    def __init__(
        self,
        explore_policy: Policy,
        greedy_policy: Policy,
        explore_prob: float = 0.0,
    ):
        # Delegates predictor ops to the greedy policy's predictor. The
        # explore probability is owned by the policy (reference
        # policies.py:335-346) because run_env calls reset() with no args.
        super().__init__(greedy_policy.predictor)
        self._explore_policy = explore_policy
        self._greedy_policy = greedy_policy
        self._explore_prob = explore_prob
        self._active = greedy_policy

    def restore(self, is_async: bool = False) -> bool:
        ok = self._explore_policy.restore(is_async=is_async)
        return self._greedy_policy.restore(is_async=is_async) and ok

    def init_randomly(self) -> None:
        self._explore_policy.init_randomly()
        self._greedy_policy.init_randomly()

    def reset(self, explore_prob: Optional[float] = None) -> None:
        if explore_prob is not None:
            self._explore_prob = explore_prob
        self._explore_policy.reset()
        self._greedy_policy.reset()
        self._active = (
            self._explore_policy
            if self._rng.uniform() < self._explore_prob
            else self._greedy_policy
        )

    @property
    def active_policy(self) -> Policy:
        return self._active

    def SelectAction(self, state, context=None, timestep: int = 0) -> np.ndarray:
        return self._active.SelectAction(state, context, timestep)

    def sample_action(self, obs, explore_prob: float = 0.0):
        return self._active.sample_action(obs, explore_prob)
