"""Predictors: weight loading + predict(features) for robot processes."""

from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.predictors.checkpoint_predictor import CheckpointPredictor
from tensor2robot_tpu.predictors.exported_savedmodel_predictor import (
    ExportedSavedModelPredictor,
)
from tensor2robot_tpu.predictors.saved_model_v2_predictor import (
    SavedModelCodePredictor,
    SavedModelPredictorBase,
    SavedModelSignaturePredictor,
)
