"""AbstractPredictor: model loading + predict(features) for robot processes.

The on-robot half of the filesystem actor/learner bus: a predictor loads the
newest weights the learner produced (exported model dir or checkpoint),
exposes the input contract via get_feature_specification, and serves
predict() at robot control rates. Parity with the reference
predictors/abstract_predictor.py:27-81.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Mapping, Optional

from tensor2robot_tpu.specs import TensorSpecStruct


class AbstractPredictor(abc.ABC):
    """predict/restore lifecycle contract."""

    @abc.abstractmethod
    def predict(self, features: Mapping[str, Any]) -> Dict[str, Any]:
        """Runs the serving fn on spec-conforming numpy features."""

    @abc.abstractmethod
    def get_feature_specification(self) -> TensorSpecStruct:
        """The raw input contract callers pack observations against."""

    def get_label_specification(self) -> Optional[TensorSpecStruct]:
        return None

    @abc.abstractmethod
    def restore(self, is_async: bool = False) -> bool:
        """Loads the newest available weights; returns success. With
        is_async, kicks a background reload and returns immediately
        (reference exported_savedmodel_predictor.py:137-163)."""

    def init_randomly(self) -> None:
        """Random-weight initialization for tests/bringup (reference
        checkpoint_predictor.py:127-131)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support random initialization."
        )

    def close(self) -> None:
        pass

    @property
    @abc.abstractmethod
    def model_version(self) -> int:
        """Monotonic version of the loaded weights (-1 when unloaded)."""

    @property
    @abc.abstractmethod
    def global_step(self) -> int:
        """Training global step of the loaded weights (-1 when unknown)."""

    @property
    @abc.abstractmethod
    def model_path(self) -> Optional[str]:
        """Filesystem path the weights came from."""

    def assert_is_loaded(self) -> None:
        if self.model_version < 0:
            raise ValueError(
                f"{type(self).__name__} has no model loaded; call restore() "
                "or init_randomly() first."
            )

    def __enter__(self) -> "AbstractPredictor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
