"""CheckpointPredictor: serve straight from training checkpoints.

Rebuilds the predict fn from model code and polls the trainer's orbax
checkpoint directory for new steps — the robot-side view of a learner that
checkpoints but has not (yet) exported. Parity with the reference
predictors/checkpoint_predictor.py:36-214 (fresh-graph rebuild, polling
`latest_checkpoint` restore with timeout, random init for tests).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Mapping, Optional

import jax
import numpy as np

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.specs import TensorSpecStruct, flatten_spec_structure
from tensor2robot_tpu.train import state as state_lib


@configurable("CheckpointPredictor")
class CheckpointPredictor(AbstractPredictor):
    """Serves a T2RModel from the newest checkpoint under model_dir."""

    def __init__(
        self,
        t2r_model,
        checkpoint_dir: Optional[str] = None,
        timeout: int = 600,
        use_ema: Optional[bool] = None,
    ):
        """Args:
        t2r_model: the model whose predict path to serve.
        checkpoint_dir: the trainer's model_dir (its checkpoints/ subdir is
          polled). Optional when only init_randomly will be used.
        timeout: seconds restore() busy-waits for a first checkpoint.
        use_ema: serve averaged params; defaults to the model's
          use_avg_model_params (swapping-saver parity).
        """
        from tensor2robot_tpu.train.train_eval import CompiledModel, maybe_wrap_for_tpu

        self._model = maybe_wrap_for_tpu(t2r_model)
        self._compiled = CompiledModel(self._model, donate_state=False)
        self._checkpoint_dir = checkpoint_dir
        self._timeout = timeout
        self._use_ema = (
            use_ema
            if use_ema is not None
            else getattr(self._model, "use_avg_model_params", False)
        )
        self._feature_spec = self._compiled.preprocessor.get_in_feature_specification(
            "predict"
        )
        self._variables = None
        self._restored_step = -1
        self._template_state = None

    # -- state template -------------------------------------------------------

    def _example_features(self) -> TensorSpecStruct:
        from tensor2robot_tpu.specs import make_constant_numpy

        flat = make_constant_numpy(self._feature_spec, batch_size=1)
        return TensorSpecStruct(dict(flat.items()))

    def _get_template_state(self):
        """An abstract TrainState matching the trainer's checkpoint layout."""
        if self._template_state is None:
            features, _ = self._compiled.preprocessor.preprocess(
                self._example_features(), None, mode="predict", rng=None
            )
            self._template_state = self._compiled_init_state(features)
        return self._template_state

    def _compiled_init_state(self, features):
        from tensor2robot_tpu.train.state import create_train_state

        return create_train_state(
            self._model, jax.random.PRNGKey(0), features, self._compiled.optimizer
        )

    # -- restore --------------------------------------------------------------

    def restore(self, is_async: bool = False) -> bool:
        del is_async  # Checkpoint reload is fast; always synchronous.
        if self._checkpoint_dir is None:
            raise ValueError("CheckpointPredictor needs checkpoint_dir to restore.")
        import orbax.checkpoint as ocp

        path = os.path.abspath(os.path.join(self._checkpoint_dir, "checkpoints"))
        start = time.time()
        while True:
            latest = None
            if os.path.isdir(path):
                with ocp.CheckpointManager(path) as manager:
                    # Durable steps only (read-only skip, never quarantine):
                    # this predictor polls a LIVE trainer's dir, where
                    # latest_step() can name a torn final-named dir — the
                    # durability contract (docs/RESILIENCE.md) says no
                    # reader ever loads one. durability (not train_eval):
                    # it is orbax/jax-free, so this serving-side poll
                    # does not drag in the training stack.
                    from tensor2robot_tpu.train.durability import (
                        latest_durable_step_in,
                    )

                    latest = latest_durable_step_in(manager)
                    if latest is not None and latest != self._restored_step:
                        # Restore against the checkpoint's OWN metadata with
                        # host-placed leaves (train/state.py): serving must
                        # depend neither on the trainer's topology (whose
                        # sharding file a template-less restore replays) nor
                        # on its optimizer layout (per-leaf vs
                        # optax.flatten). Fall back to the model-derived
                        # template — exact for same-config trainers — only
                        # if metadata probing fails.
                        from tensor2robot_tpu.train.state import (
                            checkpoint_metadata_template,
                        )

                        try:
                            abstract = checkpoint_metadata_template(
                                path, latest
                            )
                        except Exception:  # noqa: BLE001 — best-effort
                            state = self._get_template_state()
                            abstract = jax.tree_util.tree_map(
                                lambda x: jax.ShapeDtypeStruct(
                                    x.shape, x.dtype
                                ),
                                state,
                            )
                        restored = manager.restore(
                            latest, args=ocp.args.StandardRestore(abstract)
                        )
                        # Metadata-derived restore yields the raw on-disk
                        # dict; the model-template fallback yields a
                        # TrainState. Both carry the same fields.
                        if isinstance(restored, dict):
                            variables = dict(restored["variables"])
                            if (
                                self._use_ema
                                and restored.get("ema_params") is not None
                            ):
                                # ema_as_tree: a flat-EMA checkpoint
                                # (flatten_optimizer_update) stores one
                                # 1-D vector, not a params tree.
                                variables["params"] = state_lib.ema_as_tree(
                                    restored["ema_params"],
                                    variables["params"],
                                )
                            self._variables = variables
                        else:
                            self._variables = restored.export_variables(
                                use_ema=self._use_ema
                            )
                        self._restored_step = int(latest)
                        return True
            if latest is not None and latest == self._restored_step:
                return True
            if time.time() - start > self._timeout:
                return False
            time.sleep(2.0)

    def init_randomly(self) -> None:
        features, _ = self._compiled.preprocessor.preprocess(
            self._example_features(), None, mode="predict", rng=None
        )
        variables = self._model.init_variables(jax.random.PRNGKey(0), features)
        self._variables = variables
        self._restored_step = 0

    # -- predict --------------------------------------------------------------

    def predict(self, features: Mapping[str, Any]) -> Dict[str, Any]:
        self.assert_is_loaded()
        struct = TensorSpecStruct(
            {k: np.asarray(v) for k, v in flatten_spec_structure(features).items()}
        )
        preprocessed, _ = self._compiled.preprocessor.preprocess(
            struct, None, mode="predict", rng=None
        )
        outputs = self._compiled.predict_step(self._variables, preprocessed)
        return {
            key: np.asarray(value)
            for key, value in flatten_spec_structure(outputs).items()
        }

    # -- introspection --------------------------------------------------------

    def get_feature_specification(self) -> TensorSpecStruct:
        """The client-facing input contract: the preprocessor's RAW in-spec
        (what predict() itself validates), filtered to required tensors —
        reference predictors/checkpoint_predictor.py:72-75,118-120. The
        model's packed spec describes the post-preprocess network input and
        is NOT what a caller feeds."""
        from tensor2robot_tpu.specs.utils import (
            filter_required_flat_tensor_spec,
        )

        return filter_required_flat_tensor_spec(self._feature_spec)

    @property
    def model_version(self) -> int:
        return self._restored_step

    @property
    def global_step(self) -> int:
        return self._restored_step

    @property
    def model_path(self) -> Optional[str]:
        if self._checkpoint_dir is None or self._restored_step < 0:
            return None
        return os.path.join(
            self._checkpoint_dir, "checkpoints", str(self._restored_step)
        )
