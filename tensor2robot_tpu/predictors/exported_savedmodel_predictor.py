"""Predictor over exported model dirs (the SavedModel-equivalent artifact).

Loads the latest timestamped export under a root, reconstructing the input
contract from assets.extra/t2r_assets.pbtxt — no model code needed when the
export carries a StableHLO artifact. Supports the reference's operational
behaviors (predictors/exported_savedmodel_predictor.py:54-355):

  * busy-wait restore with timeout for fleets that boot before the learner
    has exported anything (:192-215);
  * async restore: a background thread loads the new version while predict()
    keeps serving the old one, swap on completion (:137-163,351-355);
  * action-tile-aware input expansion: a critic exported with an
    `action_batch_size` population dim accepts un-tiled inputs, which are
    broadcast up (:106-118).

When the export has no StableHLO payload, pass `t2r_model` and the predictor
rebuilds the serving fn from model code + the exported variables (the same
fallback relationship the reference had between SavedModel loading and
graph-rebuild predictors).
"""

from __future__ import annotations

import logging
import threading

from tensor2robot_tpu.testing import locksmith
import time
from typing import Any, Callable, Dict, Mapping, Optional

import numpy as np

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.export.saved_model import ExportedModel, latest_export_dir
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    flatten_spec_structure,
)


@configurable("ExportedSavedModelPredictor")
class ExportedSavedModelPredictor(AbstractPredictor):
    """Serves the newest export under `export_dir`."""

    def __init__(
        self,
        export_dir: str,
        t2r_model=None,
        timeout: int = 600,
        tile_batch_for_action: bool = True,
    ):
        """Args:
        export_dir: root containing timestamped export versions.
        t2r_model: optional model for the code-rebuild fallback when an
          export has no StableHLO artifact.
        timeout: seconds restore() busy-waits for a first export.
        tile_batch_for_action: expand inputs whose leading dims miss the
          exported action-population dim (CEM critics).
        """
        self._export_dir = export_dir
        self._t2r_model = t2r_model
        self._timeout = timeout
        self._tile = tile_batch_for_action
        self._loaded: Optional[ExportedModel] = None
        self._predict_fn: Optional[Callable] = None
        self._lock = locksmith.make_lock(
            "ExportedSavedModelPredictor._lock", budget_ms=0
        )
        self._restore_thread: Optional[threading.Thread] = None
        # True from the moment an async restore is SCHEDULED until its
        # thread finishes — is_alive() alone has a window where the thread
        # exists but has not started, during which a second restore(
        # is_async=True) would spawn a duplicate.
        self._restore_in_flight = False
        self._restore_thread_leaked = False
        self._restore_prewarm: Optional[Callable] = None

    def set_restore_prewarm(self, fn: Optional[Callable]) -> None:
        """Installs `fn(loaded, predict_fn)` to run on every restore AFTER
        the new version's serving fn is built but BEFORE it is swapped in.
        The policy server uses this to compile every serving bucket on the
        incoming version while the old one keeps serving — a hot swap must
        never put a cold executable in front of live traffic. A prewarm
        failure aborts the swap (the old version keeps serving)."""
        with self._lock:
            self._restore_prewarm = fn

    # -- restore --------------------------------------------------------------

    def restore(self, is_async: bool = False) -> bool:
        if is_async:
            with self._lock:
                if self._restore_in_flight:
                    # A restore thread is already scheduled or running;
                    # do not start a duplicate.
                    return True
                thread = threading.Thread(
                    target=self._restore_async_target,
                    name="t2r-async-restore",
                    daemon=True,
                )
                self._restore_in_flight = True
                self._restore_thread = thread
                # Start under the lock: once _restore_in_flight is set no
                # other caller can race a second thread in, and the
                # flag/thread pair stays consistent. If start() itself
                # fails (thread exhaustion) the flag must not stay stuck
                # True — that would turn every future async restore into
                # a silent no-op.
                try:
                    thread.start()
                except BaseException:
                    self._restore_in_flight = False
                    self._restore_thread = None
                    raise
            return True
        return self._restore_sync()

    def _restore_async_target(self) -> None:
        try:
            self._restore_sync()
        finally:
            with self._lock:
                self._restore_in_flight = False

    def _restore_sync(self) -> bool:
        start = time.time()
        while True:
            path = latest_export_dir(self._export_dir)
            if path is not None:
                current = self._loaded
                if current is not None and current.export_dir == path:
                    return True
                try:
                    loaded = ExportedModel(path)
                except OSError:
                    # Raced the version GC deleting this dir between listing
                    # and reading; treat as not-yet-available and re-poll
                    # (reference retry behavior :330-345).
                    loaded = None
                if loaded is not None:
                    # Persistent-compile-cache engagement per incoming
                    # version, BEFORE its prewarm compiles — skipped
                    # entirely when AOT executables cover every warmup
                    # bucket (this version will never compile, so the
                    # cache round-trip is pure overhead).
                    from tensor2robot_tpu.serving.compile_cache import (
                        enable_compile_cache_for,
                    )

                    enable_compile_cache_for(loaded)
                    # Configuration errors (no StableHLO and no model code)
                    # are permanent: propagate instead of burning the timeout.
                    predict_fn = self._build_predict_fn(loaded)
                    prewarm = self._restore_prewarm
                    if prewarm is not None:
                        try:
                            prewarm(
                                loaded,
                                self._serving_callable(loaded, predict_fn),
                            )
                        except Exception:  # noqa: BLE001 — a version that
                            # cannot prewarm cannot serve; keep the old one.
                            logging.exception(
                                "restore: prewarm of %s failed; not swapping",
                                loaded.export_dir,
                            )
                            return False
                    with self._lock:
                        self._loaded = loaded
                        self._predict_fn = predict_fn
                    return True
            if time.time() - start > self._timeout:
                return False
            time.sleep(2.0)

    def _build_predict_fn(self, loaded: ExportedModel) -> Callable:
        if loaded.has_stablehlo:
            return loaded.predict
        if getattr(loaded, "quant_regime", "none") != "none":
            # The model-code fallback rebuilds an fp32 forward — under a
            # quant regime that would silently serve full precision where
            # the operator asked for int8/fp16. Fail loudly instead.
            raise ValueError(
                f"Export {loaded.export_dir} has no serving program for "
                f"quant regime {loaded.quant_regime!r} "
                f"({(loaded.metadata.get('serve_quant') or {}).get('stablehlo_error')}); "
                "re-export it or serve with T2R_SERVE_QUANT=none."
            )
        if self._t2r_model is None:
            raise ValueError(
                f"Export {loaded.export_dir} has no StableHLO artifact "
                f"({loaded.metadata.get('stablehlo_error')}); construct the "
                "predictor with t2r_model= to rebuild the serving fn from code."
            )
        from tensor2robot_tpu.predictors.saved_model_v2_predictor import (
            build_model_code_serving_fn,
        )

        predict_fn, _ = build_model_code_serving_fn(self._t2r_model, loaded)
        return predict_fn

    def init_randomly(self) -> None:
        """Serves random weights from model code — for tests and robot
        bring-up before any export exists."""
        if self._t2r_model is None:
            raise ValueError("init_randomly requires t2r_model.")
        from tensor2robot_tpu.predictors.saved_model_v2_predictor import (
            build_model_code_serving_fn,
            make_random_loaded,
        )

        predict_fn, generator = build_model_code_serving_fn(self._t2r_model)
        with self._lock:
            self._loaded = make_random_loaded(generator)  # type: ignore[assignment]
            self._predict_fn = predict_fn

    @property
    def loaded_model(self):
        """The currently-loaded ExportedModel (None before restore). Jit-
        native consumers (policies.JitCEMPolicy) trace through its
        StableHLO call instead of the numpy predict surface."""
        with self._lock:
            return self._loaded

    # -- predict --------------------------------------------------------------

    def _serving_callable(self, loaded, predict_fn) -> Callable:
        """predict()-shaped view (flatten + tiling applied) over a SPECIFIC
        (loaded, predict_fn) pair — the surface restore-prewarm hooks see,
        identical to what predict() will run once the pair swaps in."""

        def serve(features: Mapping[str, Any]) -> Dict[str, Any]:
            flat = dict(flatten_spec_structure(features).items())
            if self._tile:
                flat = self._maybe_expand_dims(loaded.feature_spec, flat)
            return dict(predict_fn(flat))

        return serve

    def predict(self, features: Mapping[str, Any]) -> Dict[str, Any]:
        return self.predict_versioned(features)[0]

    def predict_versioned(
        self, features: Mapping[str, Any]
    ) -> "tuple[Dict[str, Any], int]":
        """predict() plus the model version that computed it, read as one
        atomic pair: an async-restore swap landing mid-call cannot
        mislabel the outputs (the policy server reports this version per
        response)."""
        self.assert_is_loaded()
        with self._lock:
            loaded, predict_fn = self._loaded, self._predict_fn
        serve = self._serving_callable(loaded, predict_fn)
        return serve(features), self._version_of(loaded)

    def _maybe_expand_dims(
        self, spec: TensorSpecStruct, flat: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Aligns input ranks with the exported spec: a missing leading dim
        (e.g. the CEM action-population dim baked into predict-mode specs)
        is broadcast in (reference _maybe_expand_dim :106-118)."""
        out = {}
        flat_spec = flatten_spec_structure(spec)
        for key, value in flat.items():
            value = np.asarray(value)
            leaf = flat_spec.get(key)
            if isinstance(leaf, ExtendedTensorSpec):
                want = len(leaf.shape) + 1  # + batch dim
                while value.ndim < want:
                    value = np.expand_dims(value, axis=1 if value.ndim >= 1 else 0)
                if value.ndim == want and leaf.shape and leaf.shape[0] is not None:
                    # Broadcast a singleton population dim up to the spec's.
                    if value.shape[1] == 1 and leaf.shape[0] > 1:
                        value = np.repeat(value, leaf.shape[0], axis=1)
            out[key] = value
        return out

    # -- introspection --------------------------------------------------------

    def get_feature_specification(self) -> TensorSpecStruct:
        self.assert_is_loaded()
        return self._loaded.feature_spec

    def get_label_specification(self) -> Optional[TensorSpecStruct]:
        self.assert_is_loaded()
        return self._loaded.label_spec

    @staticmethod
    def _version_of(loaded) -> int:
        if loaded is None:
            return -1
        base = loaded.export_dir.rstrip("/").rsplit("/", 1)[-1]
        return int(base) if base.isdigit() else 0

    @property
    def model_version(self) -> int:
        return self._version_of(self._loaded)

    @property
    def global_step(self) -> int:
        return -1 if self._loaded is None else int(self._loaded.global_step)

    @property
    def model_path(self) -> Optional[str]:
        return None if self._loaded is None else self._loaded.export_dir

    @property
    def quant_regime(self) -> str:
        """The low-precision serving regime of the LOADED artifact
        ('none' before restore or when serving unquantized). Restore
        resolves T2R_SERVE_QUANT when it constructs the ExportedModel,
        so every version this predictor swaps in serves the same regime
        — fleet snapshots report it per replica for mix-verification."""
        loaded = self.loaded_model
        return getattr(loaded, "quant_regime", "none") if loaded else "none"

    @property
    def native_dot_layers(self) -> tuple:
        """Layers the loaded artifact contracts natively in the storage
        dtype (ExportedModel.native_dot_layers); empty before restore,
        under 'none', or when the export's parity gate demoted the map."""
        loaded = self.loaded_model
        return tuple(getattr(loaded, "native_dot_layers", ()) or ())

    @property
    def native_attention(self) -> tuple:
        """Attention modules the loaded artifact contracts on quantized
        operands (ExportedModel.native_attention); empty before restore
        or under 'none'."""
        loaded = self.loaded_model
        return tuple(getattr(loaded, "native_attention", ()) or ())

    @property
    def calib_mode(self):
        """Activation-calibration mode of the loaded regime's program
        (ExportedModel.calib_mode); None before restore, under 'none',
        or when the program has no native contractions to calibrate."""
        loaded = self.loaded_model
        return getattr(loaded, "calib_mode", None) if loaded else None

    @property
    def quant_reduce_audit(self):
        """The export-recorded reduce audit of the loaded regime's
        program (ExportedModel.quant_reduce_audit); None before restore
        or under 'none'."""
        loaded = self.loaded_model
        return getattr(loaded, "quant_reduce_audit", None) if loaded else None

    @property
    def restore_thread_leaked(self) -> bool:
        """True when close() gave up waiting on a restore thread (it keeps
        polling until its own timeout; fleet monitors should surface it)."""
        return self._restore_thread_leaked

    def close(self, join_timeout: float = 30.0) -> None:
        with self._lock:
            thread = self._restore_thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=join_timeout)
            if thread.is_alive():
                # The restore busy-wait can legitimately outlive us (its
                # poll timeout may be minutes); surface the leak instead
                # of silently abandoning the thread.
                self._restore_thread_leaked = True
                logging.warning(
                    "ExportedSavedModelPredictor.close(): async restore "
                    "thread still alive after %.0fs join; leaking it "
                    "(daemon, polling %s)",
                    join_timeout,
                    self._export_dir,
                )
