"""SavedModel-v2 predictor family: explicit code-path and signature-path
serving over one exported artifact.

The reference shipped three predictors over SavedModels
(predictors/saved_model_v2_predictor.py:33-257): SavedModelPredictorBase,
SavedModelTF2Predictor (restores the model OBJECT and calls model.predict)
and SavedModelTF1Predictor (drives the serving SIGNATURE in a session). The
same split exists here over the exported-dir artifact:

  * SavedModelCodePredictor  — the TF2 analogue: model code + exported
    variables; the model object is in charge, so research models can expose
    intermediate outputs and dtype policies the frozen signature would hide.
  * SavedModelSignaturePredictor — the TF1 analogue: strictly the serialized
    StableHLO program; zero model code, exactly what a robot fleet runs.

Both load one pinned export version (a specific dir or the latest under a
root at construction). The polling/async-restore fleet behavior lives in
ExportedSavedModelPredictor; these are the simple, explicit variants.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import numpy as np

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.export.saved_model import (
    ExportedModel,
    latest_export_dir,
)
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.specs import TensorSpecStruct, flatten_spec_structure


def build_model_code_serving_fn(
    t2r_model, loaded: Optional[ExportedModel] = None
) -> Tuple[Callable[[Dict[str, Any]], Dict[str, Any]], Any]:
    """(serving_fn, generator) from model code, with variables taken from
    `loaded` when given, else freshly initialized (random-init serving).

    Shared by the v2 family and ExportedSavedModelPredictor's code fallback.
    """
    import jax

    from tensor2robot_tpu.export.export_generators import DefaultExportGenerator
    from tensor2robot_tpu.train.train_eval import (
        CompiledModel,
        maybe_wrap_for_tpu,
    )

    model = maybe_wrap_for_tpu(t2r_model)
    compiled = CompiledModel(model, donate_state=False)
    generator = DefaultExportGenerator()
    generator.set_specification_from_model(model)
    example = {
        k: np.zeros(v.shape, v.dtype)
        for k, v in generator.create_example_features(batch_size=1).items()
    }
    features, _ = compiled.preprocessor.preprocess(
        TensorSpecStruct(example), None, mode="predict", rng=None
    )
    target = model.init_variables(jax.random.PRNGKey(0), features)
    variables = (
        loaded.load_variables(target=target) if loaded is not None else target
    )
    serving_fn = generator.create_serving_fn(compiled, variables)

    def predict_fn(flat_features: Dict[str, Any]) -> Dict[str, Any]:
        return {k: np.asarray(v) for k, v in serving_fn(flat_features).items()}

    return predict_fn, generator


def make_random_loaded(generator):
    """A stand-in for ExportedModel carrying randomly-initialized serving
    state — what init_randomly predictors report as their loaded artifact."""

    class _RandomLoaded:
        export_dir = "<random-init>"
        global_step = 0
        feature_spec = generator.serving_input_spec()
        label_spec = generator.label_spec
        metadata: Dict[str, Any] = {}

    return _RandomLoaded()


def _resolve_export_dir(saved_model_path: str) -> Optional[str]:
    """A specific export version dir passes through; a root resolves to its
    latest version."""
    from tensor2robot_tpu.export.saved_model import is_valid_export_dir

    if is_valid_export_dir(saved_model_path):
        return saved_model_path
    return latest_export_dir(saved_model_path)


class SavedModelPredictorBase(AbstractPredictor):
    """Shared loading/introspection over one export version
    (reference SavedModelPredictorBase, saved_model_v2_predictor.py:33)."""

    def __init__(self, saved_model_path: str):
        self._saved_model_path = saved_model_path
        self._loaded: Optional[ExportedModel] = None
        self._predict_fn: Optional[Callable] = None

    def _build_predict_fn(self, loaded: ExportedModel) -> Callable:
        raise NotImplementedError

    def restore(self, is_async: bool = False) -> bool:
        del is_async  # one-shot load; fleets use ExportedSavedModelPredictor
        path = _resolve_export_dir(self._saved_model_path)
        if path is None:
            return False
        loaded = ExportedModel(path)
        self._predict_fn = self._build_predict_fn(loaded)
        self._loaded = loaded
        return True

    def init_randomly(self) -> None:
        raise ValueError(
            f"{type(self).__name__} serves a fixed artifact; random init is "
            "only meaningful for model-code predictors (CheckpointPredictor "
            "or SavedModelCodePredictor)."
        )

    def predict(self, features: Mapping[str, Any]) -> Dict[str, Any]:
        self.assert_is_loaded()
        flat = dict(flatten_spec_structure(features).items())
        return dict(self._predict_fn(flat))

    def get_feature_specification(self) -> TensorSpecStruct:
        self.assert_is_loaded()
        return self._loaded.feature_spec

    def get_label_specification(self) -> Optional[TensorSpecStruct]:
        self.assert_is_loaded()
        return self._loaded.label_spec

    @property
    def model_version(self) -> int:
        if self._loaded is None:
            return -1
        base = os.path.basename(self._loaded.export_dir.rstrip("/"))
        return int(base) if base.isdigit() else 0

    @property
    def global_step(self) -> int:
        return -1 if self._loaded is None else int(self._loaded.global_step)

    @property
    def model_path(self) -> Optional[str]:
        return None if self._loaded is None else self._loaded.export_dir


@configurable("SavedModelCodePredictor")
class SavedModelCodePredictor(SavedModelPredictorBase):
    """Model-object serving: exported variables restored into `t2r_model`
    (reference SavedModelTF2Predictor, saved_model_v2_predictor.py:179)."""

    def __init__(self, saved_model_path: str, t2r_model):
        super().__init__(saved_model_path)
        self._t2r_model = t2r_model

    def _build_predict_fn(self, loaded: ExportedModel) -> Callable:
        if getattr(loaded, "quant_regime", "none") != "none":
            # Model-code serving rebuilds an fp32 forward from the
            # variables file — under T2R_SERVE_QUANT=int8/fp16 that would
            # silently serve full precision where the operator asked for
            # a quantized regime (the same loud-failure rule as
            # ExportedSavedModelPredictor).
            raise ValueError(
                f"SavedModelCodePredictor serves fp32 model code and "
                f"cannot honor quant regime {loaded.quant_regime!r}; "
                "serve the export's quantized program with "
                "ExportedSavedModelPredictor/SavedModelSignaturePredictor "
                "or set T2R_SERVE_QUANT=none."
            )
        predict_fn, _ = build_model_code_serving_fn(self._t2r_model, loaded)
        return predict_fn

    def init_randomly(self) -> None:
        predict_fn, generator = build_model_code_serving_fn(self._t2r_model)
        self._loaded = make_random_loaded(generator)  # type: ignore[assignment]
        self._predict_fn = predict_fn


@configurable("SavedModelSignaturePredictor")
class SavedModelSignaturePredictor(SavedModelPredictorBase):
    """Signature-only serving: the serialized StableHLO program, no model
    code (reference SavedModelTF1Predictor, saved_model_v2_predictor.py:199)."""

    def _build_predict_fn(self, loaded: ExportedModel) -> Callable:
        if not loaded.has_stablehlo:
            raise ValueError(
                f"Export {loaded.export_dir} carries no StableHLO signature "
                f"({loaded.metadata.get('stablehlo_error')}); serve it with "
                "SavedModelCodePredictor instead."
            )
        return loaded.predict
