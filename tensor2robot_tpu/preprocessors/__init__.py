"""Preprocessors: validated per-batch transforms, jittable and device-placed."""

from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
    NoOpPreprocessor,
    SpecTransformationPreprocessor,
)
from tensor2robot_tpu.preprocessors.dtype_policy import TPUPreprocessorWrapper
from tensor2robot_tpu.preprocessors import image_transformations
from tensor2robot_tpu.preprocessors import distortion
