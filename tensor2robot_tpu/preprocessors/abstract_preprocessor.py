"""Preprocessor abstraction: per-batch transforms between parsed data and the
model.

A preprocessor declares four specs — what it consumes (`in`) and what it
produces (`out`), for features and labels — and a pure `_preprocess_fn`.
The public `preprocess` validates+packs its inputs, applies the transform,
and validates+flattens the outputs, so models always see exactly their
declared contract (reference preprocessors/abstract_preprocessor.py:29-218).

TPU-first design: `_preprocess_fn` is a *pure jittable function* taking an
explicit `jax.random` key. The trainer composes it with the model step under
one jit, so crops/distortions/casts fuse into the device program and the
host feeds raw (small, uint8) tensors — the opposite placement from the
reference's host-side tf.data maps, chosen for infeed bandwidth.
"""

from __future__ import annotations

import abc
from typing import Any, Optional, Tuple

import jax

from tensor2robot_tpu.specs import (
    TensorSpecStruct,
    validate_and_flatten,
    validate_and_pack,
)

MODE_TRAIN = "train"
MODE_EVAL = "eval"
MODE_PREDICT = "predict"
ALL_MODES = (MODE_TRAIN, MODE_EVAL, MODE_PREDICT)


class AbstractPreprocessor(abc.ABC):
    """Base preprocessor; subclasses override the 4 spec getters and
    `_preprocess_fn`."""

    def __init__(self, model_spec_provider: Optional[Any] = None):
        # When constructed against a model, validate that the model exposes
        # specs for all modes up front (reference :60-66 does the same).
        if model_spec_provider is not None:
            for mode in (MODE_TRAIN, MODE_EVAL):
                model_spec_provider.get_feature_specification(mode)
                model_spec_provider.get_label_specification(mode)
        self._model = model_spec_provider

    # -- spec contract --------------------------------------------------------

    @abc.abstractmethod
    def get_in_feature_specification(self, mode: str) -> TensorSpecStruct:
        """Spec of the features this preprocessor consumes (what's on disk)."""

    @abc.abstractmethod
    def get_in_label_specification(self, mode: str) -> TensorSpecStruct:
        """Spec of the labels this preprocessor consumes."""

    @abc.abstractmethod
    def get_out_feature_specification(self, mode: str) -> TensorSpecStruct:
        """Spec of the features this preprocessor produces (= model in-spec)."""

    @abc.abstractmethod
    def get_out_label_specification(self, mode: str) -> TensorSpecStruct:
        """Spec of the labels this preprocessor produces."""

    # -- decode-time ROI ------------------------------------------------------

    def get_decode_rois(self, mode: str):
        """Optional {in-feature key: data.roi.DecodeROI} describing crops
        the DATA LAYER may apply at jpeg-decode time instead of this
        preprocessor applying them on device (the pixels are identical;
        see data/roi.py). The input generator forwards the map to
        RecordDataset; `preprocess` then accepts the named features at
        either the source or the cropped shape, and `_preprocess_fn`
        must skip its own crop when the input already arrives cropped.
        Base: no ROIs (None)."""
        del mode
        return None

    # -- transform ------------------------------------------------------------

    @abc.abstractmethod
    def _preprocess_fn(
        self,
        features: TensorSpecStruct,
        labels: Optional[TensorSpecStruct],
        mode: str,
        rng: Optional[jax.Array],
    ) -> Tuple[TensorSpecStruct, Optional[TensorSpecStruct]]:
        """The pure transform. Must be jit-traceable (no python branching on
        tensor values; randomness via the explicit `rng` key)."""

    def preprocess(
        self,
        features,
        labels=None,
        mode: str = MODE_TRAIN,
        rng: Optional[jax.Array] = None,
    ) -> Tuple[TensorSpecStruct, Optional[TensorSpecStruct]]:
        """Validated transform: pack(in-spec) -> _preprocess_fn ->
        flatten(out-spec) (reference :172-218)."""
        if mode not in ALL_MODES:
            raise ValueError(f"mode must be one of {ALL_MODES}, got {mode!r}")
        in_feature_spec = self.get_in_feature_specification(mode)
        decode_rois = self.get_decode_rois(mode)
        if decode_rois:
            # Features named in the decode-ROI map may arrive already
            # cropped (a ROI-decoding dataset) or at the source shape
            # (direct feeds / T2R_DECODE_ROI=0); accept exactly those two.
            from tensor2robot_tpu.data.roi import adjust_spec_for_roi_tensors

            in_feature_spec = adjust_spec_for_roi_tensors(
                in_feature_spec, decode_rois, features
            )
        packed_features = validate_and_pack(
            in_feature_spec, features, ignore_batch=True
        )
        packed_labels = None
        if labels is not None:
            packed_labels = validate_and_pack(
                self.get_in_label_specification(mode), labels, ignore_batch=True
            )
        out_features, out_labels = self._preprocess_fn(
            packed_features, packed_labels, mode, rng
        )
        out_features = validate_and_flatten(
            self.get_out_feature_specification(mode), out_features,
            ignore_batch=True,
        )
        if out_labels is not None:
            out_labels = validate_and_flatten(
                self.get_out_label_specification(mode), out_labels,
                ignore_batch=True,
            )
        return out_features, out_labels


class NoOpPreprocessor(AbstractPreprocessor):
    """Identity: in == out == the model's specs
    (reference noop_preprocessor.py:27)."""

    def __init__(self, model_spec_provider: Any):
        super().__init__(model_spec_provider)

    def get_in_feature_specification(self, mode: str) -> TensorSpecStruct:
        return self._model.get_feature_specification(mode)

    def get_in_label_specification(self, mode: str) -> TensorSpecStruct:
        return self._model.get_label_specification(mode)

    def get_out_feature_specification(self, mode: str) -> TensorSpecStruct:
        return self._model.get_feature_specification(mode)

    def get_out_label_specification(self, mode: str) -> TensorSpecStruct:
        return self._model.get_label_specification(mode)

    def _preprocess_fn(self, features, labels, mode, rng):
        return features, labels


class SpecTransformationPreprocessor(NoOpPreprocessor):
    """Convenience base: identity transform with rewritten *in* specs.

    Override `_transform_in_feature_specification` (and/or label variant) to
    declare a different on-disk representation — e.g. a uint8 jpeg source for
    a float32 model input — then implement `_preprocess_fn` for the value
    conversion (reference spec_transformation_preprocessor.py:25-174).
    """

    def get_in_feature_specification(self, mode: str) -> TensorSpecStruct:
        return self._transform_in_feature_specification(
            self._model.get_feature_specification(mode).copy(), mode
        )

    def get_in_label_specification(self, mode: str) -> TensorSpecStruct:
        return self._transform_in_label_specification(
            self._model.get_label_specification(mode).copy(), mode
        )

    def _transform_in_feature_specification(
        self, spec: TensorSpecStruct, mode: str
    ) -> TensorSpecStruct:
        return spec

    def _transform_in_label_specification(
        self, spec: TensorSpecStruct, mode: str
    ) -> TensorSpecStruct:
        return spec

    @staticmethod
    def update_spec(spec_struct: TensorSpecStruct, key: str, **overrides) -> None:
        """In-place spec rewrite helper (reference update_spec :46-63)."""
        from tensor2robot_tpu.specs import ExtendedTensorSpec

        spec_struct[key] = ExtendedTensorSpec.from_spec(
            spec_struct[key], **overrides
        )
