"""Train-time image distortion helpers (reference preprocessors/distortion.py).

Thin aliases over image_transformations for call-site parity.
"""

from tensor2robot_tpu.preprocessors.image_transformations import (
    crop_image_batch as crop_image,
    maybe_distort_image_batch,
    preprocess_image,
    resize_image_batch,
)

__all__ = [
    "crop_image",
    "maybe_distort_image_batch",
    "preprocess_image",
    "resize_image_batch",
]
