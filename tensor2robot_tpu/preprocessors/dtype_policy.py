"""TPU dtype-policy preprocessor wrapper: the bfloat16 infeed contract.

Wraps any preprocessor so that, on TPU:
  * its *in* specs re-declare bfloat16 features as float32 — the host pipeline
    always produces float32 (bf16 has no on-disk form),
  * its *out* specs re-declare float32 as bfloat16 and *drop optional
    tensors* — halving infeed bandwidth and stripping anything the model
    doesn't strictly need,
  * `_preprocess_fn` delegates to the wrapped preprocessor then filters +
    casts the results.

Behavioral parity: tensor2robot/preprocessors/tpu_preprocessor_wrapper.py:33-156.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import (
    TensorSpecStruct,
    cast_bfloat16_to_float32,
    cast_float32_to_bfloat16,
    cast_tensors,
    filter_required_flat_tensor_spec,
    flatten_spec_structure,
)
import jax.numpy as jnp
import numpy as np


class TPUPreprocessorWrapper(AbstractPreprocessor):
    """Decorates `preprocessor` with the TPU bf16 + strip-optional policy."""

    def __init__(self, preprocessor: AbstractPreprocessor):
        super().__init__(model_spec_provider=None)
        self._preprocessor = preprocessor

    @property
    def wrapped(self) -> AbstractPreprocessor:
        return self._preprocessor

    # In-specs: bf16 -> f32 (host side produces f32; reference :74-102).
    def get_in_feature_specification(self, mode: str) -> TensorSpecStruct:
        return cast_bfloat16_to_float32(
            self._preprocessor.get_in_feature_specification(mode)
        )

    def get_in_label_specification(self, mode: str) -> TensorSpecStruct:
        return cast_bfloat16_to_float32(
            self._preprocessor.get_in_label_specification(mode)
        )

    # Out-specs: f32 -> bf16 AND optional stripped (reference :104-140).
    def get_out_feature_specification(self, mode: str) -> TensorSpecStruct:
        return cast_float32_to_bfloat16(
            filter_required_flat_tensor_spec(
                self._preprocessor.get_out_feature_specification(mode)
            )
        )

    def get_out_label_specification(self, mode: str) -> TensorSpecStruct:
        return cast_float32_to_bfloat16(
            filter_required_flat_tensor_spec(
                self._preprocessor.get_out_label_specification(mode)
            )
        )

    def _preprocess_fn(
        self,
        features: TensorSpecStruct,
        labels: Optional[TensorSpecStruct],
        mode: str,
        rng: Optional[jax.Array],
    ) -> Tuple[TensorSpecStruct, Optional[TensorSpecStruct]]:
        # The wrapped preprocessor runs at its own (f32-in) contract: its in
        # specs may declare bf16, but values arriving here are f32, which the
        # wrapped _preprocess_fn consumes directly (casts are egress-side).
        out_features, out_labels = self._preprocessor._preprocess_fn(
            features, labels, mode, rng
        )
        out_features = self._filter_and_cast(
            out_features, self.get_out_feature_specification(mode)
        )
        if out_labels is not None:
            out_labels = self._filter_and_cast(
                out_labels, self.get_out_label_specification(mode)
            )
        return out_features, out_labels

    @staticmethod
    def _filter_and_cast(tensors, out_spec: TensorSpecStruct) -> TensorSpecStruct:
        flat = flatten_spec_structure(tensors)
        filtered = TensorSpecStruct()
        for key in out_spec.keys():
            if key in flat:
                filtered[key] = flat[key]
        return cast_tensors(filtered, np.float32, jnp.bfloat16)
