"""Image transformations as pure jittable JAX functions.

Crops and photometric distortions used by the robotic-vision preprocessors
(behavioral parity: tensor2robot/preprocessors/image_transformations.py).
Everything takes explicit `jax.random` keys and runs on-device under jit,
where XLA fuses the elementwise work into adjacent ops; batches distort
per-image with vmapped independent keys.

Images are float32 in [0, 1] unless stated otherwise.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _check_crop(image_shape, target_shape) -> None:
    h, w = int(image_shape[-3]), int(image_shape[-2])
    th, tw = int(target_shape[0]), int(target_shape[1])
    if th > h or tw > w:
        raise ValueError(
            f"Crop {target_shape} larger than image {(h, w)}."
        )


def random_crop_image_batch(
    rng: jax.Array, images: jax.Array, target_shape: Sequence[int]
) -> jax.Array:
    """Randomly crops a [B, H, W, C] batch to [B, th, tw, C].

    One random offset per batch element (reference RandomCropImages :26).
    Uses dynamic_slice so the offsets can be traced values.
    """
    _check_crop(images.shape, target_shape)
    th, tw = int(target_shape[0]), int(target_shape[1])
    b, h, w = images.shape[0], images.shape[1], images.shape[2]
    key_y, key_x = jax.random.split(rng)
    ys = jax.random.randint(key_y, (b,), 0, h - th + 1)
    xs = jax.random.randint(key_x, (b,), 0, w - tw + 1)

    def crop_one(image, y, x):
        return jax.lax.dynamic_slice(
            image, (y, x, 0), (th, tw, image.shape[-1])
        )

    return jax.vmap(crop_one)(images, ys, xs)


def center_crop_image_batch(
    images: jax.Array, target_shape: Sequence[int]
) -> jax.Array:
    """Deterministic center crop (reference CenterCropImages :63)."""
    _check_crop(images.shape, target_shape)
    th, tw = int(target_shape[0]), int(target_shape[1])
    h, w = images.shape[-3], images.shape[-2]
    y = (h - th) // 2
    x = (w - tw) // 2
    return images[..., y : y + th, x : x + tw, :]


def custom_crop_image_batch(
    images: jax.Array, y: int, x: int, target_shape: Sequence[int]
) -> jax.Array:
    """Fixed-offset crop (reference CustomCropImages :105)."""
    _check_crop(images.shape, target_shape)
    th, tw = int(target_shape[0]), int(target_shape[1])
    h, w = int(images.shape[-3]), int(images.shape[-2])
    if y < 0 or x < 0 or y + th > h or x + tw > w:
        raise ValueError(
            f"Crop offset ({y}, {x}) + size ({th}, {tw}) exceeds image "
            f"bounds ({h}, {w})."
        )
    return images[..., y : y + th, x : x + tw, :]


# -- photometric distortions --------------------------------------------------


def _rgb_to_hsv(rgb: jax.Array) -> jax.Array:
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = jnp.maximum(jnp.maximum(r, g), b)
    minc = jnp.minimum(jnp.minimum(r, g), b)
    v = maxc
    delta = maxc - minc
    s = jnp.where(maxc > 0, delta / jnp.maximum(maxc, 1e-12), 0.0)
    safe_delta = jnp.maximum(delta, 1e-12)
    rc = (maxc - r) / safe_delta
    gc = (maxc - g) / safe_delta
    bc = (maxc - b) / safe_delta
    h = jnp.where(
        maxc == r, bc - gc, jnp.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc)
    )
    h = jnp.where(delta == 0.0, 0.0, (h / 6.0) % 1.0)
    return jnp.stack([h, s, v], axis=-1)


def _hsv_to_rgb(hsv: jax.Array) -> jax.Array:
    # Branchless sector-free formulation: c(n) = v - v*s*clip(min(k, 4-k), 0, 1)
    # with k = (n + 6h) mod 6. Equivalent to the classic 6-sector table but
    # pure elementwise VPU code. The table version (jnp.choose over a
    # stacked [..., 6] candidate array) lowers to a per-pixel gather, which
    # the round-3 TPU profile showed costing 225 ms PER CHANNEL per step on
    # a [64, 472, 472] image batch — 92% of the whole train step — vs ~0 for
    # this form, which fuses into the surrounding elementwise pipeline.
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]

    def channel(n):
        k = jnp.mod(n + h * 6.0, 6.0)
        return v - v * s * jnp.clip(jnp.minimum(k, 4.0 - k), 0.0, 1.0)

    return jnp.stack([channel(5.0), channel(3.0), channel(1.0)], axis=-1)


def adjust_brightness(image: jax.Array, delta: jax.Array) -> jax.Array:
    return image + delta


def adjust_contrast(image: jax.Array, factor: jax.Array) -> jax.Array:
    mean = jnp.mean(image, axis=(-3, -2), keepdims=True)
    return (image - mean) * factor + mean


def adjust_saturation(image: jax.Array, factor: jax.Array) -> jax.Array:
    gray = jnp.mean(image, axis=-1, keepdims=True)
    return gray + (image - gray) * factor


def adjust_hue(image: jax.Array, delta: jax.Array) -> jax.Array:
    hsv = _rgb_to_hsv(jnp.clip(image, 0.0, 1.0))
    h = (hsv[..., 0] + delta) % 1.0
    return _hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], axis=-1))


def apply_photometric_image_distortions(
    rng: jax.Array,
    images: jax.Array,
    max_delta_brightness: float = 32.0 / 255.0,
    lower_saturation: float = 0.5,
    upper_saturation: float = 1.5,
    max_delta_hue: float = 0.2,
    lower_contrast: float = 0.5,
    upper_contrast: float = 1.5,
    noise_stddev: float = 0.0,
    random_order: bool = False,
) -> jax.Array:
    """Random brightness/saturation/hue/contrast + optional pixel noise,
    independently per batch element (reference
    ApplyPhotometricImageDistortions :177).

    `random_order` shuffles the op order per image via a branch over the
    4! permutations (small lax.switch — XLA-friendly).
    """

    def distort_one(rng, image):
        k_b, k_s, k_h, k_c, k_n, k_o = jax.random.split(rng, 6)
        ops = [
            lambda im: adjust_brightness(
                im,
                jax.random.uniform(
                    k_b, (), minval=-max_delta_brightness,
                    maxval=max_delta_brightness,
                ),
            ),
            lambda im: adjust_saturation(
                im,
                jax.random.uniform(
                    k_s, (), minval=lower_saturation, maxval=upper_saturation
                ),
            ),
            lambda im: adjust_hue(
                im,
                jax.random.uniform(
                    k_h, (), minval=-max_delta_hue, maxval=max_delta_hue
                ),
            ),
            lambda im: adjust_contrast(
                im,
                jax.random.uniform(
                    k_c, (), minval=lower_contrast, maxval=upper_contrast
                ),
            ),
        ]
        if random_order:
            # Cyclic rotations of the op order: 4 branches instead of 4! = 24,
            # keeping lax.switch compile time bounded while still decorrelating
            # op-order artifacts across images (the point of the reference's
            # shuffled order).
            perms = [tuple((i + s) % 4 for i in range(4)) for s in range(4)]

            def apply_perm(perm):
                def fn(im):
                    for idx in perm:
                        im = ops[idx](im)
                    return jnp.clip(im, 0.0, 1.0)

                return fn

            branch = jax.random.randint(k_o, (), 0, len(perms))
            image = jax.lax.switch(branch, [apply_perm(p) for p in perms], image)
        else:
            for op in ops:
                image = op(image)
            image = jnp.clip(image, 0.0, 1.0)
        if noise_stddev > 0.0:
            image = image + noise_stddev * jax.random.normal(k_n, image.shape)
            image = jnp.clip(image, 0.0, 1.0)
        return image

    keys = jax.random.split(rng, images.shape[0])
    return jax.vmap(distort_one)(keys, images)


def apply_depth_image_distortions(
    rng: jax.Array,
    depth_images: jax.Array,
    noise_stddev: float = 0.02,
    clip_min: float = 0.0,
    clip_max: float = 1.0,
) -> jax.Array:
    """Per-pixel gaussian noise on depth maps (reference
    ApplyDepthImageDistortions :389)."""
    noise = noise_stddev * jax.random.normal(rng, depth_images.shape)
    return jnp.clip(depth_images + noise, clip_min, clip_max)


# -- composite helpers (reference preprocessors/distortion.py) ---------------


def maybe_distort_image_batch(
    rng: Optional[jax.Array], images: jax.Array, mode: str, **distortion_kwargs
) -> jax.Array:
    """Distorts only in train mode (reference distortion.py:22)."""
    if mode != "train" or rng is None:
        return images
    return apply_photometric_image_distortions(rng, images, **distortion_kwargs)


def crop_image_batch(
    rng: Optional[jax.Array],
    images: jax.Array,
    target_shape: Sequence[int],
    mode: str,
) -> jax.Array:
    """Random crop when training, center crop otherwise
    (reference distortion.py:92)."""
    if mode == "train" and rng is not None:
        return random_crop_image_batch(rng, images, target_shape)
    return center_crop_image_batch(images, target_shape)


def resize_image_batch(images: jax.Array, target_shape: Sequence[int]) -> jax.Array:
    """Bilinear resize of [B, H, W, C] (or [..., H, W, C]) images."""
    th, tw = int(target_shape[0]), int(target_shape[1])
    out_shape = images.shape[:-3] + (th, tw, images.shape[-1])
    return jax.image.resize(images, out_shape, method="bilinear")


def preprocess_image(
    images: jax.Array,
    mode: str,
    rng: Optional[jax.Array] = None,
    is_training: Optional[bool] = None,
    crop_size: Optional[Sequence[int]] = None,
    target_size: Optional[Sequence[int]] = None,
    distort: bool = False,
    **distortion_kwargs,
) -> jax.Array:
    """uint8 -> float[0,1] -> crop -> distort(train) -> resize — the standard
    vision-model ingest (reference distortion.py:38 preprocess_image).

    Handles 4D [B,H,W,C] and 5D [B,T,H,W,C] batches: 5D folds time into the
    batch for spatially-uniform treatment, then restores it.
    """
    del is_training  # mode is authoritative; kept for call-site parity
    original_shape = images.shape
    if images.ndim == 5:
        images = images.reshape((-1,) + images.shape[2:])
    if images.dtype == jnp.uint8:
        images = images.astype(jnp.float32) / 255.0
    rng_crop = rng_distort = None
    if rng is not None:
        rng_crop, rng_distort = jax.random.split(rng)
    if crop_size is not None:
        images = crop_image_batch(rng_crop, images, crop_size, mode)
    if distort and mode == "train" and rng_distort is not None:
        images = apply_photometric_image_distortions(
            rng_distort, images, **distortion_kwargs
        )
    if target_size is not None:
        images = resize_image_batch(images, target_size)
    if len(original_shape) == 5:
        images = images.reshape(original_shape[:2] + images.shape[1:])
    return images
