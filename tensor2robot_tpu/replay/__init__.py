"""Crash-tolerant replay-buffer service + actor fleet: the online loop.

The QT-Opt topology (arXiv:1806.10293) that every other subsystem was
built for: actors run research envs against the serving fleet, append
episodes to a replay buffer as tf.Example *wire bytes* (zero-parse
append; the fast parser reads spans in place at sample time), and a
learner trains from the buffer while publishing fresh policies back to
the actors. This package is the connective tissue — built so that the
failure modes distributed RL dies of in practice (actor SIGKILL
mid-episode, replay-service restart, learner preemption, stale
policies) are first-class, *tested* behaviors:

  * `segment`  — CRC-framed episode segment files with seal-time
                 durability manifests (the train/durability.py
                 discipline applied to replay data): torn segments are
                 never sampled, quarantined on startup sweep, and the
                 crash-loss bound is exactly the unsealed tail —
                 counted, not guessed.
  * `service`  — ReplayBuffer (in-process core) + the replay service
                 process, client, and respawning supervisor; FIFO /
                 prioritized sampling; staleness + replay-ratio
                 accounting.
  * `input_generator` — the learner-side bridge: replay samples as
                 spec-parsed batches (FastSpecParser over raw wire
                 bytes, SpecParser fallback), deterministic in FIFO
                 dir mode (the crash-consistency contract).
  * `actor`    — episode collection off policy clients (serving-fleet
                 gateway, local predictor, or seeded random), actor
                 process entry, and the router gateway.
  * `transport` — the cross-host wire: length-prefixed CRC-framed
                 request/response over TCP with per-request deadlines,
                 published-address discovery, and the network chaos
                 sites (`net_send`/`net_recv`).
  * `shard_map` / `sharded` — the sharded fabric: consistent-hash
                 episode placement stable under shard respawn, N shard
                 services with per-shard durability, and the
                 placement-aware client (sample failover with COUNTED
                 coverage loss, bounded append spill to dead shards,
                 cross-shard zero-duplicate uid audit).
  * `loop`     — the closed online loop harness used by `bench.py rl`
                 and the chaos suites.

Fault model + contract: docs/RESILIENCE.md "Online loop fault model";
quickstart: docs/RL_LOOP.md.

Exports resolve lazily (PEP 562): replay service and actor CHILD
processes import `replay.service` / `replay.actor` through this
package, and an eager import of `input_generator`/`loop` here would
drag jax (via data/parser.py) into every jax-free worker.
"""

_EXPORTS = {
    "SegmentManifest": "segment",
    "SegmentReader": "segment",
    "SegmentWriter": "segment",
    "list_sealed_segments": "segment",
    "salvage_open_segment": "segment",
    "sweep_replay_dir": "segment",
    "validate_segment": "segment",
    "ReplayBuffer": "service",
    "ReplayClient": "service",
    "ReplayEmpty": "service",
    "ReplayError": "service",
    "ReplayServiceHandle": "service",
    "ReplayUnavailable": "service",
    "ShardMap": "shard_map",
    "ShardedReplayClient": "sharded",
    "ShardedReplayService": "sharded",
    "audit_episode_uids": "sharded",
    "ReplayInputGenerator": "input_generator",
    "EpisodeCollector": "actor",
    "GatewayPolicyClient": "actor",
    "LocalPolicyClient": "actor",
    "RandomPolicyClient": "actor",
    "RouterGateway": "actor",
    "actor_main": "actor",
    "LoopReport": "loop",
    "OnlineLoop": "loop",
    "PublishPolicyHook": "loop",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    module = importlib.import_module(f"{__name__}.{module_name}")
    return getattr(module, name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
