"""Actors: research envs driving episodes into the replay buffer.

An actor is a loop around a policy client: reset the env task, ask the
policy for an action, step, convert the finished episode to transition
tf.Examples (`research/pose_env/episode_to_transitions.py`), serialize
to wire bytes, and append the WHOLE episode to the replay buffer in one
call. Episode-at-a-time append is the actor-crash contract: an actor
SIGKILLed mid-episode has handed nothing to the buffer yet, so the
crash drops exactly the partial episode and nothing else.

Policy clients (the `act(obs) -> (action, policy_version)` seam):

  * `GatewayPolicyClient` — the production topology: actions come from
    the serving fleet. Actor processes cannot hold the driver's
    FleetRouter, so a `RouterGateway` thread in the driver forwards
    queue-borne requests into `router.submit()` and ships replies back;
    the response's `model_version` is the policy version the episode is
    stamped with (the staleness metric's raw material). Retries with
    backoff through router hiccups; after the budget, falls back to a
    seeded random action (counted — collection degrades, never stalls).
  * `LocalPolicyClient` — in-process loops/tests: wraps any
    `predict(features) -> outputs` callable plus a version supplier.
  * `RandomPolicyClient` — seeded random actions (bring-up, baselines).

Chaos: `actor_step` fires before every env step, under the actor
process's `a<index>` scope — a seeded `kill` clause there is the
actor-SIGKILL-mid-episode fault.
"""

from __future__ import annotations

import logging
import os
import queue
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu.replay.service import ReplayClient
from tensor2robot_tpu.research.pose_env.episode_to_transitions import (
    episode_to_transitions_pose_toy,
)
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.backoff import Backoff
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "EpisodeCollector",
    "GatewayPolicyClient",
    "LocalPolicyClient",
    "RandomPolicyClient",
    "RouterGateway",
    "actor_main",
]


class RandomPolicyClient:
    """Seeded uniform-random actions; policy version 0 (bring-up)."""

    def __init__(self, seed: int = 0, action_size: int = 2):
        self._rng = np.random.RandomState(seed)
        self._action_size = action_size

    def act(self, obs: np.ndarray) -> Tuple[np.ndarray, int]:
        del obs
        return (
            self._rng.uniform(-1.0, 1.0, size=self._action_size).astype(
                np.float32
            ),
            0,
        )


class LocalPolicyClient:
    """In-process policy: wraps predict(features)->outputs + a version
    supplier (the in-process online loop's client)."""

    def __init__(
        self,
        predict_fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]],
        version_fn: Callable[[], int],
        feature_key: str = "state",
        output_key: str = "inference_output",
    ):
        self._predict_fn = predict_fn
        self._version_fn = version_fn
        self._feature_key = feature_key
        self._output_key = output_key

    def act(self, obs: np.ndarray) -> Tuple[np.ndarray, int]:
        outputs = self._predict_fn({self._feature_key: obs[None]})
        action = np.asarray(outputs[self._output_key])[0]
        return action.astype(np.float32), int(self._version_fn())


class GatewayPolicyClient:
    """Actor-process side of the serving-fleet gateway (see module doc).

    Wire: puts (actor_id, req_id, obs) on the shared gateway request
    queue, waits on its own response queue for (req_id, action, version,
    error). Retries `retries` times with jittered backoff (the shared
    seeded schedule, utils/backoff.py); exhausted, returns a seeded
    random action with version -1 and bumps `fallback_actions` — an
    actor must keep collecting through a serving brown-out, and the
    stamp (-1) keeps those episodes honest in the staleness accounting.

    Two degradations, stamped and counted SEPARATELY (they used to
    share -1, which made "we served a random action" indistinguishable
    from "we served a fleet action of unknowable age"):

      * **fallback action** (`fallback_actions`, stamp -1): the fleet
        never answered — the action is random, version -1 by fiat.
      * **version unknown** (`version_unknown_actions`): the fleet
        answered, but the gateway could not translate the artifact's
        model_version to a publish counter (version=None on the wire —
        a reply racing the first publish, before any mapping exists).
        The action is REAL; only its age is unknown. Stamp: the last
        publish counter this client has ever seen, or -1 on first
        contact — never a fabricated 0 that would claim freshness.
    """

    def __init__(
        self,
        actor_id: str,
        request_q,
        response_q,
        timeout_s: float = 10.0,
        retries: int = 3,
        seed: int = 0,
        action_size: int = 2,
    ):
        self._actor_id = actor_id
        self._request_q = request_q
        self._response_q = response_q
        self._timeout_s = timeout_s
        self._retries = retries
        self._rng = np.random.RandomState(seed)
        self._backoff = Backoff(base_ms=50.0, cap_ms=1000.0, seed=seed)
        self._action_size = action_size
        # Opaque (instance token, counter) request ids, same rationale as
        # ReplayClient: ids from different client instances sharing a
        # queue must never alias.
        self._token = f"{os.getpid()}-{id(self):x}"
        self._req_counter = 0
        self.fallback_actions = 0
        self.version_unknown_actions = 0
        self._last_known_version: Optional[int] = None

    def act(self, obs: np.ndarray) -> Tuple[np.ndarray, int]:
        for attempt in range(self._retries + 1):
            if attempt:
                time.sleep(self._backoff.delay_s(attempt))
            self._req_counter += 1
            req_id = (self._token, self._req_counter)
            try:
                self._request_q.put(
                    (self._actor_id, req_id, np.asarray(obs)), timeout=1.0
                )
            except (queue.Full, OSError, ValueError):
                continue
            deadline = time.monotonic() + self._timeout_s
            while time.monotonic() < deadline:
                try:
                    response = self._response_q.get(
                        timeout=max(deadline - time.monotonic(), 0.01)
                    )
                except queue.Empty:
                    break
                except (OSError, ValueError):
                    break
                if response[0] != req_id:
                    continue  # stale reply from a timed-out attempt
                _, action, version, error = response
                if error is None:
                    action = np.asarray(action, np.float32).reshape(-1)[
                        : self._action_size
                    ]
                    if version is None:
                        # Staleness unknown, action real (see class doc).
                        self.version_unknown_actions += 1
                        stamp = (
                            self._last_known_version
                            if self._last_known_version is not None
                            else -1
                        )
                        return action, stamp
                    self._last_known_version = int(version)
                    return action, int(version)
                break  # typed failure: next attempt
        self.fallback_actions += 1
        return (
            self._rng.uniform(-1.0, 1.0, size=self._action_size).astype(
                np.float32
            ),
            -1,
        )


class RouterGateway:
    """Driver-side forwarder: gateway queues -> FleetRouter -> replies.

    One thread drains the shared request queue and submits each request
    to the router (non-blocking: the reply is posted from the router
    future's done callback, so a slow replica never serializes other
    actors' requests behind it).
    """

    def __init__(
        self,
        router,
        actor_ids: Sequence[str],
        mp_context=None,
        feature_key: str = "state",
        output_key: str = "inference_output",
        deadline_ms: float = 2000.0,
        version_translate: Optional[Dict[int, int]] = None,
    ):
        import multiprocessing
        import threading

        self._router = router
        # Artifact model_versions are timestamp dir names; the loop keeps
        # a {model_version: publish_counter} map (mutated on each
        # publish, read here under the GIL) so episode stamps — and
        # therefore staleness — count PUBLISHES, not timestamps.
        self._version_translate = (
            version_translate if version_translate is not None else {}
        )
        self._ctx = mp_context or multiprocessing.get_context("spawn")
        self.request_q = self._ctx.Queue()
        self.response_queues = {
            actor_id: self._ctx.Queue() for actor_id in actor_ids
        }
        self._feature_key = feature_key
        self._output_key = output_key
        self._deadline_ms = deadline_ms
        self._closed = False
        self.requests_served = 0
        self.requests_failed = 0
        self.unknown_version_replies = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "RouterGateway":
        self._thread.start()
        return self

    def actor_queues(self, actor_id: str):
        return self.request_q, self.response_queues[actor_id]

    def _reply(self, actor_id: str, message) -> None:
        out = self.response_queues.get(actor_id)
        if out is not None:
            best_effort(out.put, message)

    def _loop(self) -> None:
        while not self._closed:
            try:
                actor_id, req_id, obs = self.request_q.get(timeout=0.1)
            except queue.Empty:
                continue
            except (OSError, ValueError):
                return
            features = {self._feature_key: np.asarray(obs)[None]}
            try:
                future = self._router.submit(
                    features, deadline_ms=self._deadline_ms
                )
            except Exception as err:
                self.requests_failed += 1
                self._reply(
                    actor_id, (req_id, None, -1, f"{type(err).__name__}: {err}")
                )
                continue

            def on_done(f, actor_id=actor_id, req_id=req_id):
                error = f.error()
                if error is not None:
                    self.requests_failed += 1
                    self._reply(
                        actor_id,
                        (req_id, None, -1,
                         f"{type(error).__name__}: {error}"),
                    )
                    return
                response = f.result(0)
                action = np.asarray(
                    response.outputs[self._output_key]
                )[0]
                self.requests_served += 1
                raw_version = int(response.model_version)
                version = self._version_translate.get(raw_version)
                if version is None:
                    # The artifact's model_version has no publish-counter
                    # mapping yet (a reply racing the first publish).
                    # Ship version=None — "staleness unknown" — and
                    # count it; the actor-side client stamps its last
                    # KNOWN counter (or -1 on first contact), never a
                    # fabricated fresh 0 and never the raw timestamp
                    # (which would poison staleness).
                    self.unknown_version_replies += 1
                self._reply(
                    actor_id, (req_id, action, version, None)
                )

            future.add_done_callback(on_done)

    def stop(self) -> None:
        self._closed = True
        self._thread.join(5.0)
        best_effort(self.request_q.close)
        for response_q in self.response_queues.values():
            best_effort(response_q.close)


class EpisodeCollector:
    """Runs episodes on a PoseToyEnv-shaped env and serializes them.

    `collect()` returns (wire_records, info): one serialized tf.Example
    per transition, plus the episode's policy version, raw/relabeled
    reward and step count. Rewards are relabeled through
    `binary_success_threshold` (the env's raw reward is a negative
    distance; downstream reward-weighted losses need non-negative
    weights — research/pose_env/episode_to_transitions.py).
    """

    def __init__(
        self,
        env,
        policy_client,
        binary_success_threshold: float = -0.35,
        max_steps: int = 1,
    ):
        self._env = env
        self._policy = policy_client
        self._threshold = binary_success_threshold
        self._max_steps = max_steps

    def collect(self) -> Tuple[List[bytes], Dict[str, Any]]:
        self._env.reset_task()
        obs = self._env.reset()
        episode = []
        versions: List[int] = []
        raw_reward = 0.0
        for _ in range(self._max_steps):
            chaos.maybe_fire("actor_step")
            action, version = self._policy.act(obs)
            versions.append(version)
            new_obs, reward, done, debug = self._env.step(action)
            episode.append((obs, action, reward, new_obs, done, debug))
            raw_reward += float(reward)
            obs = new_obs
            if done:
                break
        examples = episode_to_transitions_pose_toy(
            episode, binary_success_threshold=self._threshold
        )
        records = [example.SerializeToString() for example in examples]
        successes = sum(
            1 for (_, _, reward, _, _, _) in episode
            if reward > self._threshold
        )
        info = {
            "policy_version": min(versions) if versions else -1,
            "raw_reward": raw_reward,
            "successes": successes,
            "steps": len(episode),
            # Successful episodes get double weight under prioritized
            # sampling; failures still replay (exploration signal).
            "priority": 1.0 + float(successes),
        }
        return records, info


def actor_main(
    actor_id: int,
    replay_queues=None,
    gateway_queues=None,
    num_episodes: int = 0,
    seed: int = 0,
    binary_success_threshold: float = -0.35,
    hidden_drift: bool = False,
    report_q=None,
    throttle_s: float = 0.0,
    shard_specs=None,
    stop_event=None,
) -> None:
    """Actor process entry (spawn-safe: queue objects ride the args).

    Collects `num_episodes` episodes (0 = until the replay append path
    raises, i.e. supervisor teardown), appending each whole episode with
    its policy version + priority. The replay wire is either the single
    service's `replay_queues` pair, or — sharded topology —
    `shard_specs`, the per-shard client recipes from
    `ShardedReplayService.client_specs` (socket specs are just paths:
    the shape a remote-host actor needs). Declares chaos scope
    `a<actor_id>` so seeded plans can target one actor
    (`a1/actor_step:3:kill` is the actor-SIGKILL-mid-episode fault).
    Posts a final summary dict on `report_q` when given.
    """
    from tensor2robot_tpu.research.pose_env.pose_env import PoseToyEnv

    chaos.set_scope(f"a{actor_id}")
    if shard_specs is not None:
        from tensor2robot_tpu.replay.sharded import (
            sharded_client_from_specs,
        )

        replay: Any = sharded_client_from_specs(
            shard_specs, f"actor-{actor_id}", seed=seed
        )
    else:
        request_q, response_q = replay_queues
        replay = ReplayClient(
            f"actor-{actor_id}", request_q, response_q, seed=seed
        )
    if gateway_queues is not None:
        policy: Any = GatewayPolicyClient(
            f"actor-{actor_id}", gateway_queues[0], gateway_queues[1],
            seed=seed,
        )
    else:
        policy = RandomPolicyClient(seed=seed)
    env = PoseToyEnv(seed=seed, hidden_drift=hidden_drift)
    collector = EpisodeCollector(
        env, policy, binary_success_threshold=binary_success_threshold
    )
    episodes = 0
    appended = 0
    rewards: List[float] = []
    try:
        while num_episodes == 0 or episodes < num_episodes:
            if stop_event is not None and stop_event.is_set():
                break  # cooperative drain: report before the terminate
            records, info = collector.collect()
            episodes += 1
            rewards.append(info["raw_reward"])
            replay.append(
                records,
                policy_version=max(info["policy_version"], 0),
                priority=info["priority"],
            )
            appended += 1
            if throttle_s:
                time.sleep(throttle_s)
    finally:
        if shard_specs is not None:
            # Spilled episodes get one last drain before the report, so
            # the bench's append accounting sees what actually landed.
            best_effort(replay.flush_spill, 5.0)
        if report_q is not None:
            sharded_counters = dict(getattr(replay, "counters", {}))
            best_effort(
                report_q.put,
                {
                    "actor_id": actor_id,
                    "pid": os.getpid(),
                    "episodes": episodes,
                    "appended": appended,
                    "mean_reward": (
                        float(np.mean(rewards)) if rewards else 0.0
                    ),
                    "fallback_actions": getattr(
                        policy, "fallback_actions", 0
                    ),
                    "version_unknown_actions": getattr(
                        policy, "version_unknown_actions", 0
                    ),
                    "replay_counters": sharded_counters,
                },
            )
