"""Learner-side replay consumption: wire bytes -> spec-parsed batches.

`ReplayInputGenerator` is the bridge between the replay buffer and the
trainer: it draws raw tf.Example wire-bytes records (zero-copy spans
out of sealed segments) and parses them with the model's own in-specs
through `data/wire.FastSpecParser` — the spans are read in place at
sample time, never on the append path — with `SpecParser` as the
per-batch fallback oracle, the same discipline `data/dataset.py` uses.

Two sources, one contract:

  * `source="dir"` — reads sealed segments straight off disk with a
    private FIFO sampler. Deterministic: given the same directory
    contents, batch k is the same records for every run — which is what
    lets `train_eval_model`'s host-batch realignment (islice to the
    restored step) restore the SAMPLING STATE of a crashed learner
    exactly: the resumed run consumes batches [start_step:] of the very
    schedule the uninterrupted run would have drawn, so no sealed
    segment is ever double-sampled relative to that schedule. Sampled
    (segment_seq, record_index) coordinates are logged per batch
    (`coords_log`) as the audit trail the crash tests pin.
  * `source=<ReplayClient>` — samples through the live service (the
    online loop): blocks politely while the buffer is still empty
    (actors haven't sealed a first segment yet), rides client retries
    through service restarts, and surfaces the service's staleness
    numbers per batch.

Batches are packed as {features/..., labels/...} TensorSpecStructs —
exactly what `train_eval_model` consumes.
"""

from __future__ import annotations

import hashlib
import logging
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu.data.parser import SpecParser
from tensor2robot_tpu.utils.backoff import Backoff
from tensor2robot_tpu.data.wire import FastSpecParser
from tensor2robot_tpu.data.input_generators import AbstractInputGenerator
from tensor2robot_tpu.replay import segment as segment_lib
from tensor2robot_tpu.replay.service import (
    ReplayClient,
    ReplayEmpty,
    ReplayUnavailable,
    _FifoSampler,
)
from tensor2robot_tpu.specs import TensorSpecStruct

_log = logging.getLogger(__name__)

__all__ = ["ReplayInputGenerator"]


class _DirFifo(_FifoSampler):
    """Deterministic FIFO over the sealed segments of a directory: the
    dir-mode sampler (no service round trip, no shared cursor).

    The cursor/wrap schedule IS `service._FifoSampler` — one
    implementation, so the dir-mode and service-mode FIFO schedules can
    never silently diverge (the crash-consistency contract names them
    as the same schedule). This wrapper adds discovery: sealed files
    are immutable, so `refresh` lists names cheaply and pays the
    full-file CRC validation once per NEWLY seen seq, not per poll.
    """

    def __init__(self, root: str):
        super().__init__(root)
        self._checked: set = set()
        self.refresh()

    def refresh(self) -> None:
        for seq in segment_lib.sealed_segment_seqs(self._root):
            if seq in self._checked:
                continue
            self._checked.add(seq)
            if segment_lib.validate_segment(self._root, seq) is None:
                self.note_sealed(seq)

    def empty(self) -> bool:
        return not self._order

    def draw_records(self, n: int):
        coords = self.draw(n)
        records: List[bytes] = []
        versions: List[int] = []
        for record in self.read(coords):
            records.append(bytes(record.payload))
            versions.append(record.policy_version)
        return records, coords, versions


class ReplayInputGenerator(AbstractInputGenerator):
    """Batches for the learner out of a replay directory or service.

    Args:
      replay_root: the replay directory (dir mode reads it directly;
        also used for bookkeeping in service mode).
      batch_size: records per batch.
      client: a ReplayClient — service mode — or a ShardedReplayClient
        (replay/sharded.py; same sample() contract, shard-qualified
        coordinates). None -> dir mode.
      wait_timeout_s: how long to wait for a first sealed segment
        before giving up (both modes; bring-up patience).
      refresh: dir mode only — rescan for newly sealed segments when
        the FIFO wraps (the in-process online loop); off (the default)
        the segment set is frozen at iterator start, which is what the
        deterministic crash tests want.
    """

    def __init__(
        self,
        replay_root: str,
        batch_size: int = 32,
        client: Optional[ReplayClient] = None,
        wait_timeout_s: float = 60.0,
        refresh: bool = False,
        staleness_anchor=None,
    ):
        super().__init__(batch_size=batch_size)
        self._root = replay_root
        self._client = client
        self._wait_timeout_s = wait_timeout_s
        self._refresh = refresh
        # Dir mode computes staleness itself (there is no service to ask):
        # anchor() -> the current published policy version.
        self._staleness_anchor = staleness_anchor
        self._parser: Optional[SpecParser] = None
        self._fast: Optional[FastSpecParser] = None
        # Observability: per-batch audit trail + running digest over the
        # sampled (segment_seq, record_index) schedule. The digest is
        # printable from a subprocess trainer, which is how the
        # crash-consistency suite proves a resumed run continued the
        # uninterrupted run's exact sample schedule. The log is BOUNDED
        # (oldest batches trimmed past coords_log_limit, trim count in
        # coords_log_dropped): a multi-day online learner must not grow
        # an unbounded coordinate list — the digest stays complete.
        self.coords_log: List[List[Tuple[int, int]]] = []
        self.coords_log_limit = 4096
        self.coords_log_dropped = 0
        self.batches_drawn = 0
        self.last_staleness: Dict[str, float] = {}
        self._schedule_digest = hashlib.sha256()

    # -- parsing ---------------------------------------------------------------

    def _ensure_parsers(self) -> None:
        if self._parser is None:
            spec = self.combined_spec()
            self._parser = SpecParser(spec)
            fast = FastSpecParser(spec)
            self._fast = fast if fast.supported else None
            if self._fast is None:
                _log.info(
                    "replay fast parse unsupported for this spec; using "
                    "SpecParser oracle"
                )

    def _parse(self, records: List[bytes]) -> TensorSpecStruct:
        self._ensure_parsers()
        if self._fast is not None:
            try:
                return self._fast.parse_batch(records)
            except Exception:
                self._fast.fallbacks += 1
                _log.warning(
                    "replay fast parse failed for a batch; re-parsing "
                    "with SpecParser"
                )
        return self._parser.parse_batch(records)

    def schedule_digest(self) -> str:
        """sha256 over every (segment_seq, record_index) sampled so far,
        in order — equal digests == identical sample schedules."""
        return self._schedule_digest.hexdigest()

    def _note_batch(self, coords) -> None:
        # Coordinates are (segment_seq, record_index) pairs from a
        # single buffer/service, or shard-qualified (shard, segment_seq,
        # record_index) triples from the sharded client — logged and
        # digested uniformly. The 2-tuple digest bytes are UNCHANGED
        # ("a:b;"), which is what keeps the pre-shard crash-consistency
        # schedule pins bitwise-stable.
        coords = [tuple(int(part) for part in coord) for coord in coords]
        self.coords_log.append(coords)
        if len(self.coords_log) > self.coords_log_limit:
            drop = len(self.coords_log) - self.coords_log_limit
            del self.coords_log[:drop]
            self.coords_log_dropped += drop
        self.batches_drawn += 1
        for coord in coords:
            self._schedule_digest.update(
                (":".join(str(part) for part in coord) + ";").encode()
            )

    # -- batch stream ----------------------------------------------------------

    def _wait_predicate(self, ready, what: str):
        # Seeded, hard-bounded poll (utils/backoff.py): the generator's
        # bring-up wait cannot exceed its configured budget by more than
        # one capped delay, and the cadence replays under a fixed seed.
        result = Backoff(
            base_ms=50.0, cap_ms=150.0, factor=1.0, seed=3
        ).poll(ready, total_s=self._wait_timeout_s)
        if result:
            return result
        raise ReplayEmpty(
            f"replay buffer produced no {what} within "
            f"{self._wait_timeout_s}s"
        )

    def _dir_batches(self) -> Iterator[TensorSpecStruct]:
        fifo = _DirFifo(self._root)

        def ready():
            fifo.refresh()
            return not fifo.empty()

        self._wait_predicate(ready, "sealed segment")
        while True:
            if self._refresh:
                fifo.refresh()
            records, coords, versions = fifo.draw_records(self._batch_size)
            if self._staleness_anchor is not None:
                anchor = int(self._staleness_anchor())
                staleness = [max(0, anchor - v) for v in versions]
                self.last_staleness = {
                    "staleness_mean": (
                        sum(staleness) / max(len(staleness), 1)
                    ),
                    "staleness_max": float(max(staleness, default=0)),
                }
            self._note_batch(coords)
            yield self._pack(self._parse(records))

    def _service_batches(self) -> Iterator[TensorSpecStruct]:
        client = self._client
        assert client is not None
        while True:
            try:
                records, coords, info = client.sample(self._batch_size)
            except (ReplayEmpty, ReplayUnavailable) as err:
                # Bring-up or a service mid-restart: wait it out within
                # the generator's own patience, then surface. Each poll
                # is SHORT (no retries, 2 s) so wait_timeout_s is a real
                # bound — the client's full retry budget per poll would
                # multiply the configured patience.
                def ready():
                    try:
                        return client.sample(
                            self._batch_size, wait_for_data=False,
                            timeout_s=2.0, retries=0,
                        )
                    except (ReplayEmpty, ReplayUnavailable):
                        return None

                result = self._wait_predicate(ready, f"batch ({err})")
                records, coords, info = result
            self.last_staleness = dict(info)
            self._note_batch(coords)
            yield self._pack(self._parse(records))

    def _pack(self, parsed: TensorSpecStruct) -> TensorSpecStruct:
        out = TensorSpecStruct()
        for key, value in parsed.items():
            out[key] = np.asarray(value)
        return out

    def _create_dataset(self, mode: str) -> Iterator[TensorSpecStruct]:
        del mode  # replay data is mode-less: the specs already chose
        if self._client is not None:
            return self._service_batches()
        return self._dir_batches()
