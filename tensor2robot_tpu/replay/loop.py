"""The closed online loop: actors -> replay -> learner -> policy -> actors.

`OnlineLoop` wires the whole QT-Opt topology out of the pieces the repo
already has — research env actors (`replay/actor.py`), the replay
service (`replay/service.py`), the learner (`train/train_eval.py` over
a `ReplayInputGenerator`), the export path (`export/exporters.py`) and
the serving fleet (`serving/router.py`) — in two shapes:

  * **multi-process** (the default; `bench.py rl` and the slow soak):
    replay service + actor processes, optionally a FleetRouter over
    policy-server replicas with the RouterGateway feeding actors real
    fleet predictions; the learner runs in the driver and PUBLISHES a
    fresh policy at every checkpoint (export -> rolling fleet swap ->
    staleness anchor bump). Every process is individually SIGKILL-able,
    which is the point.
  * **in-process** (`in_process=True`; the tier-1 chaos twin): the same
    loop with the buffer in-process, actors as threads and a local
    policy client — every chaos site (`append`/`seal`/`sample`/
    `actor_step`/`publish_policy`) still fires, every counter still
    counts, no subprocess spend.

Policy publication rides the trainer's `after_checkpoint_saved` hook
(`PublishPolicyHook`): fires the `publish_policy` chaos site, exports
the current weights as a new artifact version, rolls the serving fleet
onto it, and advances the replay buffer's staleness anchor. Version
arithmetic is in *publishes* (1, 2, 3, ...): artifact model_versions
(timestamp dir names) are translated at the gateway so staleness is
always "how many publishes behind", not a timestamp delta.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from tensor2robot_tpu.hooks.hook_builder import Hook, HookBuilder
from tensor2robot_tpu.replay.actor import (
    EpisodeCollector,
    LocalPolicyClient,
    RandomPolicyClient,
    RouterGateway,
    actor_main,
)
from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.replay.input_generator import ReplayInputGenerator
from tensor2robot_tpu.replay.service import (
    ReplayBuffer,
    ReplayServiceHandle,
)
from tensor2robot_tpu.replay.sharded import (
    ShardedReplayClient,
    ShardedReplayService,
    local_shard_backends,
    shard_root,
)
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = ["LoopReport", "OnlineLoop", "PublishPolicyHook"]


@dataclasses.dataclass
class LoopReport:
    """What one loop run measured (the bench leg's raw material)."""

    learner_steps: int = 0
    episodes_appended: int = 0
    records_appended: int = 0
    samples_drawn: int = 0
    segments_sealed: int = 0
    episodes_lost: int = 0
    records_lost: int = 0
    replay_ratio: float = 0.0
    staleness_mean: float = 0.0
    staleness_max: int = 0
    publishes: int = 0
    replay_restarts: int = 0
    actors_killed: int = 0
    wall_s: float = 0.0
    episodes_per_s: float = 0.0
    samples_per_s: float = 0.0
    actor_reports: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    recovery: Dict[str, int] = dataclasses.field(default_factory=dict)
    # False when the post-run service stats read failed: the loss/sample
    # counters above are then absent, not zero — acceptance gates must
    # treat the run as unmeasured, never as lossless.
    stats_ok: bool = True
    # Serving-degradation split (distinct meanings that used to share a
    # -1 stamp): fallback = the fleet never answered, the action is
    # random; version-unknown = a REAL fleet action whose publish age
    # could not be determined. Counted separately across all actors.
    fallback_actions: int = 0
    version_unknown_actions: int = 0
    # Sharded-fabric accounting (empty/zero for the single service).
    shards: int = 1
    per_shard: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    coverage_lost_draws: List[int] = dataclasses.field(
        default_factory=list
    )
    spill_replayed: int = 0
    spill_dropped_episodes: int = 0
    appends_deduped: int = 0
    shards_unreachable: List[int] = dataclasses.field(
        default_factory=list
    )

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class PublishPolicyHook(Hook):
    """after_checkpoint_saved -> chaos site + export + fleet swap + anchor.

    `publish_fn(step, state) -> int` does the mode-specific work and
    returns the new publish counter; the hook only owns the chaos site
    and failure containment (a failed publish is logged and counted —
    the learner must keep training on the old policy, not die)."""

    def __init__(self, publish_fn: Callable[[int, Any], int]):
        self._publish_fn = publish_fn
        self.publishes = 0
        self.failures = 0

    def after_checkpoint_saved(self, ctx) -> None:
        try:
            # Chaos site INSIDE the containment: an injected fault here
            # is a publish-path fault (export died, fleet swap failed)
            # and must be survived exactly like a real one. A `kill`
            # clause still takes the whole learner down — that is the
            # learner-preemption fault, pinned separately.
            chaos.maybe_fire("publish_policy")
            self.publishes = self._publish_fn(ctx.step, ctx.state)
        except Exception:
            self.failures += 1
            _log.exception(
                "policy publish at step %d failed; actors keep the "
                "previous version", ctx.step,
            )


class _PublishHookBuilder(HookBuilder):
    """Hands the trainer's CompiledModel to the loop (the export path
    needs export_variables) and installs the publish hook."""

    def __init__(
        self,
        hook: PublishPolicyHook,
        on_trainer: Optional[Callable[[Any], None]] = None,
    ):
        self._hook = hook
        self._on_trainer = on_trainer

    def create_hooks(self, t2r_model, trainer=None) -> List[Hook]:
        del t2r_model
        if self._on_trainer is not None:
            self._on_trainer(trainer)
        return [self._hook]


class OnlineLoop:
    """Harness for the closed loop; the caller owns pacing and chaos.

    Typical use (multi-process):

        loop = OnlineLoop(root, num_actors=2, use_router=True).start()
        loop.run_learner(max_steps=30, save_steps=10)  # blocks
        report = loop.stop()

    Chaos controls for the bench/suites: `kill_replay_service()` and
    `kill_actor(i)` SIGKILL live processes mid-run (the service handle
    respawns the service; a killed actor stays dead and is counted).
    """

    def __init__(
        self,
        root: str,
        num_actors: int = 2,
        episodes_per_actor: int = 0,  # 0 = collect until stopped
        batch_size: int = 8,
        seal_episodes: int = 4,
        seal_bytes: Optional[int] = None,
        sampler: Optional[str] = None,
        seed: int = 7,
        in_process: bool = False,
        use_router: bool = False,
        router: Any = None,
        binary_success_threshold: float = -0.35,
        model_fn: Optional[Callable[[], Any]] = None,
        wait_timeout_s: float = 120.0,
        actor_throttle_s: float = 0.0,
        shards: Optional[int] = None,
        transport: Optional[str] = None,
    ):
        self.root = root
        self.replay_root = os.path.join(root, "replay")
        self.model_dir = os.path.join(root, "learner")
        self.export_dir = self.model_dir  # exporters nest export/ inside
        self.num_actors = num_actors
        self.episodes_per_actor = episodes_per_actor
        self.batch_size = batch_size
        self.seal_episodes = seal_episodes
        self.seal_bytes = seal_bytes
        self.sampler = sampler
        self.seed = seed
        self.in_process = in_process
        self.use_router = use_router
        # Sharded topology: >1 = consistent-hash placement over N shard
        # services (replay/sharded.py); the transport picks the wire
        # (socket = the cross-host fabric, queue = single-host default).
        self.shards = (
            t2r_flags.get_int("T2R_REPLAY_SHARDS")
            if shards is None else max(1, shards)
        )
        self.transport = transport
        self._router = router
        self._threshold = binary_success_threshold
        self._model_fn = model_fn or self._default_model_fn
        self._wait_timeout_s = wait_timeout_s
        self._actor_throttle_s = actor_throttle_s

        self._service: Optional[ReplayServiceHandle] = None
        self._sharded: Optional[ShardedReplayService] = None
        self._sharded_client: Optional[ShardedReplayClient] = None
        self._shard_buffers: List[ReplayBuffer] = []
        self._buffer: Optional[ReplayBuffer] = None
        self._gateway: Optional[RouterGateway] = None
        self._actor_processes: List[Any] = []
        self._actor_threads: List[threading.Thread] = []
        self._actor_stop = threading.Event()
        self._actor_stop_event = None  # mp.Event, multi-process modes
        self._report_q = None
        self._publish_hook: Optional[PublishPolicyHook] = None
        self._version_counter = 0
        self._version_translate: Dict[int, int] = {}
        self._exporter = None
        self._compiled_for_export = None
        self._driver_client = None
        self._learner_client = None
        self._generator: Optional[ReplayInputGenerator] = None
        self._learner_steps = 0
        self._actors_killed = 0
        self._t_start = 0.0
        self._in_process_episodes = 0

    @staticmethod
    def _default_model_fn():
        from tensor2robot_tpu.research.pose_env.pose_env_models import (
            PoseEnvRegressionModel,
        )

        return PoseEnvRegressionModel()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "OnlineLoop":
        os.makedirs(self.replay_root, exist_ok=True)
        self._t_start = time.monotonic()
        if self.in_process:
            self._start_in_process()
        else:
            self._start_multi_process()
        return self

    def _start_in_process(self) -> None:
        if self.shards > 1:
            # The tier-1 sharded twin: N in-process buffers behind the
            # SAME placement/failover/counting client the multi-process
            # fabric uses — every sharded code path, zero subprocesses.
            self._shard_buffers = [
                ReplayBuffer(
                    shard_root(self.replay_root, shard),
                    seal_episodes=self.seal_episodes,
                    seal_bytes=self.seal_bytes,
                    sampler=self.sampler,
                    seed=self.seed,
                )
                for shard in range(self.shards)
            ]
            self._sharded_client = ShardedReplayClient(
                local_shard_backends(self._shard_buffers),
                client_id="loop",
                seed=self.seed,
            )
        else:
            self._buffer = ReplayBuffer(
                self.replay_root,
                seal_episodes=self.seal_episodes,
                seal_bytes=self.seal_bytes,
                sampler=self.sampler,
                seed=self.seed,
            )

        def actor_thread(index: int) -> None:
            from tensor2robot_tpu.research.pose_env.pose_env import (
                PoseToyEnv,
            )

            policy = self._local_policy_client(seed=self.seed + index)
            env = PoseToyEnv(seed=self.seed + index)
            collector = EpisodeCollector(
                env, policy, binary_success_threshold=self._threshold
            )
            sink = self._sharded_client or self._buffer
            episodes = 0
            while not self._actor_stop.is_set() and (
                self.episodes_per_actor == 0
                or episodes < self.episodes_per_actor
            ):
                records, info = collector.collect()
                sink.append(
                    records,
                    policy_version=max(info["policy_version"], 0),
                    priority=info["priority"],
                )
                episodes += 1
                self._in_process_episodes += 1
                if self._actor_throttle_s:
                    self._actor_stop.wait(self._actor_throttle_s)

        for index in range(self.num_actors):
            thread = threading.Thread(
                target=actor_thread, args=(index,), daemon=True
            )
            thread.start()
            self._actor_threads.append(thread)

    def _local_policy_client(self, seed: int):
        """In-process actors read the loop's published version; actions
        stay random (the in-process twin tests the PLUMBING — append/
        seal/sample/publish/staleness — not fleet serving)."""
        random_client = RandomPolicyClient(seed=seed)

        loop = self

        class _Client:
            def act(self, obs):
                action, _ = random_client.act(obs)
                return action, loop._version_counter

        return _Client()

    def _start_multi_process(self) -> None:
        client_ids = [f"actor-{i}" for i in range(self.num_actors)] + [
            "learner", "driver",
        ]
        config = {
            "seal_episodes": self.seal_episodes,
            "seal_bytes": self.seal_bytes,
            "sampler": self.sampler,
            "seed": self.seed,
        }
        if self.shards > 1:
            self._sharded = ShardedReplayService(
                self.replay_root,
                self.shards,
                client_ids,
                config=config,
                transport=self.transport,
            ).start()
            mp_ctx = self._sharded.handles[0]._ctx
        else:
            self._service = ReplayServiceHandle(
                self.replay_root,
                client_ids,
                config=config,
                transport=self.transport,
            ).start()
            mp_ctx = self._service._ctx
        gateway_queue_pairs: List[Any] = [None] * self.num_actors
        if self.use_router:
            if self._router is None:
                raise ValueError(
                    "use_router=True needs a started FleetRouter passed "
                    "as router= (the loop does not own fleet lifecycle)"
                )
            actor_ids = [f"actor-{i}" for i in range(self.num_actors)]
            self._gateway = RouterGateway(
                self._router,
                actor_ids,
                mp_context=mp_ctx,
                version_translate=self._version_translate,
            ).start()
            gateway_queue_pairs = [
                self._gateway.actor_queues(actor_id)
                for actor_id in actor_ids
            ]
        self._report_q = mp_ctx.Queue()
        self._actor_stop_event = mp_ctx.Event()
        for index in range(self.num_actors):
            replay_kwargs: Dict[str, Any] = (
                {"shard_specs": self._sharded.client_specs(
                    f"actor-{index}")}
                if self._sharded is not None
                else {"replay_queues": self._service.client_queues(
                    f"actor-{index}")}
            )
            process = mp_ctx.Process(
                target=actor_main,
                kwargs=dict(
                    actor_id=index,
                    gateway_queues=gateway_queue_pairs[index],
                    num_episodes=self.episodes_per_actor,
                    seed=self.seed + index,
                    binary_success_threshold=self._threshold,
                    report_q=self._report_q,
                    throttle_s=self._actor_throttle_s,
                    stop_event=self._actor_stop_event,
                    **replay_kwargs,
                ),
                daemon=True,
            )
            process.start()
            self._actor_processes.append(process)

    def register_artifact_version(
        self, model_version: int, publish_counter: int = 0
    ) -> None:
        """Maps a pre-existing artifact's model_version (the bootstrap
        export the fleet booted on) to a publish counter, so episodes
        collected before the first publish stamp 0, not a timestamp."""
        self._version_translate[int(model_version)] = publish_counter

    # -- chaos controls --------------------------------------------------------

    def kill_replay_service(self) -> Optional[int]:
        if self._sharded is not None:
            return self.kill_shard(0)
        if self._service is None:
            raise RuntimeError("no replay service in in-process mode")
        return self._service.kill()

    def kill_shard(self, shard: int) -> Optional[int]:
        """SIGKILL one shard's service process (its supervisor respawns
        it); the fabric spills/fails over meanwhile — that is the leg."""
        if self._sharded is None:
            raise RuntimeError("no sharded replay service in this mode")
        return self._sharded.kill_shard(shard)

    def kill_actor(self, index: int) -> Optional[int]:
        process = self._actor_processes[index]
        if not process.is_alive():
            return None
        pid = process.pid
        os.kill(pid, 9)
        self._actors_killed += 1
        return pid

    # -- the learner -----------------------------------------------------------

    def _publish(self, step: int, state) -> int:
        """Export the current weights, roll the fleet, bump the anchor."""
        self._version_counter += 1
        if self._exporter is not None and not self.in_process:
            path = self._exporter.maybe_export(
                step=step,
                state=state,
                eval_metrics={"loss": 0.0},
                compiled=self._compiled_for_export,
                model_dir=self.model_dir,
            )
            if path is not None:
                base = os.path.basename(path.rstrip("/"))
                if base.isdigit():
                    self._version_translate[int(base)] = (
                        self._version_counter
                    )
            if self._router is not None:
                self._router.rolling_swap()
        if self._buffer is not None:
            self._buffer.set_policy_version(self._version_counter)
        elif self._sharded_client is not None:
            self._sharded_client.set_policy_version(self._version_counter)
        elif self._service is not None or self._sharded is not None:
            self._driver().set_policy_version(self._version_counter)
        return self._version_counter

    def _driver(self):
        """ONE long-lived driver client: a fresh client per call would
        share the response queue with its predecessors (reply aliasing
        is guarded by opaque tokens, but one instance is simply right)."""
        if self._driver_client is None:
            if self._sharded is not None:
                self._driver_client = self._sharded.client("driver")
            else:
                self._driver_client = self._service.client(
                    "driver", timeout_s=10.0, retries=3
                )
        return self._driver_client

    def run_learner(
        self,
        max_steps: int = 20,
        save_steps: int = 10,
        publish: bool = True,
        export_buckets=(1,),
        learner_kwargs: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, float]:
        """Blocks training the learner over replay samples; publishes at
        every checkpoint when `publish`."""
        from tensor2robot_tpu.train import train_eval as te

        model = self._model_fn()
        if self._sharded is not None:
            client: Any = self._sharded.client("learner")
        elif self._sharded_client is not None:
            client = self._sharded_client  # in-process sharded twin
        elif self._service is not None:
            client = self._service.client("learner", timeout_s=30.0)
        else:
            client = None
        self._learner_client = client
        self._generator = ReplayInputGenerator(
            self.replay_root,
            batch_size=self.batch_size,
            client=client,
            wait_timeout_s=self._wait_timeout_s,
            refresh=client is None,
            staleness_anchor=(
                (lambda: self._version_counter) if client is None else None
            ),
        )
        hook_builders = []
        if publish:
            from tensor2robot_tpu.export.exporters import LatestExporter

            if not self.in_process:
                self._exporter = LatestExporter(
                    name="latest",
                    warmup_batch_sizes=tuple(export_buckets),
                )
            self._publish_hook = PublishPolicyHook(self._publish)

            def on_trainer(trainer):
                self._compiled_for_export = trainer

            hook_builders.append(
                _PublishHookBuilder(self._publish_hook, on_trainer)
            )
        final = te.train_eval_model(
            model,
            input_generator_train=self._generator,
            model_dir=self.model_dir,
            max_train_steps=max_steps,
            eval_steps=None,
            save_checkpoints_steps=save_steps,
            log_every_steps=max(save_steps, 1),
            seed=self.seed,
            hook_builders=hook_builders,
            **(learner_kwargs or {}),
        )
        # The step the learner ACTUALLY reached, read off the final
        # durable checkpoint (train_eval_model blesses it at exit) —
        # never assume max_steps: the bench acceptance gate compares
        # this across the chaos/fault-free twins, and a silently
        # under-trained leg must FAIL that gate, not sail through.
        from tensor2robot_tpu.train import durability

        actual = durability.latest_durable_step(self.model_dir)
        self._learner_steps = actual if actual is not None else 0
        return final

    # -- teardown + report -----------------------------------------------------

    def _merge_fabric_counters(
        self, report: LoopReport, client: ShardedReplayClient
    ) -> None:
        self._merge_fabric_counter_dict(report, client.counters)

    @staticmethod
    def _merge_fabric_counter_dict(
        report: LoopReport, counters: Dict[str, Any]
    ) -> None:
        """Folds one sharded client's degradation counters into the
        report — every client (each actor's, the learner's) keeps its
        own, and the fabric-wide number is their sum."""
        if not counters:
            return
        report.spill_replayed += counters.get("spill_replayed", 0)
        report.spill_dropped_episodes += counters.get(
            "spill_dropped_episodes", 0
        )
        report.appends_deduped += counters.get("appends_deduped", 0)
        lost = counters.get("coverage_lost_draws") or []
        if not report.coverage_lost_draws:
            report.coverage_lost_draws = [0] * len(lost)
        for shard, count in enumerate(lost):
            if shard < len(report.coverage_lost_draws):
                report.coverage_lost_draws[shard] += count

    def stop(self, timeout_s: float = 30.0) -> LoopReport:
        report = LoopReport()
        report.wall_s = time.monotonic() - self._t_start
        report.learner_steps = self._learner_steps
        report.actors_killed = self._actors_killed
        report.shards = self.shards
        if self._publish_hook is not None:
            report.publishes = self._publish_hook.publishes
        self._actor_stop.set()
        for thread in self._actor_threads:
            thread.join(timeout_s)
        stats: Dict[str, Any] = {}
        if self._buffer is not None:
            stats = self._buffer.stats()
            self._buffer.close(seal_tail=True)
        if self._sharded_client is not None:
            # In-process sharded twin: the shared client holds the
            # fabric counters; seal + close the buffers it fronts.
            stats = self._sharded_client.stats()
            self._merge_fabric_counters(report, self._sharded_client)
            for buffer in self._shard_buffers:
                buffer.close(seal_tail=True)
        if self._service is not None or self._sharded is not None:
            # Cooperative actor drain FIRST: the stop event lets each
            # actor finish its in-flight episode, flush any spill, and
            # post its report (spill/fallback counters) before the
            # hard-terminate backstop below.
            if self._actor_stop_event is not None:
                self._actor_stop_event.set()
            for process in self._actor_processes:
                process.join(3.0)
            try:
                if self._sharded is not None:
                    # A shard SIGKILLed moments before stop() is mid-
                    # respawn right now; give each supervisor a bounded
                    # window to republish before the stats read calls
                    # it unreachable (stats_ok=False is for shards that
                    # STAY dark, not for losing a boot race).
                    for handle in self._sharded.handles:
                        handle.wait_ready(10.0)
                stats = self._driver().stats()
                if self._sharded is not None and stats.get(
                    "shards_unreachable"
                ):
                    # Partial totals are not measured totals: a shard
                    # whose counters could not be read means every
                    # summed gate below would under-count.
                    report.stats_ok = False
                    report.shards_unreachable = list(
                        stats["shards_unreachable"]
                    )
            except Exception:
                # NOT silently zeroed: fabricated-zero loss counters
                # would pass every acceptance gate. The report says the
                # stats read itself failed; gates must check stats_ok.
                _log.exception("post-run replay stats read failed")
                stats = {}
                report.stats_ok = False
            report.replay_restarts = (
                self._sharded.respawns
                if self._sharded is not None
                else self._service.respawns
            )
            for process in self._actor_processes:
                if process.is_alive():
                    process.terminate()
                    process.join(5.0)
            if self._report_q is not None:
                while True:
                    try:
                        report.actor_reports.append(
                            self._report_q.get_nowait()
                        )
                    except Exception:
                        break
            if self._sharded is not None:
                self._sharded.stop()
            else:
                self._service.stop()
        if (
            isinstance(self._learner_client, ShardedReplayClient)
            and self._learner_client is not self._sharded_client
        ):
            self._merge_fabric_counters(report, self._learner_client)
        for actor_report in report.actor_reports:
            report.fallback_actions += actor_report.get(
                "fallback_actions", 0
            )
            report.version_unknown_actions += actor_report.get(
                "version_unknown_actions", 0
            )
            counters = actor_report.get("replay_counters") or {}
            self._merge_fabric_counter_dict(report, counters)
        if self._gateway is not None:
            self._gateway.stop()
        if stats:
            report.episodes_appended = stats.get(
                "episodes_appended_total", 0
            )
            report.records_appended = stats.get("records_appended_total", 0)
            report.samples_drawn = stats.get("samples_drawn", 0)
            report.segments_sealed = stats.get("segments_sealed", 0)
            report.episodes_lost = stats.get("episodes_lost_total", 0)
            report.records_lost = stats.get("records_lost_total", 0)
            report.replay_ratio = stats.get("replay_ratio", 0.0)
            staleness = stats.get("staleness_last", {})
            report.staleness_mean = staleness.get("staleness_mean", 0.0)
            report.staleness_max = int(stats.get("staleness_max_seen", 0))
            report.recovery = stats.get("recovery", {})
            per_shard = stats.get("per_shard")
            if per_shard is not None:
                report.per_shard = [dict(entry) for entry in per_shard]
                # Fabric-level recovery/staleness: sum the shards'
                # recovery sweeps; take the worst staleness any shard
                # has seen (a partitioned shard's lag must not average
                # away).
                merged_recovery: Dict[str, int] = {}
                for entry in report.per_shard:
                    for key, value in (entry.get("recovery") or {}).items():
                        merged_recovery[key] = (
                            merged_recovery.get(key, 0) + value
                        )
                    report.staleness_max = max(
                        report.staleness_max,
                        int(entry.get("staleness_max_seen", 0)),
                    )
                report.recovery = merged_recovery
        if self.in_process:
            report.episodes_appended = max(
                report.episodes_appended, self._in_process_episodes
            )
        if self._generator is not None and self._generator.batches_drawn:
            # Dir-mode sampling happens in the learner's generator, not
            # the buffer — its counters are the truth there; in service
            # mode they cross-check the service's.
            drawn = self._generator.batches_drawn * self.batch_size
            report.samples_drawn = max(report.samples_drawn, drawn)
            if report.records_appended:
                report.replay_ratio = (
                    report.samples_drawn / report.records_appended
                )
            staleness = self._generator.last_staleness
            if staleness:
                report.staleness_mean = staleness.get(
                    "staleness_mean", report.staleness_mean
                )
                report.staleness_max = max(
                    report.staleness_max,
                    int(staleness.get("staleness_max", 0)),
                )
        if report.wall_s > 0:
            report.episodes_per_s = (
                report.episodes_appended / report.wall_s
            )
            report.samples_per_s = report.samples_drawn / report.wall_s
        return report
