"""Durable episode segments: CRC-framed wire bytes + seal manifests.

The replay buffer's unit of durability. Actors append whole episodes as
serialized tf.Example records (wire bytes — nothing is parsed on the
append path); the writer accumulates them into an *open* segment file
and periodically *seals* it. Only sealed segments are ever sampled, so
the crash-loss bound of a replay-service SIGKILL is exactly the open
tail — and because every record is CRC-framed, that loss is *counted*
(salvage scans the torn tail) rather than guessed.

On-disk layout (`<root>/`):

    segment-00000012.seg        sealed data file (frames, below)
    segment-00000012.json       seal manifest (atomic tmp+replace)
    segment-00000013.open       the open tail (torn after a crash)
    replay_state.json           writer counters (atomic tmp+replace)
    replay.quarantine/          swept wreckage (forensics, never deleted)

Frame format (little-endian), one frame per transition record:

    u32 payload_length
    u32 crc32(payload)
    u32 episode_seq      (segment-local; groups a multi-step episode)
    u32 policy_version   (the policy that generated this transition)
    payload              (tf.Example wire bytes, untouched)

Seal discipline (mirrors train/durability.py's manifest contract):
flush + fsync the data file, write `segment-<seq>.json` with the
record/episode counts, byte size, whole-file CRC and per-episode
priorities via tmp + `os.replace`, then rename `.open` -> `.seg`.
Validation therefore never trusts a name: a `.seg` without a readable
manifest, or whose size/CRC disagree with it, is torn. Writers
quarantine torn forms at startup (`sweep_replay_dir`); readers only
ever skip.

Chaos hooks: the service fires `append` before a record batch is
written and `seal` before the manifest is published (testing/chaos.py),
so a seeded plan can SIGKILL mid-append or mid-seal and the suite can
pin what survives.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import struct
import time
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

_log = logging.getLogger(__name__)

__all__ = [
    "FRAME_HEADER",
    "SegmentManifest",
    "SegmentRecord",
    "SegmentReader",
    "SegmentWriter",
    "list_sealed_segments",
    "open_segment_path",
    "quarantine_root",
    "salvage_open_segment",
    "sealed_segment_path",
    "manifest_path",
    "sweep_replay_dir",
    "validate_segment",
]

FRAME_HEADER = struct.Struct("<IIII")  # length, crc32, episode_seq, version
_MANIFEST_VERSION = 1
QUARANTINE_DIRNAME = "replay.quarantine"


def sealed_segment_path(root: str, seq: int) -> str:
    return os.path.join(root, f"segment-{seq:08d}.seg")


def open_segment_path(root: str, seq: int) -> str:
    return os.path.join(root, f"segment-{seq:08d}.open")


def manifest_path(root: str, seq: int) -> str:
    return os.path.join(root, f"segment-{seq:08d}.json")


def quarantine_root(root: str) -> str:
    return os.path.join(root, QUARANTINE_DIRNAME)


@dataclasses.dataclass(frozen=True)
class SegmentManifest:
    """Seal-time inventory of one segment: what a reader may trust."""

    seq: int
    records: int
    episodes: int
    data_bytes: int
    data_crc32: int
    # Per-episode priorities in episode_seq order (prioritized sampling
    # draws by these; FIFO ignores them).
    priorities: Tuple[float, ...] = ()
    min_policy_version: int = 0
    max_policy_version: int = 0
    # Per-episode client-assigned identities in episode_seq order (""
    # for legacy/uid-less appends). Sealing an episode's uid is what
    # makes append retries idempotent ACROSS service crashes: a
    # respawned service rebuilds its dedup set from these, so a retry
    # of an append that sealed before the crash is recognized — and the
    # fabric's zero-duplicate audit (sharded chaos bench) counts
    # repeated uids across every shard's manifests.
    episode_uids: Tuple[str, ...] = ()

    def to_json(self) -> Dict:
        return {
            "version": _MANIFEST_VERSION,
            "seq": self.seq,
            "records": self.records,
            "episodes": self.episodes,
            "data_bytes": self.data_bytes,
            "data_crc32": self.data_crc32,
            "priorities": list(self.priorities),
            "min_policy_version": self.min_policy_version,
            "max_policy_version": self.max_policy_version,
            "episode_uids": list(self.episode_uids),
        }

    @staticmethod
    def from_json(payload: Dict) -> "SegmentManifest":
        return SegmentManifest(
            seq=int(payload["seq"]),
            records=int(payload["records"]),
            episodes=int(payload["episodes"]),
            data_bytes=int(payload["data_bytes"]),
            data_crc32=int(payload["data_crc32"]),
            priorities=tuple(float(p) for p in payload.get("priorities", ())),
            min_policy_version=int(payload.get("min_policy_version", 0)),
            max_policy_version=int(payload.get("max_policy_version", 0)),
            episode_uids=tuple(
                str(u) for u in payload.get("episode_uids", ())
            ),
        )


@dataclasses.dataclass(frozen=True)
class SegmentRecord:
    """One framed transition: a zero-copy span into the segment bytes."""

    episode_seq: int
    policy_version: int
    payload: memoryview  # into the reader's buffer — valid while it lives


def _atomic_write_json(path: str, payload: Dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class SegmentWriter:
    """Owns one open segment file; zero-parse episode appends + seal.

    Append granularity is the EPISODE: all of an episode's records are
    written in one buffered write followed by one flush, so a crash of
    the *caller* between episodes never tears a record, and a crash of
    this process mid-write tears at most the final episode (the salvage
    scan recovers every whole frame before the tear).
    """

    def __init__(self, root: str, seq: int):
        self.root = root
        self.seq = seq
        self.records = 0
        self.episodes = 0
        self.data_bytes = 0
        self._crc = 0
        self._priorities: List[float] = []
        self._uids: List[str] = []
        self._min_version: Optional[int] = None
        self._max_version: Optional[int] = None
        self._path = open_segment_path(root, seq)
        self._file = open(self._path, "ab")

    @property
    def path(self) -> str:
        return self._path

    def append_episode(
        self,
        transitions: Sequence[bytes],
        policy_version: int = 0,
        priority: float = 1.0,
        episode_uid: str = "",
    ) -> int:
        """Appends one whole episode (a sequence of wire-bytes records);
        returns this episode's segment-local episode_seq. `episode_uid`
        is the client-assigned identity sealed into the manifest (""
        = uid-less legacy append)."""
        if not transitions:
            raise ValueError("an episode must carry at least one record")
        episode_seq = self.episodes
        chunks: List[bytes] = []
        for payload in transitions:
            payload = bytes(payload)
            chunks.append(
                FRAME_HEADER.pack(
                    len(payload),
                    zlib.crc32(payload) & 0xFFFFFFFF,
                    episode_seq,
                    policy_version,
                )
            )
            chunks.append(payload)
        blob = b"".join(chunks)
        self._file.write(blob)
        self._file.flush()
        self._crc = zlib.crc32(blob, self._crc) & 0xFFFFFFFF
        self.data_bytes += len(blob)
        self.records += len(transitions)
        self.episodes += 1
        self._priorities.append(float(priority))
        self._uids.append(str(episode_uid or ""))
        if self._min_version is None or policy_version < self._min_version:
            self._min_version = policy_version
        if self._max_version is None or policy_version > self._max_version:
            self._max_version = policy_version
        return episode_seq

    def seal(self) -> Optional[SegmentManifest]:
        """Publishes this segment durably; returns its manifest (None for
        an empty segment, which is simply discarded).

        Order matters: fsync data -> atomic manifest write -> rename to
        the sealed name. A crash between any two steps leaves a form
        validate_segment()/sweep_replay_dir() classify as torn — never
        a sealed-looking segment a sampler would trust.
        """
        self._file.flush()
        os.fsync(self._file.fileno())
        self._file.close()
        if self.records == 0:
            os.unlink(self._path)
            return None
        manifest = SegmentManifest(
            seq=self.seq,
            records=self.records,
            episodes=self.episodes,
            data_bytes=self.data_bytes,
            data_crc32=self._crc,
            priorities=tuple(self._priorities),
            min_policy_version=self._min_version or 0,
            max_policy_version=self._max_version or 0,
            episode_uids=tuple(self._uids),
        )
        _atomic_write_json(manifest_path(self.root, self.seq), manifest.to_json())
        os.rename(self._path, sealed_segment_path(self.root, self.seq))
        return manifest

    def abort(self) -> None:
        """Closes the file handle without sealing. A NON-empty open tail
        stays on disk for the next sweep to count + quarantine; an empty
        one (a writer opened but never appended to — every clean
        shutdown leaves one) is just removed: it holds no data and no
        forensic value."""
        try:
            self._file.close()
        except OSError:
            pass
        if self.records == 0:
            try:
                if os.path.getsize(self._path) == 0:
                    os.unlink(self._path)
            except OSError:
                pass


def _scan_frames(buffer: bytes) -> Tuple[List[Tuple[int, int, int, int]], int]:
    """Scans CRC-valid whole frames from the start of `buffer`.

    Returns ([(offset, length, episode_seq, policy_version)], clean_end):
    spans of every frame whose header fits, whose payload fits, and whose
    CRC verifies, stopping at the first violation. clean_end is the byte
    offset where scanning stopped (== len(buffer) iff the file is whole).
    """
    spans: List[Tuple[int, int, int, int]] = []
    pos = 0
    size = len(buffer)
    while pos + FRAME_HEADER.size <= size:
        length, crc, episode_seq, version = FRAME_HEADER.unpack_from(
            buffer, pos
        )
        start = pos + FRAME_HEADER.size
        end = start + length
        if end > size:
            break
        if zlib.crc32(buffer[start:end]) & 0xFFFFFFFF != crc:
            break
        spans.append((start, length, episode_seq, version))
        pos = end
    return spans, pos


class SegmentReader:
    """Read-only view over one SEALED segment: manifest-validated, whole
    file read once, records exposed as zero-copy payload spans."""

    def __init__(self, root: str, seq: int):
        reason = validate_segment(root, seq)
        if reason is not None:
            raise ValueError(
                f"segment {seq} under {root} is not durable: {reason}"
            )
        with open(manifest_path(root, seq)) as f:
            self.manifest = SegmentManifest.from_json(json.load(f))
        with open(sealed_segment_path(root, seq), "rb") as f:
            self._buffer = f.read()
        spans, clean_end = _scan_frames(self._buffer)
        if clean_end != len(self._buffer) or len(spans) != self.manifest.records:
            # validate_segment checked size+CRC of the whole file, so this
            # is a frame-level inconsistency (e.g. manifest forged around
            # corrupt framing): refuse, same as torn.
            raise ValueError(
                f"segment {seq}: framing disagrees with manifest "
                f"({len(spans)} scanned vs {self.manifest.records} declared)"
            )
        self._spans = spans
        self._view = memoryview(self._buffer)

    def __len__(self) -> int:
        return len(self._spans)

    def record(self, index: int) -> SegmentRecord:
        offset, length, episode_seq, version = self._spans[index]
        return SegmentRecord(
            episode_seq=episode_seq,
            policy_version=version,
            payload=self._view[offset:offset + length],
        )

    def records(self) -> Iterator[SegmentRecord]:
        for index in range(len(self._spans)):
            yield self.record(index)

    def episode_record_indices(self) -> Dict[int, List[int]]:
        """{episode_seq: [record index, ...]} (prioritized sampling draws
        episodes, then serves their records)."""
        by_episode: Dict[int, List[int]] = {}
        for index, (_, _, episode_seq, _) in enumerate(self._spans):
            by_episode.setdefault(episode_seq, []).append(index)
        return by_episode


def validate_segment(root: str, seq: int) -> Optional[str]:
    """None when sealed segment `seq` is durable, else a torn-reason.
    Read-only — safe on a live directory (readers skip, never sweep)."""
    data_path = sealed_segment_path(root, seq)
    if not os.path.isfile(data_path):
        if os.path.isfile(open_segment_path(root, seq)):
            return "segment still open (unsealed tail)"
        return "sealed data file missing"
    mpath = manifest_path(root, seq)
    if not os.path.isfile(mpath):
        return "no seal manifest (crash between data write and seal)"
    try:
        with open(mpath) as f:
            manifest = SegmentManifest.from_json(json.load(f))
    except (OSError, ValueError, KeyError) as err:
        return f"unreadable seal manifest: {err}"
    actual = os.path.getsize(data_path)
    if actual != manifest.data_bytes:
        return (
            f"size mismatch: data file is {actual} bytes, manifest says "
            f"{manifest.data_bytes}"
        )
    with open(data_path, "rb") as f:
        crc = 0
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            crc = zlib.crc32(block, crc) & 0xFFFFFFFF
    if crc != manifest.data_crc32:
        return (
            f"CRC mismatch: data file crc32 {crc:#010x}, manifest says "
            f"{manifest.data_crc32:#010x}"
        )
    return None


def sealed_segment_seqs(root: str) -> List[int]:
    """Seqs with a sealed-NAMED data file, ascending — a pure listdir,
    NO validation (sealed files are immutable, so pollers validate each
    seq once when they first see it, not on every tick)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if name.startswith("segment-") and name.endswith(".seg"):
            try:
                out.append(int(name[len("segment-"):-len(".seg")]))
            except ValueError:
                continue
    return sorted(out)


def list_sealed_segments(root: str) -> List[Tuple[int, SegmentManifest]]:
    """Durable (seq, manifest) pairs ascending by seq; skips torn forms
    (read-only: usable by concurrent readers of a live dir)."""
    if not os.path.isdir(root):
        return []
    out: List[Tuple[int, SegmentManifest]] = []
    for name in sorted(os.listdir(root)):
        if not (name.startswith("segment-") and name.endswith(".seg")):
            continue
        try:
            seq = int(name[len("segment-"):-len(".seg")])
        except ValueError:
            continue
        if validate_segment(root, seq) is not None:
            continue
        with open(manifest_path(root, seq)) as f:
            out.append((seq, SegmentManifest.from_json(json.load(f))))
    return out


def salvage_open_segment(path: str) -> Tuple[int, int, int]:
    """Counts what a torn open segment held: (whole_records,
    whole_episodes, torn_tail_bytes). The records themselves are NOT
    recovered into the live buffer — a crash mid-append may have lost
    the episode's remaining records, and a partial episode must never
    be sampled — but the loss is thereby *counted*, which is the
    bounded-loss report the recovery contract promises."""
    with open(path, "rb") as f:
        buffer = f.read()
    spans, clean_end = _scan_frames(buffer)
    episodes = len({episode_seq for _, _, episode_seq, _ in spans})
    return len(spans), episodes, len(buffer) - clean_end


def sweep_replay_dir(root: str) -> Dict[str, int]:
    """WRITER-ONLY startup sweep: quarantines every torn form (open
    tails, sealed-named segments that fail validation, orphan
    manifests) into replay.quarantine/ and counts the loss.

    Returns {"segments_quarantined", "episodes_lost", "records_lost",
    "torn_tail_bytes"}. Like train/durability.py's sweep: never deletes
    (the quarantined tree is the crash forensics), and must only run in
    the process that OWNS the directory — a reader sweeping a live dir
    would quarantine the write in progress.
    """
    report = {
        "segments_quarantined": 0,
        "episodes_lost": 0,
        "records_lost": 0,
        "torn_tail_bytes": 0,
    }
    if not os.path.isdir(root):
        return report

    def quarantine(name: str, reason: str) -> None:
        src = os.path.join(root, name)
        dest_dir = quarantine_root(root)
        os.makedirs(dest_dir, exist_ok=True)
        dest = os.path.join(dest_dir, f"{name}.{int(time.time() * 1e3)}")
        while os.path.exists(dest):
            dest += "x"
        shutil.move(src, dest)
        _log.warning("Quarantined replay wreckage %s -> %s (%s)",
                     src, dest, reason)

    names = sorted(os.listdir(root))
    seen_seqs = set()
    for name in names:
        if name.endswith(".open") and name.startswith("segment-"):
            records, episodes, tail = salvage_open_segment(
                os.path.join(root, name)
            )
            report["records_lost"] += records
            report["episodes_lost"] += episodes
            report["torn_tail_bytes"] += tail
            quarantine(name, f"unsealed tail ({episodes} episodes lost)")
            report["segments_quarantined"] += 1
        elif name.endswith(".seg") and name.startswith("segment-"):
            try:
                seq = int(name[len("segment-"):-len(".seg")])
            except ValueError:
                continue
            seen_seqs.add(seq)
            reason = validate_segment(root, seq)
            if reason is None:
                continue
            # Count what the torn sealed form held before it moves: the
            # manifest's declared counts when it is readable (truncation
            # can tear frames the salvage scan cannot count), else the
            # frame salvage.
            episodes = records = tail = None
            mpath = manifest_path(root, seq)
            if os.path.isfile(mpath):
                try:
                    with open(mpath) as f:
                        manifest = SegmentManifest.from_json(json.load(f))
                    episodes, records, tail = (
                        manifest.episodes, manifest.records, 0
                    )
                except (OSError, ValueError, KeyError):
                    pass
            if episodes is None:
                records, episodes, tail = salvage_open_segment(
                    os.path.join(root, name)
                )
            report["records_lost"] += records
            report["episodes_lost"] += episodes
            report["torn_tail_bytes"] += tail
            quarantine(name, reason)
            mname = os.path.basename(manifest_path(root, seq))
            if os.path.isfile(os.path.join(root, mname)):
                quarantine(mname, reason)
            report["segments_quarantined"] += 1
    # Orphan manifests (data file gone entirely).
    for name in names:
        if not (name.startswith("segment-") and name.endswith(".json")):
            continue
        try:
            seq = int(name[len("segment-"):-len(".json")])
        except ValueError:
            continue
        if seq in seen_seqs or not os.path.isfile(os.path.join(root, name)):
            continue
        if not os.path.isfile(sealed_segment_path(root, seq)):
            quarantine(name, "orphan manifest (data file missing)")
    return report
