"""Replay-buffer service: durable appends, FIFO/prioritized sampling,
staleness + replay-ratio accounting, and a crash-respawning process
wrapper.

Three layers, outermost optional:

  * `ReplayBuffer` — the in-process core every path shares: owns the
    replay directory (startup sweep quarantines torn segments and
    COUNTS the loss), appends episodes zero-parse into the open
    segment, auto-seals on the episode/byte thresholds
    (`T2R_REPLAY_SEAL_EPISODES` / `T2R_REPLAY_SEAL_BYTES`), samples
    only sealed segments, and keeps the loop's observability counters.
  * `replay_service_main` + `ReplayClient` — the service as a process.
    Two wires, one protocol (`T2R_REPLAY_TRANSPORT`):

      - `queue` (default, the tier-1 fallback): supervisor-bridged
        multiprocessing queues, exactly the PR 8 topology — in-process
        and single-host tests pay no socket tax and stay byte-for-byte
        compatible;
      - `socket` (the cross-host wire): the service binds a TCP port
        and publishes it to `<root>/transport.json`; clients speak the
        CRC-framed stream protocol of `replay/transport.py` with
        per-request deadlines. No supervisor sits in the data path,
        which is what lets shards (replay/sharded.py) — and later
        actors/learners — live on other hosts.

    Append retries are IDEMPOTENT on either wire: every append carries
    a client-assigned `episode_uid` sealed into the segment manifest,
    and the buffer refuses a uid it has already made durable — so an
    ambiguous retry cannot duplicate an episode even across a service
    crash (the respawned buffer rebuilds its uid set from manifests).
    Per-client nonces remain as the legacy/uid-less belt.
  * `ReplayServiceHandle` — the supervisor: spawns the service, detects
    its death, respawns it (fresh queues per incarnation in queue mode;
    a fresh published port in socket mode — the restarted process
    recovers from durable segments and the sweep report is surfaced in
    stats), and exposes `kill()` for chaos legs.

Chaos sites (testing/chaos.py): `append` fires before an episode's
frames are written, `seal` before a manifest is published, `sample`
before a batch is drawn — a seeded `kill` clause at any of them is the
corresponding crash fault, and `flake:N` clauses exercise the client
retry path end to end.

Failure semantics clients can rely on: every call either returns,
raises a typed `ReplayError` subclass, or (service dead mid-call)
retries with jittered backoff up to `T2R_REPLAY_RETRIES` times before
raising `ReplayUnavailable`. Nothing hangs unboundedly.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import random
import signal
import threading

from tensor2robot_tpu.testing import locksmith
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.replay import segment as segment_lib
from tensor2robot_tpu.replay import transport as transport_lib
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.backoff import Backoff, poll_loop
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "ReplayBuffer",
    "ReplayClient",
    "ReplayEmpty",
    "ReplayError",
    "ReplayServiceHandle",
    "ReplayUnavailable",
    "client_from_spec",
    "replay_service_main",
]

STATE_FILENAME = "replay_state.json"


class ReplayError(RuntimeError):
    """Base class for typed replay-service failures."""


class ReplayEmpty(ReplayError):
    """No sealed segment to sample yet (bring-up, or all data torn)."""


class ReplayUnavailable(ReplayError):
    """The service stayed unreachable through the retry budget."""


def _load_counters(root: str) -> Dict[str, int]:
    path = os.path.join(root, STATE_FILENAME)
    if not os.path.isfile(path):
        return {}
    try:
        with open(path) as f:
            return {k: int(v) for k, v in json.load(f).items()}
    except (OSError, ValueError) as err:
        _log.warning("unreadable %s (%s); counters restart at zero",
                     path, err)
        return {}


class _FifoSampler:
    """Cycles sealed segments in seal (seq) order, records in file order.

    Deterministic given the segment set and the number of draws — the
    property the crash-consistency contract leans on: a resumed learner
    that skips the already-consumed draw count continues the EXACT
    schedule an uninterrupted run would have produced.
    """

    # Loaded-reader LRU bound: FIFO touches one segment at a time, but
    # prioritized draws hop segments within one batch — re-opening a
    # segment means a full-file CRC validation + read, so keep the hot
    # ones resident (bounded: ~8 x seal_bytes of memory).
    _READER_CACHE_MAX = 8

    def __init__(self, root: str):
        self._root = root
        self._order: List[int] = []  # seqs in sampling order
        self._pos = 0  # index into _order
        self._record = 0  # index into the current segment
        self._readers: "OrderedDict[int, segment_lib.SegmentReader]" = (
            OrderedDict()
        )

    def note_sealed(self, seq: int) -> None:
        self._order.append(seq)

    def state(self) -> Dict[str, int]:
        seq = self._order[self._pos % len(self._order)] if self._order else -1
        return {"segment_seq": seq, "record_index": self._record}

    def draw(self, n: int) -> List[Tuple[int, int]]:
        """n (seq, record_index) coordinates, advancing the cursor."""
        if not self._order:
            raise ReplayEmpty("no sealed segment to sample")
        coords: List[Tuple[int, int]] = []
        while len(coords) < n:
            seq = self._order[self._pos % len(self._order)]
            reader = self._get_reader(seq)
            while self._record < len(reader) and len(coords) < n:
                coords.append((seq, self._record))
                self._record += 1
            if self._record >= len(reader):
                self._pos = (self._pos + 1) % len(self._order)
                self._record = 0
        return coords

    def _get_reader(self, seq: int) -> segment_lib.SegmentReader:
        reader = self._readers.get(seq)
        if reader is None:
            reader = segment_lib.SegmentReader(self._root, seq)
            self._readers[seq] = reader
            while len(self._readers) > self._READER_CACHE_MAX:
                self._readers.popitem(last=False)
        else:
            self._readers.move_to_end(seq)
        return reader

    def read(self, coords: Sequence[Tuple[int, int]]):
        for seq, index in coords:
            yield self._get_reader(seq).record(index)


class _PrioritizedSampler(_FifoSampler):
    """Episode-priority-weighted draws from a seeded RNG.

    Draws an episode with probability proportional to its append-time
    priority, then serves its records round-robin. Deterministic given
    (segment set, seed, draw count) — chaos twins replay the same
    schedule.
    """

    def __init__(self, root: str, seed: int = 0):
        super().__init__(root)
        self._rng = random.Random(seed)
        self._episodes: List[Tuple[int, int, float]] = []  # seq, ep, priority
        self._weights: List[float] = []
        self._ep_records: Dict[Tuple[int, int], List[int]] = {}
        self._ep_cursor: Dict[Tuple[int, int], int] = {}

    def note_sealed(self, seq: int) -> None:
        super().note_sealed(seq)
        manifest_file = segment_lib.manifest_path(self._root, seq)
        with open(manifest_file) as f:
            manifest = segment_lib.SegmentManifest.from_json(json.load(f))
        priorities = manifest.priorities or (1.0,) * manifest.episodes
        for episode_seq, priority in enumerate(priorities):
            self._episodes.append((seq, episode_seq, priority))
            self._weights.append(max(float(priority), 1e-6))

    def draw(self, n: int) -> List[Tuple[int, int]]:
        if not self._episodes:
            raise ReplayEmpty("no sealed segment to sample")
        coords: List[Tuple[int, int]] = []
        picks = self._rng.choices(
            range(len(self._episodes)), weights=self._weights, k=n
        )
        for pick in picks:
            seq, episode_seq, _ = self._episodes[pick]
            key = (seq, episode_seq)
            if key not in self._ep_records:
                reader = self._get_reader(seq)
                self._ep_records[key] = reader.episode_record_indices().get(
                    episode_seq, []
                )
                self._ep_cursor[key] = 0
            records = self._ep_records[key]
            if not records:
                continue
            cursor = self._ep_cursor[key]
            coords.append((seq, records[cursor % len(records)]))
            self._ep_cursor[key] = cursor + 1
        if not coords:
            raise ReplayEmpty("prioritized draw found no records")
        return coords


class ReplayBuffer:
    """The in-process replay core (see module docstring). Thread-safe:
    in-process loops share one instance between actor threads and the
    learner's input generator."""

    def __init__(
        self,
        root: str,
        seal_episodes: Optional[int] = None,
        seal_bytes: Optional[int] = None,
        sampler: Optional[str] = None,
        seed: int = 0,
        owns_dir: bool = True,
    ):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = locksmith.make_lock("ReplayBuffer._lock")
        self._seal_episodes = (
            t2r_flags.get_int("T2R_REPLAY_SEAL_EPISODES")
            if seal_episodes is None else max(1, seal_episodes)
        )
        self._seal_bytes = (
            t2r_flags.get_int("T2R_REPLAY_SEAL_BYTES")
            if seal_bytes is None else max(1, seal_bytes)
        )
        sampler_kind = (
            t2r_flags.get_enum("T2R_REPLAY_SAMPLER")
            if sampler is None else sampler
        )
        if sampler_kind == "prioritized":
            self._sampler: _FifoSampler = _PrioritizedSampler(root, seed)
        elif sampler_kind == "fifo":
            self._sampler = _FifoSampler(root)
        else:
            raise ValueError(f"unknown sampler {sampler_kind!r}")
        self.recovery_report: Dict[str, int] = {}
        if owns_dir:
            # Writer-owned startup sweep: quarantine wreckage, COUNT the
            # loss — the bounded-loss half of the recovery contract.
            self.recovery_report = segment_lib.sweep_replay_dir(root)
        counters = _load_counters(root)
        self._counters = {
            "episodes_appended_total": counters.get(
                "episodes_appended_total", 0
            ),
            "records_appended_total": counters.get(
                "records_appended_total", 0
            ),
            "episodes_lost_total": counters.get("episodes_lost_total", 0)
            + self.recovery_report.get("episodes_lost", 0),
            "records_lost_total": counters.get("records_lost_total", 0)
            + self.recovery_report.get("records_lost", 0),
            "restarts": counters.get("restarts", 0) + (1 if counters else 0),
        }
        sealed = segment_lib.list_sealed_segments(root)
        self._sealed_records = 0
        self._sealed_episodes = 0
        self._segments_sealed = len(sealed)
        # Durable episode identities: the idempotency set a respawned
        # service rebuilds from manifests, so an append retry whose
        # original SEALED before the crash is deduped, not duplicated.
        # (Unsealed-tail uids die with the tail — its episodes were
        # quarantined as counted loss, so the retry's copy is the only
        # live one.) ~tens of bytes per episode; bounded by the data.
        self._uid_seen: set = set()
        for seq, manifest in sealed:
            self._sampler.note_sealed(seq)
            self._sealed_records += manifest.records
            self._sealed_episodes += manifest.episodes
            self._uid_seen.update(u for u in manifest.episode_uids if u)
        next_seq = max(
            [seq for seq, _ in sealed] + [counters.get("next_seq", 0) - 1]
        ) + 1 if (sealed or counters) else 0
        self._writer = segment_lib.SegmentWriter(root, next_seq)
        self._samples_drawn = 0
        self._staleness_last: Dict[str, float] = {}
        self._staleness_max = 0
        # The staleness anchor SURVIVES restarts (persisted with the
        # counters): a respawned service that forgot the learner's last
        # publish would report staleness 0 in exactly the crash window
        # the metric exists to describe.
        self._policy_version = counters.get("policy_version", 0)
        self._closed = False
        if self.recovery_report.get("segments_quarantined"):
            self._persist_counters()

    # -- write path ------------------------------------------------------------

    def append(
        self,
        transitions: Sequence[bytes],
        policy_version: int = 0,
        priority: float = 1.0,
        episode_uid: Optional[str] = None,
    ) -> Dict[str, int]:
        """Appends one whole episode; returns {episode_seq, segment_seq,
        sealed (0/1 whether this append tripped a seal)} — or
        {"deduped": 1} when `episode_uid` names an episode this buffer
        already holds (the idempotent-retry contract)."""
        chaos.maybe_fire("append")
        with self._lock:
            if self._closed:
                raise ReplayError("replay buffer is closed")
            if episode_uid and episode_uid in self._uid_seen:
                self._counters["appends_deduped_total"] = (
                    self._counters.get("appends_deduped_total", 0) + 1
                )
                return {"deduped": 1}
            episode_seq = self._writer.append_episode(
                transitions, policy_version=policy_version,
                priority=priority, episode_uid=episode_uid or "",
            )
            if episode_uid:
                self._uid_seen.add(episode_uid)
            self._counters["episodes_appended_total"] += 1
            self._counters["records_appended_total"] += len(transitions)
            segment_seq = self._writer.seq
            sealed = 0
            if (
                self._writer.episodes >= self._seal_episodes
                or self._writer.data_bytes >= self._seal_bytes
            ):
                self._seal_locked()
                sealed = 1
        return {
            "episode_seq": episode_seq,
            "segment_seq": segment_seq,
            "sealed": sealed,
        }

    def seal(self) -> bool:
        """Seals the open segment if it holds any episode; returns whether
        a segment was sealed."""
        with self._lock:
            if self._closed:
                raise ReplayError("replay buffer is closed")
            if self._writer.episodes == 0:
                return False
            self._seal_locked()
            return True

    def _seal_locked(self) -> None:
        chaos.maybe_fire("seal")
        manifest = self._writer.seal()
        if manifest is not None:
            self._sampler.note_sealed(manifest.seq)
            self._sealed_records += manifest.records
            self._sealed_episodes += manifest.episodes
            self._segments_sealed += 1
        self._writer = segment_lib.SegmentWriter(
            self.root, self._writer.seq + 1
        )
        self._persist_counters()

    def _persist_counters(self) -> None:
        payload = dict(self._counters)
        payload["next_seq"] = self._writer.seq + 1
        payload["policy_version"] = self._policy_version
        segment_lib._atomic_write_json(
            os.path.join(self.root, STATE_FILENAME), payload
        )

    # -- read path -------------------------------------------------------------

    def sample(
        self, batch_size: int
    ) -> Tuple[List[bytes], List[Tuple[int, int]], Dict[str, float]]:
        """batch_size records by the configured policy.

        Returns (payloads, coords, info): the raw wire-bytes payloads,
        their (segment_seq, record_index) coordinates (the audit trail
        the no-double-sampling tests pin), and the batch's staleness
        summary. Only SEALED segments are ever touched.
        """
        chaos.maybe_fire("sample")
        with self._lock:
            if self._closed:
                raise ReplayError("replay buffer is closed")
            coords = self._sampler.draw(batch_size)
            payloads: List[bytes] = []
            staleness: List[int] = []
            for record in self._sampler.read(coords):
                payloads.append(bytes(record.payload))
                staleness.append(
                    max(0, self._policy_version - record.policy_version)
                )
            self._samples_drawn += len(payloads)
            info = {
                "staleness_mean": sum(staleness) / max(len(staleness), 1),
                "staleness_max": float(max(staleness, default=0)),
            }
            self._staleness_last = info
            self._staleness_max = max(
                self._staleness_max, int(info["staleness_max"])
            )
        return payloads, coords, info

    # -- observability ---------------------------------------------------------

    def set_policy_version(self, version: int) -> None:
        """The learner's currently-published policy version — the anchor
        of the staleness metric (sampled records carry the version that
        GENERATED them; staleness = published - generated). Persisted
        immediately (publishes are rare; the anchor must survive a
        service crash)."""
        with self._lock:
            self._policy_version = int(version)
            self._persist_counters()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            if self._closed:
                # Mirrors a dead service process: a closed shard's
                # counters are UNREACHABLE, not implicitly final — the
                # sharded stats merge must report it as such instead of
                # folding in numbers nobody maintains anymore.
                raise ReplayError("replay buffer is closed")
            appended = self._counters["records_appended_total"]
            return {
                **self._counters,
                "segments_sealed": self._segments_sealed,
                "sealed_records": self._sealed_records,
                "sealed_episodes": self._sealed_episodes,
                "unsealed_tail_episodes": self._writer.episodes,
                "unsealed_tail_records": self._writer.records,
                "samples_drawn": self._samples_drawn,
                # Classic replay ratio: average times each appended record
                # has been consumed by the learner.
                "replay_ratio": self._samples_drawn / max(appended, 1),
                "policy_version": self._policy_version,
                "staleness_last": dict(self._staleness_last),
                "staleness_max_seen": self._staleness_max,
                "sampler_state": self._sampler.state(),
                "recovery": dict(self.recovery_report),
            }

    def close(self, seal_tail: bool = False) -> None:
        """seal_tail seals the open tail (clean shutdown keeps every
        episode); default leaves it open — the crash path's behavior."""
        with self._lock:
            if self._closed:
                return
            if seal_tail and self._writer.episodes:
                self._seal_locked()
            self._writer.abort()
            self._closed = True


# -- the service process -------------------------------------------------------


class _ServiceCore:
    """The transport-independent op dispatcher: one request tuple in,
    one reply tuple out — shared verbatim by the queue loop and the
    socket server so the two wires cannot drift.

    Requests are (client_id, req_id, op, args tuple); replies
    (req_id, "ok", payload) | (req_id, "error", error class name,
    message). `handle` returns None for the lifecycle "stop" op after
    setting `stop_requested` — the transport loop owns what that means.
    """

    def __init__(self, buffer: ReplayBuffer):
        self.buffer = buffer
        self.stop_requested = threading.Event()
        self._last_nonce: Dict[str, int] = {}

    def handle(self, request) -> Optional[Tuple]:
        try:
            client_id, req_id, op, args = request
        except (TypeError, ValueError):
            _log.warning("malformed replay request %r dropped", request)
            return None
        if op == "stop":
            self.stop_requested.set()
            return None
        try:
            if op == "append":
                transitions, policy_version, priority, nonce, *rest = args
                episode_uid = rest[0] if rest else None
                if (
                    episode_uid is None
                    and nonce is not None
                    and nonce <= self._last_nonce.get(client_id, -1)
                ):
                    # Legacy uid-less retry: per-client monotonic nonce
                    # dedup (in-memory; the uid path survives crashes).
                    payload: Any = {"deduped": 1}
                else:
                    payload = self.buffer.append(
                        transitions,
                        policy_version=policy_version,
                        priority=priority,
                        episode_uid=episode_uid,
                    )
                    if nonce is not None:
                        self._last_nonce[client_id] = nonce
            elif op == "sample":
                (batch_size,) = args
                payloads, coords, info = self.buffer.sample(batch_size)
                payload = {
                    "records": payloads,
                    "coords": coords,
                    "info": info,
                }
            elif op == "stats":
                payload = self.buffer.stats()
            elif op == "seal":
                payload = {"sealed": int(self.buffer.seal())}
            elif op == "set_policy_version":
                (version,) = args
                self.buffer.set_policy_version(version)
                payload = {"ok": 1}
            else:
                raise ReplayError(f"unknown replay op {op!r}")
            return (req_id, "ok", payload)
        except Exception as err:
            return (req_id, "error", type(err).__name__, str(err))


def replay_service_main(
    root: str,
    request_q=None,
    response_q=None,
    config: Optional[Dict[str, Any]] = None,
) -> None:
    """Process entry: serves append/sample/stats/seal over one of two
    wires, selected by config["transport"]:

      * "queue" — requests off `request_q`, replies (client_id-prefixed
        for supervisor routing) onto `response_q`. The queue pair is
        FRESH per incarnation: a SIGKILL mid-`get` leaves the queue's
        reader lock held by a dead process forever (the poisoned-queue
        trap; the fleet router dodges it the same way,
        serving/router.py `_spawn`), so the supervisor bridges clients'
        stable queues to each incarnation's fresh ones.
      * "socket" — binds an ephemeral localhost TCP port, publishes it
        to `<root>/transport.json`, and serves the CRC-framed stream
        protocol (replay/transport.py). No queues, no supervisor in the
        data path; a respawn publishes its fresh port.

    Append idempotency (both wires): appends carry a client-assigned
    `episode_uid` the buffer refuses to re-apply — sealed uids survive
    crashes via the segment manifests — plus the legacy per-client
    monotonic nonce for uid-less callers.
    """
    config = dict(config or {})
    chaos.set_scope(config.get("chaos_scope", "replay"))
    buffer = ReplayBuffer(
        root,
        seal_episodes=config.get("seal_episodes"),
        seal_bytes=config.get("seal_bytes"),
        sampler=config.get("sampler"),
        seed=int(config.get("seed", 0)),
    )
    core = _ServiceCore(buffer)
    _log.info(
        "replay service up at %s (recovery: %s)", root, buffer.recovery_report
    )
    try:
        if config.get("transport") == "socket":
            server = transport_lib.ReplayTransportServer(core.handle).start()
            transport_lib.publish_address(
                root, server.port,
                incarnation=int(config.get("incarnation", 0)),
            )
            try:
                while not core.stop_requested.wait(0.2):
                    pass
            finally:
                server.stop()
            return
        while True:
            try:
                request = request_q.get(timeout=0.1)
            except queue.Empty:
                continue
            except (OSError, ValueError, EOFError):
                return  # queue torn down: supervisor is gone
            reply = core.handle(request)
            if core.stop_requested.is_set():
                return
            if reply is not None:
                best_effort(response_q.put, (request[0],) + reply)
    finally:
        # Graceful stop: seal the open tail so a clean shutdown keeps
        # every appended episode (the crash path never reaches here —
        # its tail is the next startup's counted loss).
        best_effort(buffer.close, True)


class ReplayClient:
    """One client's synchronous view of the replay service.

    Every call retries through service restarts: a timeout or an
    explicit transport failure backs off (the shared seeded schedule,
    utils/backoff.py) and retries up to `T2R_REPLAY_RETRIES` extra
    attempts — bounded by BOTH the retry count and `total_timeout_s`, a
    hard wall-clock cap on the whole call: a dead service must never
    hold an actor past its episode deadline, however generous the
    per-attempt timeouts. Typed service-side errors (ReplayEmpty,
    validation errors) are NOT retried except ReplayEmpty when
    `wait_for_data` asks for it — an empty buffer during bring-up is a
    normal state to wait out, not a failure.

    The wire is either the supervisor-bridged queue pair
    (`request_q`/`response_q`) or a `transport.SocketChannel`
    (`channel=`); the retry/id/idempotency discipline is identical.
    """

    def __init__(
        self,
        client_id: str,
        request_q=None,
        response_q=None,
        timeout_s: float = 10.0,
        retries: Optional[int] = None,
        backoff_ms: float = 50.0,
        seed: int = 0,
        channel: Optional[transport_lib.SocketChannel] = None,
        total_timeout_s: Optional[float] = 60.0,
    ):
        if channel is None and (request_q is None or response_q is None):
            raise ValueError(
                "ReplayClient needs either a queue pair or a channel"
            )
        self.client_id = client_id
        self._request_q = request_q
        self._response_q = response_q
        self._channel = channel
        self._timeout_s = timeout_s
        self._retries = (
            t2r_flags.get_int("T2R_REPLAY_RETRIES")
            if retries is None else retries
        )
        total_ms = None if total_timeout_s is None else total_timeout_s * 1e3
        self._backoff = Backoff(
            base_ms=backoff_ms, cap_ms=2000.0, total_ms=total_ms, seed=seed
        )
        # Request ids are OPAQUE (instance token, counter) pairs echoed
        # verbatim by the service: two client instances sharing one
        # response queue (the driver creates several over a run) must
        # never alias each other's replies — a bare counter restarts at
        # 1 per instance, and a stale reply from a timed-out call of a
        # PREVIOUS instance would match a fresh call's id and be
        # returned as its (wrong-op!) result.
        self._token = f"{os.getpid()}-{id(self):x}-{random.getrandbits(32):08x}"
        self._req_counter = 0
        self._nonce = 0
        self._lock = locksmith.make_lock("ReplayClient._lock")

    def _attempt(self, req_id, op, args, call_timeout: float):
        """One wire attempt: (reply tuple, None) on a matched reply, or
        (None, error-or-None) on timeout / wire failure — the caller
        backs off and retries."""
        request = (self.client_id, req_id, op, args)
        if self._channel is not None:
            try:
                return self._channel.call(request, req_id, call_timeout), None
            except transport_lib.TransportError as err:
                return None, err
        try:
            self._request_q.put(request, timeout=1.0)
        except (queue.Full, OSError, ValueError) as err:
            return None, err
        deadline = time.monotonic() + call_timeout
        while time.monotonic() < deadline:
            try:
                candidate = self._response_q.get(
                    timeout=max(deadline - time.monotonic(), 0.01)
                )
            except queue.Empty:
                break
            except (OSError, ValueError) as err:
                return None, err
            if candidate[0] == req_id:
                return candidate, None
            # Stale reply from a timed-out earlier attempt: drop.
        return None, None

    def _call(
        self,
        op: str,
        args: Tuple,
        retry_empty: bool = False,
        timeout_s: Optional[float] = None,
        retries: Optional[int] = None,
    ):
        call_timeout = self._timeout_s if timeout_s is None else timeout_s
        call_retries = self._retries if retries is None else retries
        with self._lock:
            self._backoff.start()
            last_error: Optional[Exception] = None
            attempts = 0
            for attempt in range(call_retries + 1):
                # t2r: blocking-ok(the client lock IS the request serializer; it paces exactly one in-flight conversation)
                if attempt and not self._backoff.sleep(attempt):
                    break  # total budget exhausted: stop retrying
                remaining = self._backoff.remaining_s()
                if remaining <= 0:
                    break
                attempts += 1
                self._req_counter += 1
                req_id = (self._token, self._req_counter)
                response, wire_error = self._attempt(
                    req_id, op, args, min(call_timeout, remaining)
                )
                if response is None:
                    last_error = wire_error or last_error or TimeoutError(
                        f"replay {op} timed out"
                    )
                    continue
                _, status, *rest = response
                if status == "ok":
                    return rest[0]
                error_class, message = rest
                if error_class == "ReplayEmpty":
                    if retry_empty:
                        last_error = ReplayEmpty(message)
                        continue
                    raise ReplayEmpty(message)
                if error_class == "ChaosFault":
                    # Injected infrastructure failure (a flake/raise
                    # clause at a service site): retryable by design —
                    # this is exactly the path `flake:N` plans exist to
                    # exercise (append/sample recover after N failures).
                    last_error = ReplayError(f"{error_class}: {message}")
                    continue
                raise ReplayError(f"{error_class}: {message}")
            raise ReplayUnavailable(
                f"replay {op} failed after {attempts} attempt(s) "
                f"(retry budget {call_retries + 1}, total budget "
                f"{self._backoff.total_ms}ms): {last_error}"
            )

    def append(
        self,
        transitions: Sequence[bytes],
        policy_version: int = 0,
        priority: float = 1.0,
        episode_uid: Optional[str] = None,
        timeout_s: Optional[float] = None,
        retries: Optional[int] = None,
    ) -> Dict[str, int]:
        """Appends one whole episode. `episode_uid` is the durable
        idempotency key; None derives one from this client's token +
        nonce (callers that place episodes themselves — the sharded
        client — pass their own)."""
        self._nonce += 1
        if episode_uid is None:
            episode_uid = f"{self._token}:{self._nonce}"
        return self._call(
            "append",
            (
                [bytes(t) for t in transitions],
                policy_version,
                priority,
                self._nonce,
                episode_uid,
            ),
            timeout_s=timeout_s,
            retries=retries,
        )

    def sample(
        self,
        batch_size: int,
        wait_for_data: bool = True,
        timeout_s: Optional[float] = None,
        retries: Optional[int] = None,
    ):
        payload = self._call(
            "sample", (batch_size,), retry_empty=wait_for_data,
            timeout_s=timeout_s, retries=retries,
        )
        return payload["records"], payload["coords"], payload["info"]

    def stats(self) -> Dict[str, Any]:
        return self._call("stats", ())

    def seal(self) -> bool:
        return bool(self._call("seal", ())["sealed"])

    def set_policy_version(self, version: int) -> None:
        self._call("set_policy_version", (version,))

    def close(self) -> None:
        """Closes the socket channel (queue wires are supervisor-owned)."""
        if self._channel is not None:
            self._channel.close()


def client_from_spec(spec, client_id: str, **kwargs) -> ReplayClient:
    """Builds a ReplayClient in a (possibly child) process from a
    `ReplayServiceHandle.client_spec()` recipe."""
    kind = spec[0]
    if kind == "socket":
        _, root, peer = spec
        return ReplayClient(
            client_id,
            channel=transport_lib.SocketChannel(root, peer=peer),
            **kwargs,
        )
    if kind == "queue":
        _, request_q, response_q = spec
        return ReplayClient(client_id, request_q, response_q, **kwargs)
    raise ValueError(f"unknown replay client spec kind {kind!r}")


class ReplayServiceHandle:
    """Supervisor: spawns the service process, respawns it when it dies
    (the chaos legs SIGKILL it on purpose), and hands out per-client
    `ReplayClient`s. Transport-aware (`T2R_REPLAY_TRANSPORT`):

    * **queue** — clients never share a queue with the service process
      directly: a SIGKILL mid-`get`/`put` leaves that mp.Queue's lock
      held by a dead process, poisoning it for every later user.
      Clients talk to queues only the supervisor (which our fault model
      never kills) touches on the other end; two bridge threads forward
      requests into — and replies out of — a FRESH queue pair created
      for each incarnation. Requests parked in a dead incarnation's
      queue are simply lost; the client's timeout+retry resubmits them
      to the live one. Client ids must be declared up front: mp queues
      have to exist before a child can inherit them.

    * **socket** — no queues and no bridge threads: the service binds
      its own port and publishes it under the root; each incarnation
      publishes afresh and clients re-resolve on reconnect. The
      supervisor is ONLY lifecycle (spawn / monitor / respawn / stop) —
      nothing of it sits in the data path, so clients built from just
      the root path work from any process (`client_spec()` is what the
      sharded fabric hands to actor children).

    `peer_scope` names this service on chaos partition plans (shards
    set `s<k>`); it is also the service process's chaos scope.
    """

    def __init__(
        self,
        root: str,
        client_ids: Sequence[str] = (),
        config: Optional[Dict[str, Any]] = None,
        max_respawns: int = 10,
        transport: Optional[str] = None,
        peer_scope: Optional[str] = None,
    ):
        import multiprocessing

        self.root = root
        self._config = dict(config or {})
        self.transport = (
            transport
            or self._config.get("transport")
            or t2r_flags.get_enum("T2R_REPLAY_TRANSPORT")
        )
        if self.transport not in ("queue", "socket"):
            raise ValueError(f"unknown replay transport {self.transport!r}")
        self._config["transport"] = self.transport
        self.peer_scope = peer_scope or self._config.get(
            "chaos_scope", "replay"
        )
        self._config.setdefault("chaos_scope", self.peer_scope)
        self._ctx = multiprocessing.get_context("spawn")
        if self.transport == "socket":
            # A stale address file from a previous run would make
            # wait_ready() vouch for a port nobody listens on.
            best_effort(
                os.unlink,
                os.path.join(root, transport_lib.ADDRESS_FILENAME),
            )
        if self.transport == "queue":
            # Stable, client-facing (supervisor is the only peer process):
            self._request_q = self._ctx.Queue()
            self._response_queues = {
                client_id: self._ctx.Queue() for client_id in client_ids
            }
        else:
            self._request_q = None
            self._response_queues = {}
        # Per-incarnation (fresh on every spawn):
        self._svc_request_q = None
        self._svc_response_q = None
        self._incarnation = 0
        self._max_respawns = max_respawns
        self.respawns = 0
        self._process = None
        self._closed = False
        self._threads: List[threading.Thread] = []

    def start(
        self, ready_timeout_s: float = 30.0
    ) -> "ReplayServiceHandle":
        self._spawn()
        targets = [self._monitor_loop]
        if self.transport == "queue":
            targets += [self._forward_loop, self._drain_loop]
        for target in targets:
            thread = threading.Thread(target=target, daemon=True)
            thread.start()
            self._threads.append(thread)
        if self.transport == "socket" and not self.wait_ready(
            ready_timeout_s
        ):
            # start() returning means "addressable": clients are built
            # with SHORT budgets on the assumption that no-address is a
            # respawn window, not a cold start.
            self.stop()
            raise ReplayUnavailable(
                f"replay service at {self.root} published no transport "
                f"address within {ready_timeout_s}s of start"
            )
        return self

    def _spawn(self) -> None:
        self._incarnation += 1
        if self.transport == "queue":
            self._svc_request_q = self._ctx.Queue()
            self._svc_response_q = self._ctx.Queue()
        self._config["incarnation"] = self._incarnation
        self._process = self._ctx.Process(
            target=replay_service_main,
            args=(
                self.root,
                self._svc_request_q,
                self._svc_response_q,
                self._config,
            ),
            daemon=True,
        )
        self._process.start()

    @poll_loop
    def _monitor_loop(self) -> None:
        while not self._closed:
            process = self._process
            if process is not None and not process.is_alive():
                if self._closed or self.respawns >= self._max_respawns:
                    return
                self.respawns += 1
                _log.warning(
                    "replay service died (exitcode %s); respawn %d",
                    process.exitcode, self.respawns,
                )
                self._spawn()
            time.sleep(0.05)

    def _forward_loop(self) -> None:
        """Client requests -> the CURRENT incarnation's request queue."""
        while not self._closed:
            try:
                request = self._request_q.get(timeout=0.1)
            except queue.Empty:
                continue
            except (OSError, ValueError, EOFError):
                return
            if request[2] == "stop":
                continue  # lifecycle is the supervisor's, not clients'
            best_effort(self._svc_request_q.put, request)

    @poll_loop
    def _drain_loop(self) -> None:
        """Service replies -> the owning client's stable queue. Tracks
        incarnation flips so it always reads the LIVE response queue
        (replies stranded in a dead incarnation's queue are gone, like
        the requests; retries cover both)."""
        incarnation = self._incarnation
        response_q = self._svc_response_q
        while not self._closed:
            if incarnation != self._incarnation:
                incarnation = self._incarnation
                response_q = self._svc_response_q
            try:
                message = response_q.get(timeout=0.1)
            except queue.Empty:
                continue
            except (OSError, ValueError, EOFError):
                time.sleep(0.05)
                continue
            client_id, rest = message[0], message[1:]
            out = self._response_queues.get(client_id)
            if out is None:
                _log.warning(
                    "reply for unknown replay client %r dropped", client_id
                )
                continue
            best_effort(out.put, rest)

    def client(self, client_id: str, **kwargs) -> ReplayClient:
        if self.transport == "socket":
            return ReplayClient(
                client_id,
                channel=transport_lib.SocketChannel(
                    self.root, peer=self.peer_scope
                ),
                **kwargs,
            )
        if client_id not in self._response_queues:
            raise KeyError(
                f"client {client_id!r} was not declared at construction "
                f"(known: {sorted(self._response_queues)})"
            )
        return ReplayClient(
            client_id,
            self._request_q,
            self._response_queues[client_id],
            **kwargs,
        )

    def client_queues(self, client_id: str):
        """(request_q, response_q) for building a ReplayClient in a
        CHILD process (queue objects must ride the spawn args)."""
        if self.transport == "socket":
            raise RuntimeError(
                "socket transport has no client queues; build the child's "
                "client from client_spec() instead"
            )
        return self._request_q, self._response_queues[client_id]

    def client_spec(self, client_id: str):
        """A picklable recipe for building this service's client in a
        CHILD process: ("socket", root, peer_scope) needs only the path
        (the address file does the rest — the cross-host shape);
        ("queue", request_q, response_q) carries the inherited queues."""
        if self.transport == "socket":
            return ("socket", self.root, self.peer_scope)
        request_q, response_q = self.client_queues(client_id)
        return ("queue", request_q, response_q)

    def wait_ready(self, timeout_s: float = 30.0) -> bool:
        """Blocks until the service is addressable (socket mode: the
        CURRENT incarnation published its port — a dead predecessor's
        stale address file does not count, or a stop()-time wait for a
        mid-respawn shard would vouch for a port nobody listens on;
        queue mode: immediate — the queues exist before the child
        does). Returns readiness rather than raising: callers at
        bring-up decide whether a late shard is fatal (the sharded
        client would otherwise spill the first appends of a perfectly
        healthy cold start)."""
        if self.transport == "queue":
            return True

        def current_published() -> bool:
            # Liveness first: right after a SIGKILL the monitor may not
            # have bumped _incarnation yet, so the stale file still
            # "matches" — but its process is dead, which is checkable.
            process = self._process
            if process is None or not process.is_alive():
                return False
            info = transport_lib.read_address_info(self.root)
            return (
                info is not None
                and info["incarnation"] >= self._incarnation
            )

        # Seeded, bounded poll (utils/backoff.py): a hard total-time
        # bound by construction, jittered so a fleet of shards waiting
        # on each other does not probe in lockstep.
        return bool(
            Backoff(base_ms=20.0, cap_ms=60.0, factor=1.0, seed=1).poll(
                lambda: self._closed or current_published(),
                total_s=timeout_s,
            )
            and not self._closed
            and current_published()
        )

    def pid(self) -> Optional[int]:
        process = self._process
        return process.pid if process is not None else None

    def kill(self) -> Optional[int]:
        """SIGKILL the live service process (chaos legs); the monitor
        respawns it. Returns the killed pid."""
        process = self._process
        if process is None or not process.is_alive():
            return None
        pid = process.pid
        os.kill(pid, signal.SIGKILL)
        return pid

    def alive(self) -> bool:
        process = self._process
        return process is not None and process.is_alive()

    def stop(self, timeout_s: float = 10.0) -> None:
        # Closed FIRST: the monitor must not respawn a service that is
        # exiting because we asked it to.
        self._closed = True
        process = self._process
        if process is not None and process.is_alive():
            if self.transport == "socket":
                channel = transport_lib.SocketChannel(self.root)
                best_effort(
                    channel.send_only, ("_supervisor", 0, "stop", ())
                )
                best_effort(channel.close)
            else:
                best_effort(
                    self._svc_request_q.put, ("_supervisor", 0, "stop", ()),
                )
            process.join(timeout_s)
        if process is not None and process.is_alive():
            process.terminate()
            process.join(5.0)
        for thread in self._threads:
            thread.join(timeout_s)
        for q in (
            [self._request_q, self._svc_request_q, self._svc_response_q]
            + list(self._response_queues.values())
        ):
            if q is None:
                continue
            best_effort(q.cancel_join_thread)
            best_effort(q.close)

    def __enter__(self) -> "ReplayServiceHandle":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
