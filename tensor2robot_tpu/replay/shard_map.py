"""Consistent-hash episode placement for the sharded replay fabric.

Placement must survive the fabric's fault model: a shard process is
SIGKILLed and respawned constantly (that is the point), and a client
that re-derived placements from the *live* shard set would scatter
episodes — and, worse, sample the same episode from two homes — every
time liveness flickered. So placement is a pure function of the
episode key and the CONFIGURED shard count:

  * The ring is built from `num_shards` alone: each shard contributes
    `vnodes` points at `sha256(salt/shard/vnode)`. No liveness, no
    incarnation, no port — a respawned shard owns exactly the arc it
    owned before it died, so no surviving episode's placement ever
    moves (the stability property the unit tests pin).
  * Failover placement (`shard_for(key, exclude=dead)`) walks the ring
    PAST excluded shards' points: only keys whose home shard is dead
    move, each to the next live point on its arc — and when the shard
    returns, `exclude` empties and every key is home again. This is
    the classic consistent-hashing guarantee (the same construction
    memcache/dynamo rings use), which is why shard death costs
    1/num_shards of placements, not a reshuffle.

The sharded client uses `shard_for` with no exclusions for appends
(a dead home shard means *spill and wait*, not *re-home* — re-homing
appends would duplicate episodes when the home returns and the spill
drains) and exclusions only for read-side failover.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, List, Set, Tuple

__all__ = ["ShardMap"]


def _point(token: str) -> int:
    return int.from_bytes(
        hashlib.sha256(token.encode()).digest()[:8], "little"
    )


class ShardMap:
    """The hash ring: episode key -> shard id, stable under respawn."""

    def __init__(
        self,
        num_shards: int,
        vnodes: int = 64,
        salt: str = "t2r-replay",
    ):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.num_shards = num_shards
        self.vnodes = vnodes
        self.salt = salt
        points: List[Tuple[int, int]] = []
        for shard in range(num_shards):
            for vnode in range(vnodes):
                points.append((_point(f"{salt}/{shard}/{vnode}"), shard))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._shards = [s for _, s in points]

    def shard_for(self, key, exclude: Iterable[int] = ()) -> int:
        """The shard owning `key`; with `exclude`, the first non-excluded
        shard clockwise from the key's point (read-side failover)."""
        excluded: Set[int] = set(exclude)
        live = self.num_shards - len(
            excluded & set(range(self.num_shards))
        )
        if live <= 0:
            raise ValueError("every shard is excluded")
        start = bisect.bisect_right(self._hashes, _point(str(key)))
        size = len(self._shards)
        for step in range(size):
            shard = self._shards[(start + step) % size]
            if shard not in excluded:
                return shard
        raise AssertionError("unreachable: a live shard exists")

    def placements(
        self, keys: Iterable, exclude: Iterable[int] = ()
    ) -> List[int]:
        excluded = tuple(exclude)
        return [self.shard_for(key, excluded) for key in keys]
