"""The sharded replay fabric: N shard services, one placement-aware client.

PR 8's replay service is crash-tolerant but singular — one process, one
directory, one host's worth of append/sample bandwidth, and a single
point of (recoverable) stall. This module scales it out the way
IMPALA-class actor/learner systems assume (arXiv:1802.01561): N
independent replay-service shards, each with its OWN segment directory,
durability manifests, quarantine sweep and counters — exactly the
single service's contract, times N — plus a client that owns placement
and degradation policy:

  * **Placement** is consistent-hash over the client-assigned episode
    uid (`replay/shard_map.py`): stable under shard respawn, so a
    SIGKILLed shard's recovery changes nothing for survivors.
  * **Appends to a dead shard buffer-and-retry, bounded.** An episode
    whose home shard is unreachable goes to an in-order spill buffer
    (per shard, FIFO — order preserves the uid-idempotency story) and
    is replayed when the shard returns; past `T2R_REPLAY_SPILL_BYTES`
    episodes are DROPPED AND COUNTED. Appends are never re-homed: the
    home shard may hold the episode already (ambiguous timeout), and
    only the home shard's manifest-backed uid set can dedup the retry.
  * **Sampling fails over to surviving shards with the coverage loss
    COUNTED.** A draw that skips an unreachable (or chaos-partitioned)
    shard serves from the next shard in rotation and bumps that shard's
    `coverage_lost_draws` — the learner keeps stepping on a degraded
    data distribution it can SEE, never on a silently narrowed one.
  * **Nothing is fabricated.** A shard whose stats cannot be read is
    reported `unreachable`, not zeroed — same rule as
    `LoopReport.stats_ok`.

The fabric runs on either wire (`T2R_REPLAY_TRANSPORT`): the socket
transport is the point (shards addressable by directory + published
port — the cross-host shape), the queue wire keeps single-host tests
cheap, and `local_shard_backends` adapts in-process ReplayBuffers so
the tier-1 loop twin exercises every placement/failover/counting path
with zero subprocesses.
"""

from __future__ import annotations

import logging
import os
import random
import threading

from tensor2robot_tpu.testing import locksmith
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.replay import segment as segment_lib
from tensor2robot_tpu.utils.backoff import Backoff
from tensor2robot_tpu.replay.service import (
    ReplayBuffer,
    ReplayClient,
    ReplayEmpty,
    ReplayError,
    ReplayServiceHandle,
    ReplayUnavailable,
    client_from_spec,
)
from tensor2robot_tpu.replay.shard_map import ShardMap

_log = logging.getLogger(__name__)

__all__ = [
    "ShardedReplayClient",
    "ShardedReplayService",
    "audit_episode_uids",
    "local_shard_backends",
    "shard_root",
]

# Per-shard-attempt budgets: the sharded layer owns resilience (spill +
# failover), so each backend call is SHORT — a dead shard must cost one
# bounded probe, not a full single-service retry storm.
_FAST_TIMEOUT_S = 3.0
_FAST_RETRIES = 0
_FAST_TOTAL_S = 6.0


def shard_root(root: str, shard: int) -> str:
    return os.path.join(root, f"shard-{shard:02d}")


class _LocalBackend:
    """In-process ReplayBuffer presented through the client protocol
    (uniform kwargs; the buffer has no wire to time out on)."""

    def __init__(self, buffer: ReplayBuffer):
        self.buffer = buffer

    def append(
        self,
        transitions,
        policy_version: int = 0,
        priority: float = 1.0,
        episode_uid: Optional[str] = None,
        timeout_s: Optional[float] = None,
        retries: Optional[int] = None,
    ):
        del timeout_s, retries
        return self.buffer.append(
            transitions,
            policy_version=policy_version,
            priority=priority,
            episode_uid=episode_uid,
        )

    def sample(self, batch_size, wait_for_data: bool = True,
               timeout_s: Optional[float] = None,
               retries: Optional[int] = None):
        del wait_for_data, timeout_s, retries
        return self.buffer.sample(batch_size)

    def stats(self):
        return self.buffer.stats()

    def seal(self):
        return self.buffer.seal()

    def set_policy_version(self, version: int):
        self.buffer.set_policy_version(version)

    def close(self):
        pass  # buffer lifecycle belongs to the loop


def local_shard_backends(buffers: Sequence[ReplayBuffer]):
    return [_LocalBackend(b) for b in buffers]


class ShardedReplayClient:
    """One client's placement-aware view of the shard fleet.

    API-compatible with `ReplayClient` (append/sample/stats/seal/
    set_policy_version with the same shapes), so
    `ReplayInputGenerator(client=...)` consumes it unchanged — sampled
    coordinates become (shard, segment_seq, record_index) triples, the
    shard-qualified audit trail.

    Thread-safe; the loop shares one instance between actor threads and
    the learner in in-process mode.
    """

    def __init__(
        self,
        backends: Sequence[Any],
        client_id: str = "client",
        shard_map: Optional[ShardMap] = None,
        spill_bytes: Optional[int] = None,
        probe_interval_s: float = 0.5,
        sample_timeout_s: float = _FAST_TIMEOUT_S,
        seed: int = 0,
    ):
        if not backends:
            raise ValueError("a sharded client needs at least one backend")
        self._backends = list(backends)
        self.client_id = client_id
        self.num_shards = len(self._backends)
        self._map = shard_map or ShardMap(self.num_shards)
        self._spill_limit = (
            t2r_flags.get_int("T2R_REPLAY_SPILL_BYTES")
            if spill_bytes is None else spill_bytes
        )
        self._probe_interval_s = probe_interval_s
        self._sample_timeout_s = sample_timeout_s
        self._lock = locksmith.make_lock("ShardedReplayClient._lock")
        # Episode uids carry a per-INSTANCE token (same rationale as
        # ReplayClient's request ids): a restarted client reusing the
        # same client_id must never mint uids that collide with its
        # predecessor's sealed episodes — the manifest-backed dedup
        # would silently discard the new episodes as retries. Placement
        # only needs each uid to be a stable hash key, which any unique
        # string is.
        self._uid_token = (
            f"{os.getpid():x}-{id(self):x}-{random.getrandbits(32):08x}"
        )
        self._episode_seq = 0
        self._rotation = seed % self.num_shards
        # Per-shard down state: shard -> monotonic time of next probe.
        self._down_until: Dict[int, float] = {}
        # Per-shard in-order spill: entries are (uid, transitions,
        # policy_version, priority).
        self._spill: Dict[int, Deque[Tuple]] = {
            k: deque() for k in range(self.num_shards)
        }
        self._spill_bytes = 0
        self._anchor: Optional[int] = None
        self._anchor_pending: set = set()
        self.counters: Dict[str, Any] = {
            "appends_spilled": 0,
            "spill_replayed": 0,
            "spill_dropped_episodes": 0,
            "spill_dropped_records": 0,
            "appends_deduped": 0,
            "coverage_lost_draws": [0] * self.num_shards,
            "sample_failovers": 0,
        }

    # -- shard liveness bookkeeping (call with lock held) ----------------------

    def _is_down(self, shard: int, now: float) -> bool:
        until = self._down_until.get(shard)
        return until is not None and now < until

    def _mark_down(self, shard: int, now: float) -> None:
        self._down_until[shard] = now + self._probe_interval_s

    def _mark_up(self, shard: int) -> None:
        self._down_until.pop(shard, None)
        if shard in self._anchor_pending and self._anchor is not None:
            try:
                self._backends[shard].set_policy_version(self._anchor)
                self._anchor_pending.discard(shard)
            except ReplayError:
                pass  # still flaky; re-pushed on the next recovery

    # -- write path ------------------------------------------------------------

    def append(
        self,
        transitions: Sequence[bytes],
        policy_version: int = 0,
        priority: float = 1.0,
    ) -> Dict[str, int]:
        """Places and appends one episode; returns the backend's reply
        plus {"shard": k}, or {"spilled": 1, "shard": k} /
        {"spill_dropped": 1, "shard": k} on the degraded paths."""
        transitions = [bytes(t) for t in transitions]
        with self._lock:
            uid = (
                f"{self.client_id}/{self._uid_token}:{self._episode_seq}"
            )
            self._episode_seq += 1
            shard = self._map.shard_for(uid)
            entry = (uid, transitions, policy_version, priority)
            now = time.monotonic()
            self._drain_shard_locked(shard, now)
            if self._spill[shard] or self._is_down(shard, now):
                # Order matters: an episode may never jump the queue of
                # earlier spilled episodes to its shard.
                return self._spill_locked(shard, entry)
            try:
                out = self._backends[shard].append(
                    transitions,
                    policy_version=policy_version,
                    priority=priority,
                    episode_uid=uid,
                    timeout_s=_FAST_TIMEOUT_S,
                    retries=_FAST_RETRIES,
                )
            except (ReplayUnavailable, ReplayError) as err:
                if isinstance(err, ReplayEmpty):
                    raise  # impossible for append; do not mask a bug
                self._mark_down(shard, now)
                _log.warning(
                    "append to shard %d failed (%s); spilling", shard, err
                )
                return self._spill_locked(shard, entry)
            self._mark_up(shard)
            if out.get("deduped"):
                self.counters["appends_deduped"] += 1
            out = dict(out)
            out["shard"] = shard
            return out

    def _spill_locked(self, shard: int, entry: Tuple) -> Dict[str, int]:
        uid, transitions, _, _ = entry
        size = sum(len(t) for t in transitions)
        if self._spill_bytes + size > self._spill_limit:
            self.counters["spill_dropped_episodes"] += 1
            self.counters["spill_dropped_records"] += len(transitions)
            _log.warning(
                "spill budget exhausted (%d + %d > %d bytes): episode %s "
                "to shard %d DROPPED (counted)",
                self._spill_bytes, size, self._spill_limit, uid, shard,
            )
            return {"spill_dropped": 1, "shard": shard}
        self._spill[shard].append(entry)
        self._spill_bytes += size
        self.counters["appends_spilled"] += 1
        return {"spilled": 1, "shard": shard}

    def _drain_shard_locked(self, shard: int, now: float) -> None:
        """Replays this shard's spill queue head-first while the shard
        cooperates. Skipped entirely inside the shard's probe-backoff
        window so a dead shard costs one probe per interval, not one
        per append."""
        if not self._spill[shard] or self._is_down(shard, now):
            return
        while self._spill[shard]:
            uid, transitions, policy_version, priority = self._spill[shard][0]
            try:
                out = self._backends[shard].append(
                    transitions,
                    policy_version=policy_version,
                    priority=priority,
                    episode_uid=uid,
                    timeout_s=_FAST_TIMEOUT_S,
                    retries=_FAST_RETRIES,
                )
            except (ReplayUnavailable, ReplayError):
                self._mark_down(shard, now)
                return
            self._spill[shard].popleft()
            self._spill_bytes -= sum(len(t) for t in transitions)
            self.counters["spill_replayed"] += 1
            if out.get("deduped"):
                self.counters["appends_deduped"] += 1
        self._mark_up(shard)

    def flush_spill(self, timeout_s: float = 10.0) -> int:
        """Best-effort drain of every shard's spill (teardown); returns
        the number of episodes still spilled after the deadline. The
        retry cadence is a seeded, hard-bounded backoff schedule."""

        def drained() -> bool:
            with self._lock:
                for shard in range(self.num_shards):
                    # Teardown is the one caller that overrides the
                    # probe window: this is its last chance.
                    self._down_until.pop(shard, None)
                    self._drain_shard_locked(shard, time.monotonic())
                return not any(self._spill.values())

        Backoff(base_ms=100.0, cap_ms=250.0, factor=1.0, seed=2).poll(
            drained, total_s=timeout_s
        )
        with self._lock:
            return sum(len(q) for q in self._spill.values())

    # -- read path -------------------------------------------------------------

    def sample(
        self,
        batch_size: int,
        wait_for_data: bool = True,
        timeout_s: Optional[float] = None,
        retries: Optional[int] = None,
    ):
        """One batch from the rotation's next responsive shard.

        Rotation spreads consecutive batches over shards; an
        unreachable shard is skipped (counted as coverage loss for this
        draw) and retried after its probe interval. Raises ReplayEmpty
        when every reachable shard is empty (bring-up — the generator
        waits it out), ReplayUnavailable when NO shard is reachable.
        """
        del wait_for_data, retries  # failover IS the retry policy
        attempt_timeout = (
            self._sample_timeout_s if timeout_s is None else timeout_s
        )
        with self._lock:
            start = self._rotation
            self._rotation = (self._rotation + 1) % self.num_shards
            now = time.monotonic()
            empties = 0
            failed: List[int] = []
            skipped: List[int] = []
            for step in range(self.num_shards):
                shard = (start + step) % self.num_shards
                if self._is_down(shard, now):
                    skipped.append(shard)
                    continue
                try:
                    records, coords, info = self._backends[shard].sample(
                        batch_size,
                        wait_for_data=False,
                        timeout_s=attempt_timeout,
                        retries=_FAST_RETRIES,
                    )
                except ReplayEmpty:
                    empties += 1
                    self._mark_up(shard)
                    continue
                except (ReplayUnavailable, ReplayError):
                    self._mark_down(shard, now)
                    failed.append(shard)
                    continue
                self._mark_up(shard)
                # Every shard this draw could NOT reach is counted
                # coverage loss — the degradation is in the report, not
                # inferred from silence.
                self._count_coverage_loss(failed, skipped)
                if failed or skipped or step > 0:
                    self.counters["sample_failovers"] += 1
                coords = [
                    (shard, int(seq), int(index)) for seq, index in coords
                ]
                info = dict(info)
                info["shard"] = shard
                info["coverage_lost_shards"] = sorted(failed + skipped)
                return records, coords, info
            # A draw that raises still counts its unreachable shards:
            # the empty-buffer wait loop would otherwise hide a total
            # partition behind zero counters for its whole duration.
            self._count_coverage_loss(failed, skipped)
            if empties:
                raise ReplayEmpty(
                    f"all {empties} reachable shard(s) empty "
                    f"({len(failed) + len(skipped)} unreachable)"
                )
            raise ReplayUnavailable(
                f"no replay shard reachable (failed: {failed}, "
                f"in probe backoff: {skipped})"
            )

    def _count_coverage_loss(self, failed, skipped) -> None:
        for lost in failed + skipped:
            self.counters["coverage_lost_draws"][lost] += 1

    # -- control/observability -------------------------------------------------

    def seal(self) -> bool:
        sealed = False
        for shard, backend in enumerate(self._backends):
            try:
                sealed = bool(backend.seal()) or sealed
            except ReplayError as err:
                _log.warning("seal on shard %d failed: %s", shard, err)
        return sealed

    def set_policy_version(self, version: int) -> None:
        """Broadcasts the staleness anchor; a shard that misses it is
        remembered and re-anchored when it next recovers (its staleness
        would otherwise under-report for the whole outage)."""
        with self._lock:
            self._anchor = int(version)
            for shard, backend in enumerate(self._backends):
                try:
                    backend.set_policy_version(version)
                    self._anchor_pending.discard(shard)
                except ReplayError as err:
                    self._anchor_pending.add(shard)
                    _log.warning(
                        "anchor push to shard %d failed (%s); queued",
                        shard, err,
                    )

    def stats(self) -> Dict[str, Any]:
        """Fabric counters + per-shard stats. A shard whose stats read
        fails is reported {"unreachable": True} — the caller can see
        exactly which totals are partial (never fabricated zeros)."""
        with self._lock:
            per_shard: List[Dict[str, Any]] = []
            totals = {
                "episodes_appended_total": 0,
                "records_appended_total": 0,
                "episodes_lost_total": 0,
                "records_lost_total": 0,
                "segments_sealed": 0,
                "samples_drawn": 0,
            }
            unreachable: List[int] = []
            for shard, backend in enumerate(self._backends):
                try:
                    stats = backend.stats()
                except ReplayError:
                    per_shard.append({"shard": shard, "unreachable": True})
                    unreachable.append(shard)
                    continue
                stats = dict(stats)
                stats["shard"] = shard
                stats["unreachable"] = False
                per_shard.append(stats)
                for key in totals:
                    totals[key] += stats.get(key, 0)
            appended = totals["records_appended_total"]
            return {
                **totals,
                "replay_ratio": totals["samples_drawn"] / max(appended, 1),
                "num_shards": self.num_shards,
                "per_shard": per_shard,
                "shards_unreachable": unreachable,
                "spill_pending_episodes": sum(
                    len(q) for q in self._spill.values()
                ),
                "spill_pending_bytes": self._spill_bytes,
                **{k: (list(v) if isinstance(v, list) else v)
                   for k, v in self.counters.items()},
            }

    def close(self) -> None:
        for backend in self._backends:
            close = getattr(backend, "close", None)
            if close is not None:
                close()


class ShardedReplayService:
    """N `ReplayServiceHandle`s under one root: `<root>/shard-<k>/` each
    with its own process, supervisor, durability sweep and — in socket
    mode — published port. Chaos scope `s<k>` per shard, so seeded
    plans target one shard (`s1/append:3:kill`) and partition plans
    name them (`net_send:1:partition:s1`)."""

    def __init__(
        self,
        root: str,
        num_shards: int,
        client_ids: Sequence[str] = (),
        config: Optional[Dict[str, Any]] = None,
        transport: Optional[str] = None,
        max_respawns: int = 10,
    ):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        self.root = root
        self.num_shards = num_shards
        self.shard_roots = [
            shard_root(root, shard) for shard in range(num_shards)
        ]
        self.handles: List[ReplayServiceHandle] = []
        for shard, sroot in enumerate(self.shard_roots):
            os.makedirs(sroot, exist_ok=True)
            self.handles.append(
                ReplayServiceHandle(
                    sroot,
                    client_ids,
                    config=dict(config or {}),
                    max_respawns=max_respawns,
                    transport=transport,
                    peer_scope=f"s{shard}",
                )
            )
        self.shard_map = ShardMap(num_shards)

    def start(self, ready_timeout_s: float = 60.0) -> "ShardedReplayService":
        for handle in self.handles:
            handle.start()
        late = [
            shard for shard, handle in enumerate(self.handles)
            if not handle.wait_ready(ready_timeout_s)
        ]
        if late:
            # Bring-up is the one moment a silent degradation would be
            # invisible forever after — fail loudly instead of letting
            # the first appends spill against shards that never came up.
            self.stop()
            raise ReplayUnavailable(
                f"shard(s) {late} not addressable within "
                f"{ready_timeout_s}s of start"
            )
        return self

    def client(self, client_id: str, **kwargs) -> ShardedReplayClient:
        backends = [
            handle.client(
                client_id,
                timeout_s=_FAST_TIMEOUT_S,
                retries=_FAST_RETRIES,
                total_timeout_s=_FAST_TOTAL_S,
            )
            for handle in self.handles
        ]
        return ShardedReplayClient(
            backends, client_id=client_id, shard_map=self.shard_map,
            **kwargs,
        )

    def client_specs(self, client_id: str) -> List[Tuple]:
        """Per-shard picklable client recipes for a CHILD process (see
        `ReplayServiceHandle.client_spec`)."""
        return [
            handle.client_spec(client_id) for handle in self.handles
        ]

    def kill_shard(self, shard: int) -> Optional[int]:
        """SIGKILL shard `shard`'s live process (its supervisor respawns
        it); returns the killed pid."""
        return self.handles[shard].kill()

    def alive(self, shard: int) -> bool:
        return self.handles[shard].alive()

    def pids(self) -> List[Optional[int]]:
        return [handle.pid() for handle in self.handles]

    @property
    def respawns(self) -> int:
        return sum(handle.respawns for handle in self.handles)

    def stop(self, timeout_s: float = 10.0) -> None:
        for handle in self.handles:
            handle.stop(timeout_s)

    def __enter__(self) -> "ShardedReplayService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def sharded_client_from_specs(
    specs: Sequence[Tuple], client_id: str, seed: int = 0, **kwargs
) -> ShardedReplayClient:
    """Builds the sharded client in a CHILD process from
    `ShardedReplayService.client_specs` (actor_main's entry path)."""
    backends = [
        client_from_spec(
            spec,
            client_id,
            timeout_s=_FAST_TIMEOUT_S,
            retries=_FAST_RETRIES,
            total_timeout_s=_FAST_TOTAL_S,
            seed=seed,
        )
        for spec in specs
    ]
    return ShardedReplayClient(
        backends, client_id=client_id, seed=seed, **kwargs
    )


def audit_episode_uids(shard_roots: Sequence[str]) -> Dict[str, Any]:
    """The zero-duplicate-appends audit: reads every DURABLE segment
    manifest under every shard and counts episode uids that appear more
    than once (anywhere in the fabric — a cross-shard duplicate would
    mean placement re-homed an append, an intra-shard one that the
    idempotency contract broke). Uid-less ("") legacy episodes are
    reported but cannot be audited."""
    seen: Dict[str, Tuple[int, int]] = {}
    duplicates: List[Dict[str, Any]] = []
    episodes = 0
    unaudited = 0
    for shard, root in enumerate(shard_roots):
        for seq, manifest in segment_lib.list_sealed_segments(root):
            for uid in manifest.episode_uids:
                episodes += 1
                if not uid:
                    unaudited += 1
                    continue
                if uid in seen:
                    duplicates.append({
                        "uid": uid,
                        "first": seen[uid],
                        "second": (shard, seq),
                    })
                else:
                    seen[uid] = (shard, seq)
            # Manifests predating the uid field carry no list at all.
            if len(manifest.episode_uids) < manifest.episodes:
                unaudited += manifest.episodes - len(manifest.episode_uids)
                episodes += manifest.episodes - len(manifest.episode_uids)
    return {
        "episodes": episodes,
        "unaudited_episodes": unaudited,
        "duplicates": duplicates,
        "duplicate_count": len(duplicates),
    }
