"""Replay fabric wire transport: CRC-framed request/response over TCP.

The mp.Queue pair that carried replay traffic through PR 8 is bound to
one host by construction (queues ride fork/spawn inheritance); PR 9
replaced it with a length-prefixed, CRC-framed message stream over a
plain TCP socket. That machinery — the frame codec, the
whole-frame-or-nothing decode discipline, the published-address
`transport.json` discovery, the accept-loop server and the
self-healing client channel, plus the `net_send`/`net_recv` chaos
sites — is now shared with the serving fabric and lives in
`net/frames.py`: ONE wire implementation both fabrics consume, so the
two cannot drift (a fuzz finding against either is a finding against
both). This module re-exports it under the replay fabric's historical
names; every import, test, and byte of the replay wire is unchanged.

See `net/frames.py` for the frame format, decode discipline, address
discovery contract, and the chaos-site semantics. With `T2R_WIRE=spec`
(net/codec.py) the already-serialized episode record bytes inside
append/sample messages ride the frame as raw scatter-gather segments —
they are no longer pickled a second time into the frame body.
"""

from __future__ import annotations

from tensor2robot_tpu.net.frames import (  # noqa: F401
    ADDRESS_FILENAME,
    FRAME_HEADER,
    MAGIC,
    MAX_FRAME_BYTES,
    BadFrame,
    ConnectionClosed,
    FrameServer,
    PipelinedChannel,
    SocketChannel,
    TransportError,
    _recv_exact,
    encode_frame,
    publish_address,
    read_address,
    read_address_info,
    read_frame,
    wire_snapshot,
    write_frame,
)

__all__ = [
    "ADDRESS_FILENAME",
    "BadFrame",
    "ConnectionClosed",
    "MAX_FRAME_BYTES",
    "PipelinedChannel",
    "ReplayTransportServer",
    "SocketChannel",
    "TransportError",
    "encode_frame",
    "publish_address",
    "read_address",
    "read_address_info",
    "read_frame",
    "wire_snapshot",
    "write_frame",
]

# The replay fabric's server is the shared FrameServer in its original
# request/reply shape; the name survives for callers and logs.
ReplayTransportServer = FrameServer
