"""Convnet building blocks for grasping-style critics.

Behavioral reference: tensor2robot/research/dql_grasping_lib/tf_modules.py:
25-90 (`argscope`, `tile_to_match_context`, `add_context`). The slim
argscope (stride-2 VALID convs, truncated-normal init, relu, layer norm)
becomes an explicit `conv_block`; the context-merge helpers are pure jnp.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def conv_block(
    x: jax.Array,
    channels: int,
    kernel_size: int = 3,
    stride: int = 2,
    name: str = "conv",
) -> jax.Array:
    """conv(VALID, stride 2) + layer norm + relu — the reference argscope's
    per-layer recipe (tf_modules.py:25-44). Must be called inside an
    nn.compact parent."""
    x = nn.Conv(
        channels,
        (kernel_size, kernel_size),
        strides=(stride, stride),
        padding="VALID",
        kernel_init=nn.initializers.truncated_normal(stddev=0.01),
        name=name,
    )(x)
    x = nn.LayerNorm(name=f"{name}_ln")(x)
    return nn.relu(x)


def tile_to_match_context(net: jax.Array, context: jax.Array) -> jax.Array:
    """Tiles net along a new axis=1 to match context's per-batch examples
    (reference :47-69): [B, ...] + [B, M, C] -> [B, M, ...]."""
    num_samples = context.shape[1]
    expanded = jnp.expand_dims(net, 1)
    reps = [1] * expanded.ndim
    reps[1] = num_samples
    return jnp.tile(expanded, reps)


def add_context(net: jax.Array, context: jax.Array) -> jax.Array:
    """Broadcast-adds a [B*M, C] context into a [B*M, H, W, C] conv map
    (reference :72-90). `net` must already be tiled to B*M rows."""
    if net.shape[0] != context.shape[0]:
        raise ValueError(
            f"net rows {net.shape[0]} != context rows {context.shape[0]}; "
            "tile the conv map to the action megabatch first."
        )
    if net.shape[-1] != context.shape[-1]:
        raise ValueError(
            f"Channel mismatch: {net.shape[-1]} vs {context.shape[-1]}."
        )
    return net + context[:, None, None, :]
