"""Grasp2Vec: self-supervised grasp embeddings
(reference tensor2robot/research/grasp2vec/)."""

from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
    Grasp2VecModel,
    Grasp2VecPreprocessor,
)
from tensor2robot_tpu.research.grasp2vec.losses import (
    cosine_arithmetic_loss,
    keypoint_accuracy,
    l2_arithmetic_loss,
    npairs_loss,
    npairs_embedding_loss,
    send_to_zero_loss,
    triplet_embedding_loss,
)
from tensor2robot_tpu.research.grasp2vec.networks import Embedding
