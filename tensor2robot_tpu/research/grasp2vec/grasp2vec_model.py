"""Grasp2Vec model + preprocessor.

Behavioral reference: tensor2robot/research/grasp2vec/grasp2vec_model.py.
Learning signal: embedding arithmetic pre - post ≈ goal via bidirectional
n-pairs (or triplet) loss over per-image ResNet embeddings. Unsupervised —
the label spec is empty.

TPU notes: pre/post scene images are concatenated into one megabatch so the
scene tower runs a single large MXU-friendly forward pass (reference
:190-197); crops/flips happen in the preprocessor with explicit rng.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.models.abstract_model import MODE_TRAIN, FlaxT2RModel
from tensor2robot_tpu.research.grasp2vec import losses
from tensor2robot_tpu.research.grasp2vec.networks import Embedding
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    SpecTransformationPreprocessor,
)
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct

# (min_offset_height, max_offset_height, target_height,
#  min_offset_width, max_offset_width, target_width)
CropParams = Tuple[int, int, int, int, int, int]
_DEFAULT_CROP: CropParams = (0, 40, 472, 0, 168, 472)

_IMAGE_KEYS = ("pregrasp_image", "postgrasp_image", "goal_image")


def maybe_crop_images(
    images, params: CropParams, mode: str, rng: Optional[jax.Array]
):
    """Crops a list of images with one shared offset: random within
    [min, max) for train, centered otherwise (reference
    grasp2vec_model.py:45-74)."""
    (min_oh, max_oh, target_h, min_ow, max_ow, target_w) = params
    if mode == MODE_TRAIN and rng is not None:
        rng_h, rng_w = jax.random.split(rng)
        offset_h = jax.random.randint(rng_h, (), min_oh, max(max_oh, min_oh + 1))
        offset_w = jax.random.randint(rng_w, (), min_ow, max(max_ow, min_ow + 1))
    else:
        offset_h = jnp.asarray((min_oh + max_oh) // 2)
        offset_w = jnp.asarray((min_ow + max_ow) // 2)
    out = [
        jax.lax.dynamic_slice(
            img,
            (0, offset_h, offset_w, 0),
            (img.shape[0], target_h, target_w, img.shape[3]),
        )
        for img in images
    ]
    return out, offset_h, offset_w


def _random_flips(image: jax.Array, rng: jax.Array) -> jax.Array:
    """Independent per-image left-right and up-down flips."""
    rng_lr, rng_ud = jax.random.split(rng)
    batch = image.shape[0]
    flip_lr = jax.random.bernoulli(rng_lr, shape=(batch,))
    flip_ud = jax.random.bernoulli(rng_ud, shape=(batch,))
    image = jnp.where(flip_lr[:, None, None, None], image[:, :, ::-1, :], image)
    return jnp.where(flip_ud[:, None, None, None], image[:, ::-1, :, :], image)


class Grasp2VecPreprocessor(SpecTransformationPreprocessor):
    """512x640 jpeg uint8 source -> crop -> float [0,1] -> random flips
    (reference Grasp2VecPreprocessor, grasp2vec_model.py:77-135)."""

    def __init__(
        self,
        model_spec_provider=None,
        scene_crop: CropParams = _DEFAULT_CROP,
        goal_crop: CropParams = _DEFAULT_CROP,
    ):
        super().__init__(model_spec_provider)
        self._scene_crop = scene_crop
        self._goal_crop = goal_crop

    def _transform_in_feature_specification(self, spec, mode):
        for name in _IMAGE_KEYS:
            self.update_spec(
                spec,
                name,
                shape=(512, 640, 3),
                dtype=np.uint8,
                data_format="jpeg",
            )
        return spec

    def _preprocess_fn(self, features, labels, mode, rng):
        # No rng = no stochastic augmentation (center crops, no flips) —
        # the framework-wide None-rng convention.
        if rng is None:
            rng_scene = rng_goal = rng_flip = None
        else:
            rng_scene, rng_goal, rng_flip = jax.random.split(rng, 3)
        scene, _, _ = maybe_crop_images(
            [features["pregrasp_image"], features["postgrasp_image"]],
            self._scene_crop,
            mode,
            rng_scene,
        )
        features["pregrasp_image"] = scene[0]
        features["postgrasp_image"] = scene[1]
        features["goal_image"] = maybe_crop_images(
            [features["goal_image"]], self._goal_crop, mode, rng_goal
        )[0][0]
        # The scene pair shares one flip decision so pre/post stay spatially
        # aligned (the shared-crop invariant); the goal image flips
        # independently. (The reference flips every key independently,
        # grasp2vec_model.py:128-131 — a weaker choice we deliberately
        # tighten, since `pre - post ≈ goal` compares the scene pair.)
        flip = mode == MODE_TRAIN and rng_flip is not None
        if flip:
            flip_rngs = {
                "pregrasp_image": rng_flip,
                "postgrasp_image": rng_flip,
                "goal_image": jax.random.fold_in(rng_flip, 1),
            }
        for name in _IMAGE_KEYS:
            image = features[name].astype(jnp.float32) / 255.0
            if flip:
                image = _random_flips(image, flip_rngs[name])
            features[name] = image
        return features, labels


class _Grasp2VecNetwork(nn.Module):
    resnet_size: int = 50

    @nn.compact
    def __call__(self, features, mode: str):
        train = mode == MODE_TRAIN
        # One megabatch through the scene tower for pre+post.
        scene_images = jnp.concatenate(
            [features["pregrasp_image"], features["postgrasp_image"]], axis=0
        )
        v, s = Embedding(self.resnet_size, name="scene")(scene_images, train)
        pre_v, post_v = jnp.split(v, 2, axis=0)
        pre_s, post_s = jnp.split(s, 2, axis=0)
        goal_v, goal_s = Embedding(self.resnet_size, name="goal")(
            features["goal_image"], train
        )
        out = TensorSpecStruct()
        out["pre_vector"] = pre_v
        out["post_vector"] = post_v
        out["pre_spatial"] = pre_s
        out["post_spatial"] = post_s
        out["goal_vector"] = goal_v
        out["goal_spatial"] = goal_s
        return out


class Grasp2VecModel(FlaxT2RModel):
    """Grasp2Vec T2R model (reference grasp2vec_model.py:138-240)."""

    def __init__(
        self,
        scene_size: Tuple[int, int] = (472, 472),
        goal_size: Tuple[int, int] = (472, 472),
        embedding_loss_fn: Callable = losses.npairs_embedding_loss,
        resnet_size: int = 50,
        preprocessor_cls=None,
        **kwargs,
    ):
        if preprocessor_cls is None:
            # Derive crop windows from the requested output sizes so the
            # default preprocessor honors scene_size/goal_size (offsets span
            # the full 512x640 source slack, like the reference default
            # (0, 40, 472, 0, 168, 472) does for 472x472).
            def _crop_for(size: Tuple[int, int]) -> CropParams:
                th, tw = int(size[0]), int(size[1])
                if th > 512 or tw > 640:
                    raise ValueError(
                        f"Crop size {size} exceeds the 512x640 source."
                    )
                return (0, 512 - th, th, 0, 640 - tw, tw)

            scene_crop = _crop_for(scene_size)
            goal_crop = _crop_for(goal_size)

            def preprocessor_cls(model):
                return Grasp2VecPreprocessor(
                    model, scene_crop=scene_crop, goal_crop=goal_crop
                )

        super().__init__(preprocessor_cls=preprocessor_cls, **kwargs)
        self._scene_size = tuple(scene_size)
        self._goal_size = tuple(goal_size)
        self._embedding_loss_fn = embedding_loss_fn
        self._resnet_size = resnet_size

    def get_feature_specification(self, mode):
        spec = TensorSpecStruct()
        spec["pregrasp_image"] = ExtendedTensorSpec(
            shape=self._scene_size + (3,),
            dtype=np.float32,
            name="image",
            data_format="jpeg",
        )
        spec["postgrasp_image"] = ExtendedTensorSpec(
            shape=self._scene_size + (3,),
            dtype=np.float32,
            name="postgrasp_image",
            data_format="jpeg",
        )
        spec["goal_image"] = ExtendedTensorSpec(
            shape=self._goal_size + (3,),
            dtype=np.float32,
            name="present_image",
            data_format="jpeg",
        )
        return spec

    def get_label_specification(self, mode):
        # Unsupervised: no labels.
        return TensorSpecStruct()

    def create_network(self):
        return _Grasp2VecNetwork(resnet_size=self._resnet_size)

    def model_train_fn(self, features, labels, inference_outputs, mode):
        embed_loss = self._embedding_loss_fn(
            inference_outputs["pre_vector"],
            inference_outputs["goal_vector"],
            inference_outputs["post_vector"],
        )
        if isinstance(embed_loss, tuple):  # triplet returns (loss, pairs, labels)
            embed_loss = embed_loss[0]
        return embed_loss, {"embed_loss": embed_loss}
