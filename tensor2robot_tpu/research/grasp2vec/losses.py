"""Grasp2Vec embedding losses, jnp-native.

Behavioral reference: tensor2robot/research/grasp2vec/losses.py:20-200.
The tf_slim metric-learning primitives the reference calls (npairs_loss,
triplet_semihard_loss) are reimplemented here / in layers.tec with matching
semantics. Masked variants replace tf.dynamic_partition + tf.cond with
where-masked means, which XLA prefers (no data-dependent shapes).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import optax

from tensor2robot_tpu.layers.tec import triplet_semihard_loss


def npairs_loss(
    labels: jax.Array,
    embeddings_anchor: jax.Array,
    embeddings_positive: jax.Array,
    reg_lambda: float = 0.002,
) -> jax.Array:
    """N-pairs loss (tf_slim metric_learning.npairs_loss semantics):
    softmax cross-entropy over the anchor-positive similarity matrix with
    same-label targets, plus an L2 activation regularizer."""
    reg_anchor = jnp.mean(jnp.sum(jnp.square(embeddings_anchor), 1))
    reg_positive = jnp.mean(jnp.sum(jnp.square(embeddings_positive), 1))
    l2loss = 0.25 * reg_lambda * (reg_anchor + reg_positive)

    similarity = embeddings_anchor @ embeddings_positive.T
    same_label = (labels[:, None] == labels[None, :]).astype(similarity.dtype)
    targets = same_label / jnp.sum(same_label, axis=1, keepdims=True)
    xent = jnp.mean(optax.softmax_cross_entropy(similarity, targets))
    return xent + l2loss


def _masked_mean(values: jax.Array, mask: jax.Array) -> jax.Array:
    """Mean over mask==1 entries; 0 when the mask is empty (replaces the
    reference's dynamic_partition + cond)."""
    mask = mask.reshape(-1).astype(values.dtype)
    total = jnp.sum(mask)
    return jnp.where(
        total > 0, jnp.sum(values * mask) / jnp.maximum(total, 1.0), 0.0
    )


def l2_arithmetic_loss(
    pregrasp_embedding: jax.Array,
    goal_embedding: jax.Array,
    postgrasp_embedding: jax.Array,
    mask: jax.Array,
) -> jax.Array:
    """||pre - goal - post||^2 averaged over masked examples
    (reference losses.py:31-54)."""
    raw = pregrasp_embedding - goal_embedding - postgrasp_embedding
    distances = jnp.sum(jnp.square(raw), axis=1)
    return _masked_mean(distances, mask)


def cosine_arithmetic_loss(
    pregrasp_embedding: jax.Array,
    goal_embedding: jax.Array,
    postgrasp_embedding: jax.Array,
    mask: jax.Array,
) -> jax.Array:
    """Cosine distance between normalized (pre - post) and goal
    (reference losses.py:83-113)."""
    pair_a = _l2_normalize(pregrasp_embedding - postgrasp_embedding)
    pair_b = _l2_normalize(goal_embedding)
    distances = 1.0 - jnp.sum(pair_a * pair_b, axis=1)
    return _masked_mean(distances, mask)


def _l2_normalize(x: jax.Array, axis: int = 1) -> jax.Array:
    return x / jnp.maximum(jnp.linalg.norm(x, axis=axis, keepdims=True), 1e-12)


def triplet_embedding_loss(
    pregrasp_embedding: jax.Array,
    goal_embedding: jax.Array,
    postgrasp_embedding: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Semi-hard triplet loss over normalized (pre-post, goal) pairs
    (reference TripletLoss, losses.py:57-80). Returns (loss, pairs, labels)."""
    pair_a = _l2_normalize(pregrasp_embedding - postgrasp_embedding)
    pair_b = _l2_normalize(goal_embedding)
    n = pregrasp_embedding.shape[0]
    labels = jnp.tile(jnp.arange(n, dtype=jnp.int32), (2,))
    pairs = jnp.concatenate([pair_a, pair_b], axis=0)
    loss = triplet_semihard_loss(labels, pairs, margin=3.0)
    return loss, pairs, labels


def npairs_embedding_loss(
    pregrasp_embedding: jax.Array,
    goal_embedding: jax.Array,
    postgrasp_embedding: jax.Array,
    non_negativity_constraint: bool = False,
) -> jax.Array:
    """Bidirectional n-pairs loss over (pre - post, goal)
    (reference NPairsLoss, losses.py:161-196)."""
    pair_a = pregrasp_embedding - postgrasp_embedding
    if non_negativity_constraint:
        pair_a = jax.nn.relu(pair_a)
    pair_b = goal_embedding
    labels = jnp.arange(pregrasp_embedding.shape[0], dtype=jnp.int32)
    return npairs_loss(labels, pair_a, pair_b) + npairs_loss(
        labels, pair_b, pair_a
    )


def keypoint_accuracy(
    keypoints: jax.Array, labels: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Quadrant accuracy of spatial-softmax keypoints (Shapes dataset;
    reference losses.py:116-141). Returns (accuracy, loss)."""
    keypoints = keypoints.reshape(-1, 2)
    quadrant_centers = jnp.asarray(
        [[0.5, -0.5], [-0.5, -0.5], [0.5, 0.5], [-0.5, 0.5]],
        dtype=jnp.float32,
    )
    logits = keypoints @ quadrant_centers.T
    predictions = jnp.argmax(logits, axis=1)
    correct = (labels == predictions).astype(jnp.float32)
    labels_onehot = jax.nn.one_hot(labels, 4)
    loss = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels_onehot))
    return jnp.mean(correct), loss


def send_to_zero_loss(tensor: jax.Array, mask: jax.Array) -> jax.Array:
    """Mean L2 norm of masked rows (reference losses.py:144-158)."""
    distances = jnp.linalg.norm(tensor, axis=1)
    return _masked_mean(distances, mask)
