"""Grasp2Vec embedding towers.

Behavioral reference: tensor2robot/research/grasp2vec/networks.py:24-42
(Embedding): ResNet spatial features -> relu -> mean-pooled vector.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.resnet import ResNet


class Embedding(nn.Module):
    """Scene/goal embedding tower. Returns (summed embedding [B, C],
    spatial embedding map [B, h, w, C]).

    resnet_size is configurable (the reference pins ResNet50,
    grasp2vec/resnet.py:538); smaller sizes keep unit tests cheap.
    """

    resnet_size: int = 50

    @nn.compact
    def __call__(
        self, image: jax.Array, train: bool = False
    ) -> Tuple[jax.Array, jax.Array]:
        resnet = ResNet(
            num_classes=1, resnet_size=self.resnet_size, name="resnet"
        )
        _, endpoints = resnet(
            image, train, return_intermediate_values=True
        )
        spatial = nn.relu(endpoints["block_layer4"])
        summed = jnp.mean(spatial, axis=(1, 2))
        return summed, spatial
