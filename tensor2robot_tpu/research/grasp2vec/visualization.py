"""Grasp2Vec heatmap / keypoint visualizations.

Behavioral reference: tensor2robot/research/grasp2vec/visualization.py:78-260.
The reference writes TF summaries; here the functions return image arrays —
callers hand them to the metrics writer (train.metrics) or dump them to disk.
Heatmap math is jnp (device-side); rasterization is numpy (host-side, viz
only).
"""

from __future__ import annotations

import colorsys
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def compute_heatmap(
    feature_query: jax.Array, feature_map: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Dot product of a query embedding over a spatial feature map
    (reference add_heatmap_summary :78-98).

    Args:
      feature_query: [B, D] goal embeddings.
      feature_map: [B, h, w, D] scene embeddings.

    Returns:
      (heatmaps [B, h, w, 1], softmaxed heatmaps [B, h, w, 1]).
    """
    batch, dim = feature_query.shape
    query = feature_query.reshape(batch, 1, 1, dim)
    heatmaps = jnp.sum(feature_map * query, axis=3, keepdims=True)
    flat = heatmaps.reshape(batch, -1)
    softmaxed = jax.nn.softmax(flat, axis=-1).reshape(heatmaps.shape)
    return heatmaps, softmaxed


def heatmap_soft_argmax(heatmaps: jax.Array, temperature: float = 0.1) -> jax.Array:
    """Expected (x, y) location of a [B, h, w, 1] heatmap
    (reference add_spatial_softmax :101-111). Returns [B, 1, 2] xy in [-1, 1]."""
    from tensor2robot_tpu.layers.spatial_softmax import spatial_softmax

    points, _ = spatial_softmax(heatmaps, temperature=temperature)
    x, y = jnp.split(points, 2, axis=-1)
    return jnp.concatenate([x, y], axis=-1)[:, None, :]


def np_render_keypoints(
    image: np.ndarray,
    locations: np.ndarray,
    num_images: int = 3,
    dot_radius: int = 3,
) -> np.ndarray:
    """Rasterizes soft-argmax locations as colored dots on greyed images
    (reference np_render_keypoints :112-152)."""
    num_images = min(num_images, image.shape[0])
    _, h, w, _ = image.shape
    mx, my = np.meshgrid(np.arange(w), np.arange(h))
    num_points = locations.shape[1]
    images = []
    for i in range(num_images):
        img = np.tile(np.mean(image[i], axis=2, keepdims=True), [1, 1, 3])
        img = img / 2.0 + 0.4
        hues = np.linspace(0, 1, num_points + 1)[:-1]
        colors = [np.array(colorsys.hsv_to_rgb(h_, 1.0, 0.9)) for h_ in hues]
        xs = np.round((locations[i, :, 0] + 1.0) * w / 2.0).astype(int)
        ys = np.round((locations[i, :, 1] + 1.0) * h / 2.0).astype(int)
        for x, y, color in zip(xs, ys, colors):
            dist = np.sqrt((x - mx) ** 2 + (y - my) ** 2)
            weight = np.clip(dot_radius - dist, 0.0, 1.0)
            weight = np.tile(weight[:, :, None], [1, 1, 3])
            img = img * (1 - weight) + weight * color.reshape(1, 1, 3)
        images.append((img * 255).astype(np.uint8))
    return np.stack(images, 0)


def get_softmax_viz(
    image: np.ndarray, softmax: np.ndarray, nrows: Optional[int] = None
) -> np.ndarray:
    """Arranges softmax maps in a grid superimposed on the (greyscale) image
    via HSV encoding (reference get_softmax_viz :208-247)."""
    batch, sh, sw, num_points = softmax.shape
    th, tw = sh * 2, sw * 2
    if nrows is None:
        divs = [d for d in range(1, int(np.sqrt(num_points)) + 1)
                if num_points % d == 0]
        nrows = max(divs) if divs else 1
    ncols = num_points // nrows

    img = softmax / np.maximum(
        softmax.max(axis=(1, 2), keepdims=True), 1e-12
    )
    grey = np.mean(image, axis=3, keepdims=True)
    grey = np.asarray(
        jax.image.resize(jnp.asarray(grey), (batch, th, tw, 1), "nearest")
    )
    grey = np.tile(grey, [1, 1, 1, num_points])[..., None]
    img = np.asarray(
        jax.image.resize(jnp.asarray(img), (batch, th, tw, num_points), "nearest")
    )[..., None]
    hsv = np.concatenate([img / 2.0 + 0.5, img, grey * 0.7 + 0.3], axis=4)
    hsv = hsv.reshape(batch, th, tw, nrows, ncols, 3)
    hsv = hsv.transpose(0, 3, 1, 4, 2, 5).reshape(
        batch, th * nrows, tw * ncols, 3
    )
    # HSV -> RGB, vectorized.
    h_, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h_ * 6.0) % 6
    f = h_ * 6.0 - np.floor(h_ * 6.0)
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    rgb = np.select(
        [i[..., None] == k for k in range(6)],
        [
            np.stack(c, axis=-1)
            for c in [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v), (v, p, q)]
        ],
    )
    return rgb
