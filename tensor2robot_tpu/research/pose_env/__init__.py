from tensor2robot_tpu.config import external_configurable
from tensor2robot_tpu.research.pose_env.episode_to_transitions import (
    episode_to_transitions_pose_toy,
)
from tensor2robot_tpu.research.pose_env.pose_env import (
    PoseEnvRandomPolicy,
    PoseToyEnv,
)
from tensor2robot_tpu.research.pose_env.pose_env_maml_models import (
    PoseEnvRegressionModelMAML,
)
from tensor2robot_tpu.research.pose_env.pose_env_models import (
    DefaultPoseEnvContinuousPreprocessor,
    DefaultPoseEnvRegressionPreprocessor,
    PoseEnvContinuousMCModel,
    PoseEnvRegressionModel,
)

for _cls in (
    PoseEnvContinuousMCModel,
    PoseEnvRegressionModel,
    PoseEnvRegressionModelMAML,
):
    external_configurable(_cls, _cls.__name__)
