"""PoseToyEnv episode -> transition Examples.

Behavioral reference:
tensor2robot/research/pose_env/episode_to_transitions.py:31-50
(`episode_to_transitions_pose_toy`): the supervised pose-regression layout —
jpeg state image, attempted pose, reward, true target pose.
"""

from __future__ import annotations

import numpy as np

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.proto import example_pb2
from tensor2robot_tpu.utils import image as image_lib


@configurable("episode_to_transitions_pose_toy")
def episode_to_transitions_pose_toy(
    episode_data, binary_success_threshold=None
):
    """Converts pose toy env episodes to transition Examples
    (reference :31-50).

    Args:
      episode_data: (obs, action, reward, new_obs, done, debug) tuples.
      binary_success_threshold: if set, rewards are relabeled to
        1.0 when above the threshold else 0.0 — giving the downstream
        reward-weighted losses proper non-negative sample weights (the
        env's raw reward is a negative distance).
    """
    transitions = []
    for transition in episode_data:
        obs_t, action, reward, _, _, debug = transition
        if binary_success_threshold is not None:
            reward = float(reward > binary_success_threshold)
        example = example_pb2.Example()
        feature = example.features.feature
        feature["state/image"].bytes_list.value.append(
            image_lib.numpy_to_image_string(obs_t, "jpeg")
        )
        feature["pose"].float_list.value.extend(
            np.asarray(action, np.float32).reshape(-1).tolist()
        )
        feature["reward"].float_list.value.append(float(reward))
        feature["target_pose"].float_list.value.extend(
            np.asarray(debug["target_pose"], np.float32).reshape(-1).tolist()
        )
        transitions.append(example)
    return transitions
