"""PoseToyEnv: the minimal end-to-end testbed environment.

Behavioral reference: tensor2robot/research/pose_env/pose_env.py:36-178
(`PoseEnvRandomPolicy` :36, `PoseToyEnv` :52). Task: an object sits at a
random planar pose; the observation is a rendered 64x64 image; the (single
step) action is the predicted (x, y); reward = -||action - target_xy||; with
`hidden_drift` each task offsets the rendered pose by a hidden amount, so
only meta-adaptation can close the gap.

The reference renders with PyBullet. PyBullet is not part of this stack, so
rendering is a built-in numpy rasterizer (object = oriented ellipse with a
nose marker on a textured ground, camera yaw randomized per task) — same
observation/action/reward contract, no native sim dependency, and tests run
hermetically.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from tensor2robot_tpu.config import configurable


@configurable("PoseEnvRandomPolicy")
class PoseEnvRandomPolicy:
    """Uniform-random pose guesses, used for dataset generation
    (reference :36-48)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.RandomState(seed)

    def reset(self):
        pass

    def reset_task(self):
        pass

    def restore(self, is_async: bool = False) -> bool:
        """No weights to restore; always ready (collect_eval_loop
        polls this before each cycle)."""
        del is_async
        return True

    def init_randomly(self) -> None:
        pass

    def close(self) -> None:
        pass

    @property
    def global_step(self) -> int:
        return 0

    def sample_action(self, obs, explore_prob):
        del obs, explore_prob
        return self._rng.uniform(low=-1.0, high=1.0, size=2), None


@configurable("PoseToyEnv")
class PoseToyEnv:
    """Predict object pose from an image (reference PoseToyEnv :52-178).

    Episodes are one step: reset() -> observation image; step(pose) ->
    (observation, reward, done=True, {'target_pose': xy}).
    """

    WIDTH, HEIGHT = 64, 64

    def __init__(
        self,
        render_mode: str = "DIRECT",
        hidden_drift: bool = False,
        seed: Optional[int] = None,
    ):
        del render_mode  # Headless always; kept for config parity.
        self._rng = np.random.RandomState(seed)
        self._hidden_drift = hidden_drift
        self._hidden_drift_xy = np.zeros(2, np.float32)
        self._camera_yaw = 0.0
        self._ground_phase = 0.0
        self.reset_task()

    # -- task structure ------------------------------------------------------

    def reset_task(self) -> None:
        """New camera + (optionally) new hidden drift (reference :113-121)."""
        self._camera_yaw = self._rng.uniform(-np.pi, np.pi)
        self._ground_phase = self._rng.uniform(0, 2 * np.pi)
        if self._hidden_drift:
            self._hidden_drift_xy = self._rng.uniform(
                low=-0.3, high=0.3, size=2
            ).astype(np.float32)
        self.set_new_pose()

    def set_new_pose(self) -> None:
        """Samples the rendered pose; with hidden_drift the *label* pose is
        offset from what is rendered (reference :115-121: drift is added to
        _target_pose after the duck is moved to the raw pose)."""
        self._rendered_pose = self._sample_pose()
        self._target_pose = self._rendered_pose.copy()
        if self._hidden_drift:
            self._target_pose[:2] += self._hidden_drift_xy

    def _sample_pose(self) -> np.ndarray:
        x = self._rng.uniform(low=-0.7, high=0.7)
        y = self._rng.uniform(low=-0.4, high=0.4)
        angle = self._rng.uniform(low=-np.pi, high=np.pi)
        return np.array([x, y, angle], np.float32)

    # -- rendering -----------------------------------------------------------

    def _render(self) -> np.ndarray:
        """64x64x3 uint8 image of the object at (possibly drifted) pose."""
        x, y, angle = self._rendered_pose
        # Rotate world by the per-task camera yaw.
        c, s = np.cos(self._camera_yaw), np.sin(self._camera_yaw)
        cam_x = c * x - s * y
        cam_y = s * x + c * y

        h, w = self.HEIGHT, self.WIDTH
        ys, xs = np.mgrid[0:h, 0:w].astype(np.float32)
        # World [-1, 1] box -> pixels.
        px = (cam_x + 1.0) * (w - 1) / 2.0
        py = (cam_y + 1.0) * (h - 1) / 2.0

        # Ground: task-dependent striped texture (stands in for the table).
        ground = 96 + 32 * np.sin(
            0.25 * (xs * c + ys * s) + self._ground_phase
        )
        image = np.stack([ground * 0.9, ground, ground * 1.1], axis=-1)

        # Object: oriented ellipse with a nose marker encoding the angle.
        obj_angle = angle + self._camera_yaw
        ca, sa = np.cos(obj_angle), np.sin(obj_angle)
        dx, dy = xs - px, ys - py
        u = ca * dx + sa * dy
        v = -sa * dx + ca * dy
        body = (u / 7.0) ** 2 + (v / 4.5) ** 2 <= 1.0
        nose = ((u - 6.0) / 2.5) ** 2 + (v / 2.0) ** 2 <= 1.0
        image[body] = (230.0, 200.0, 40.0)
        image[nose] = (240.0, 120.0, 30.0)
        return np.clip(image, 0, 255).astype(np.uint8)

    def get_observation(self) -> np.ndarray:
        return self._render()

    # -- episode API ---------------------------------------------------------

    def reset(self) -> np.ndarray:
        return self.get_observation()

    def step(self, action) -> Tuple[np.ndarray, float, bool, dict]:
        reward = float(
            -np.linalg.norm(np.asarray(action) - self._target_pose[:2])
        )
        done = True
        debug = {"target_pose": self._target_pose[:2].astype(np.float32)}
        observation = self.get_observation()
        return observation, reward, done, debug
