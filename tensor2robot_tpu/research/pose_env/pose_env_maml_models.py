"""MAML variant of the PoseEnv regression model.

Behavioral reference:
tensor2robot/research/pose_env/pose_env_maml_models.py:29-110
(`PoseEnvRegressionModelMAML`): selects the regression output for meta
policies and packs live observations + conditioning transitions into the
MetaExample feature layout; missing conditioning episodes become dummy
entries with reward 0 so the inner loop applies no gradient (the
reward-weighted loss zeroes out).
"""

from __future__ import annotations

import numpy as np

from tensor2robot_tpu.meta_learning.maml_model import MAMLModel
from tensor2robot_tpu.specs import TensorSpecStruct


class PoseEnvRegressionModelMAML(MAMLModel):
    """MAML regression for the duck task (reference :29-110)."""

    def _make_dummy_labels(self) -> TensorSpecStruct:
        label_spec = self._base_model.get_label_specification("train")
        return TensorSpecStruct(
            reward=np.zeros(tuple(label_spec["reward"].shape), np.float32),
            target_pose=np.zeros(
                tuple(label_spec["target_pose"].shape), np.float32
            ),
        )

    def _select_inference_output(self, predictions: TensorSpecStruct):
        predictions["condition_output"] = predictions[
            "full_condition_output/inference_output"
        ]
        predictions["inference_output"] = predictions[
            "full_inference_output/inference_output"
        ]
        return predictions

    def pack_features(self, state, prev_episode_data, timestep) -> dict:
        """Packs obs + conditioning transitions into MetaExample columns
        (reference pack_features :52-110)."""
        meta_features = {}
        meta_features["inference/features/state/0"] = state

        def pack_condition_features(transition, idx, dummy_values=False):
            observation, action, reward = (
                transition[0],
                transition[1],
                transition[2],
            )
            meta_features[f"condition/features/state/{idx}"] = observation
            reward = 2.0 * np.asarray([reward], np.float32) - 1.0
            if dummy_values:
                # Weight 0 => no inner-loop gradient for this entry.
                reward = np.array([0.0], np.float32)
            meta_features[f"condition/labels/target_pose/{idx}"] = np.asarray(
                action, np.float32
            )
            meta_features[f"condition/labels/reward/{idx}"] = reward

        if prev_episode_data:
            pack_condition_features(prev_episode_data[0][0], 0)
        else:
            dummy_labels = self._make_dummy_labels()
            dummy_transition = (
                state,
                dummy_labels["target_pose"],
                float(dummy_labels["reward"][0]),
            )
            pack_condition_features(dummy_transition, 0, dummy_values=True)
        return {
            key: np.expand_dims(np.asarray(value), 0)
            for key, value in meta_features.items()
        }
