"""PoseEnv models: the minimal end-to-end train/collect/eval testbed.

Behavioral reference: tensor2robot/research/pose_env/pose_env_models.py
(`DefaultPoseEnvContinuousPreprocessor` :41-88,
`PoseEnvContinuousMCModel` :91-178, `DefaultPoseEnvRegressionPreprocessor`
:181-226, `PoseEnvRegressionModel` :229-324).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers.vision_layers import (
    ImageFeaturesToPoseNet,
    ImagesToFeaturesNet,
)
from tensor2robot_tpu.models.abstract_model import MODE_TRAIN
from tensor2robot_tpu.models.base_models import CriticModel, RegressionModel
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    SpecTransformationPreprocessor,
)
from tensor2robot_tpu.research.dql_grasping_lib import tf_modules
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
)


class DefaultPoseEnvContinuousPreprocessor(SpecTransformationPreprocessor):
    """uint8 jpeg image source -> float32 [0, 1] (reference :41-88)."""

    def _transform_in_feature_specification(self, spec, mode):
        self.update_spec(spec, "state/image", dtype=np.uint8)
        return spec

    def _preprocess_fn(self, features, labels, mode, rng):
        features["state/image"] = (
            features["state/image"].astype(jnp.float32) / 255.0
        )
        return features, labels


class _PoseMCNet(nn.Module):
    """Q(image, pose) tower (reference _q_features + q_func :117-173):
    3 stride-2 VALID convs with layer norm, action context broadcast-added
    to the conv map, then an fc stack to one Q logit."""

    channels: int = 32

    @nn.compact
    def __call__(self, features, mode):
        image = features.state.image
        pose = features.action.pose
        tiled = pose.ndim == 3
        if tiled:
            # CEM megabatch: [B, N, 2] actions against [B, H, W, C] states.
            action_batch = pose.shape[1]
            pose = pose.reshape(-1, pose.shape[-1])

        net = image
        for i in range(3):
            net = tf_modules.conv_block(
                net, self.channels, name=f"conv{i}"
            )
        context = nn.Dense(self.channels, name="action_fc")(pose)
        context = nn.relu(nn.LayerNorm(name="action_ln")(context))
        if tiled:
            net = jnp.repeat(net, action_batch, axis=0)
        net = tf_modules.add_context(net, context)
        net = net.reshape(net.shape[0], -1)
        for i, width in enumerate((100, 100)):
            net = nn.Dense(width, name=f"fc{i}")(net)
            net = nn.relu(nn.LayerNorm(name=f"fc_ln{i}")(net))
        q = nn.Dense(1, name="q")(net)
        q = jnp.squeeze(q, -1)
        if tiled:
            q = q.reshape(-1, action_batch)
        out = TensorSpecStruct()
        out["q_predicted"] = q
        return out


class PoseEnvContinuousMCModel(CriticModel):
    """Monte-Carlo critic Q(image, pose) (reference :91-178)."""

    def __init__(self, **kwargs):
        kwargs.setdefault(
            "preprocessor_cls", DefaultPoseEnvContinuousPreprocessor
        )
        super().__init__(**kwargs)

    def get_state_specification(self) -> TensorSpecStruct:
        return TensorSpecStruct(
            image=ExtendedTensorSpec(
                shape=(64, 64, 3),
                dtype=np.float32,
                name="state/image",
                data_format="jpeg",
            )
        )

    def get_action_specification(self) -> TensorSpecStruct:
        return TensorSpecStruct(
            pose=ExtendedTensorSpec(
                shape=(2,), dtype=np.float32, name="pose"
            )
        )

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        return TensorSpecStruct(
            reward=ExtendedTensorSpec(
                shape=(), dtype=np.float32, name="reward"
            )
        )

    def create_network(self) -> nn.Module:
        return _PoseMCNet()

    def model_train_fn(self, features, labels, inference_outputs, mode):
        # MC regression of Q toward observed return (the env's reward is
        # continuous, so MSE rather than the log loss of binary critics).
        q = inference_outputs["q_predicted"]
        loss = jnp.mean(jnp.square(q - labels["reward"]))
        return loss, {"loss/q_mse": loss}

    def model_eval_fn(self, features, labels, inference_outputs):
        loss, metrics = self.model_train_fn(
            features, labels, inference_outputs, "eval"
        )
        out = {"loss": loss}
        out.update(metrics)
        return out

    def pack_features(self, state, context, timestep, actions):
        """(obs, CEM action population) -> predict features in the CEM
        megabatch layout: [1, ...] state + [1, N, 2] actions
        (reference :175-178; the net's tiled branch scores all N at once)."""
        del context, timestep
        actions = np.asarray(actions)
        if actions.ndim == 2:
            actions = actions[None, ...]
        return {
            "state/image": np.expand_dims(state, 0),
            "action/pose": actions,
        }


class DefaultPoseEnvRegressionPreprocessor(SpecTransformationPreprocessor):
    """uint8 source image -> float32 (reference :181-226)."""

    def _transform_in_feature_specification(self, spec, mode):
        self.update_spec(spec, "state", dtype=np.uint8)
        return spec

    def _preprocess_fn(self, features, labels, mode, rng):
        features["state"] = features["state"].astype(jnp.float32) / 255.0
        return features, labels


class _PoseRegressionNet(nn.Module):
    action_size: int

    @nn.compact
    def __call__(self, features, mode):
        feature_points, _ = ImagesToFeaturesNet(
            normalizer="layer_norm", name="state_features"
        )(features["state"], mode == MODE_TRAIN)
        estimated_pose, _ = ImageFeaturesToPoseNet(
            num_outputs=self.action_size, name="pose_net"
        )(feature_points)
        out = TensorSpecStruct()
        out["inference_output"] = estimated_pose
        out["state_features"] = feature_points
        return out


class PoseEnvRegressionModel(RegressionModel):
    """Image -> pose regression, reward-weighted MSE (reference :229-324)."""

    def __init__(self, action_size: int = 2, **kwargs):
        kwargs.setdefault(
            "preprocessor_cls", DefaultPoseEnvRegressionPreprocessor
        )
        super().__init__(**kwargs)
        self._action_size = action_size

    @property
    def action_size(self) -> int:
        return self._action_size

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        return TensorSpecStruct(
            state=ExtendedTensorSpec(
                shape=(64, 64, 3),
                dtype=np.float32,
                name="state/image",
                data_format="jpeg",
            )
        )

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        return TensorSpecStruct(
            target_pose=ExtendedTensorSpec(
                shape=(self._action_size,),
                dtype=np.float32,
                name="target_pose",
            ),
            reward=ExtendedTensorSpec(
                shape=(1,), dtype=np.float32, name="reward"
            ),
        )

    def create_network(self) -> nn.Module:
        return _PoseRegressionNet(action_size=self._action_size)

    def model_train_fn(self, features, labels, inference_outputs, mode):
        # Reward-weighted MSE (reference loss_fn :317-324). Weights are
        # clamped to >= 0: the env's raw rewards are negative distances, and
        # a negative weight would flip the objective into error
        # *maximization*; zero-weight entries (the MAML dummy-episode
        # masking trick) still contribute no gradient.
        weights = jnp.maximum(labels["reward"], 0.0)
        squared = jnp.square(
            inference_outputs["inference_output"] - labels["target_pose"]
        )
        loss = jnp.sum(weights * squared) / jnp.maximum(
            jnp.sum(weights) * squared.shape[-1], 1e-6
        )
        return loss, {"loss/weighted_mse": loss}

    def pack_features(self, state, context, timestep):
        del context, timestep
        return {"state": np.expand_dims(state, 0)}
