from tensor2robot_tpu.research.qtopt import networks, optimizer_builder, pcgrad
from tensor2robot_tpu.research.qtopt.t2r_models import (
    DefaultGrasping44ImagePreprocessor,
    Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
    GraspingModelWrapper,
)
