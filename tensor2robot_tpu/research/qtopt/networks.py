"""QT-Opt grasping Q-networks, flax-native.

Behavioral reference: tensor2robot/research/qtopt/networks.py:300-741
(`Grasping44FlexibleGraspParams` and the E2E open/close/terminate variant).
Architecture (the "Grasping44" tower):

  472x472x3 image
    -> conv 64@6x6 /2 (no norm) -> BN(scale=False) -> relu -> maxpool 3x3 /3
    -> 6x [conv 64@5x5 + BN + relu]            -> maxpool 3x3 /3   (pool2)
  grasp params (one Dense(256) per named block, summed)
    -> BN(scale=False) -> relu -> Dense(64)    -> context [B,1,1,64]
  merge: image embedding (+ CEM megabatch tiling) + context broadcast-add
    -> 6x [conv 64@3x3 + BN + relu]            -> maxpool 2x2 /2
    -> 3x [conv 64@3x3 VALID + BN + relu]                        (final_conv)
    -> flatten -> 2x Dense(64) -> Dense(1) logit -> sigmoid

TPU-first notes: the CEM action megabatch is tiled *after* the conv tower
(reference networks.py:412-421 + tile_batch at :522) so the expensive image
convs run once per state, not once per action sample — the tiled add and the
tail convs stay one large MXU-batched program. All convs are NHWC float
(bf16-friendly); batch-norm statistics live in flax's `batch_stats`.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.batch_norm import BatchNorm
from tensor2robot_tpu.layers.s2d_conv import SpaceToDepthConv, stem_s2d_enabled
from tensor2robot_tpu.ops import pooling

# Named grasp-param sub-blocks of the E2E variant: {name: (offset, size)}
# (reference networks.py:724-732). Separate per-block input projections.
E2E_GRASP_PARAM_BLOCKS: Dict[str, Tuple[int, int]] = {
    "fcgrasp_wv": (0, 3),
    "fcgrasp_vr": (3, 2),
    "fcgrasp_gripper_close": (5, 1),
    "fcgrasp_gripper_open": (6, 1),
    "fcgrasp_terminate_episode": (7, 1),
    "fcgrasp_gripper_closed": (8, 1),
    "fcgrasp_height_to_bottom": (9, 1),
}

_CONV_INIT = nn.initializers.truncated_normal(stddev=0.01)


class _ConvBNRelu(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    momentum: float = 0.9997
    epsilon: float = 0.001
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, is_training: bool) -> jax.Array:
        # Conv AND BatchNorm compute in `dtype` (bf16 on the TPU forward
        # path: params are cast for the MXU matmul, master copies stay
        # f32). Passing dtype to BN is statistics-safe — flax computes
        # batch mean/var internally in f32 regardless, and the running
        # stats live in f32 param storage — while keeping the normalized
        # activation in the compute dtype, so no f32 copy of the full
        # activation ever needs to reach HBM (at bs64/472px the stage-1
        # activation is 456 MB in bf16; an f32 normalize output doubles
        # the block's write traffic on the usual-bottleneck bandwidth).
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding=self.padding,
            use_bias=False,
            kernel_init=_CONV_INIT,
            dtype=self.dtype,
        )(x)
        x = BatchNorm(
            use_running_average=not is_training,
            momentum=self.momentum,
            epsilon=self.epsilon,
            use_scale=True,
            dtype=self.dtype,
        )(x)
        return nn.relu(x)


class Grasping44(nn.Module):
    """The flexible-grasp-params Grasping44 Q-tower.

    Call with `images` [B, 472, 472, 3] and `grasp_params` either
    [B, P] (train/eval) or [B, N, P] (CEM megabatch; N = action_batch_size).
    Returns (logits, end_points) where end_points['predictions'] is
    sigmoid(logits), reshaped to [B, N] when action-tiled — matching the
    reference contract (networks.py:586-600).
    """

    grasp_param_blocks: Optional[Dict[str, Tuple[int, int]]] = None
    num_convs: Sequence[int] = (6, 6, 3)
    hid_layers: int = 2
    num_classes: int = 1
    # Reference batch_norm_decay=0.9997 (networks.py:45 slim arg_scope).
    batch_norm_momentum: float = 0.9997
    batch_norm_epsilon: float = 0.001
    # Conv-tower channel count. 64 is the reference architecture; 128 is
    # the MXU-width-aligned twin used to settle whether the 64-channel
    # tower (half the 128-lane systolic array width) caps achievable MFU
    # (docs/PERFORMANCE.md ceiling analysis). Not a reference knob.
    width: int = 64

    @nn.compact
    def __call__(
        self,
        images: jax.Array,
        grasp_params: jax.Array,
        is_training: bool = False,
        softmax: bool = False,
        goal_spatial: Optional[jax.Array] = None,
        goal_vector: Optional[jax.Array] = None,
    ):
        end_points: Dict[str, jax.Array] = {}
        tile_batch = grasp_params.ndim == 3
        action_batch_size = grasp_params.shape[1] if tile_batch else 1
        if tile_batch:
            # Collapse [B, N, P] -> [B*N, P] megabatch.
            grasp_params = grasp_params.reshape(-1, grasp_params.shape[-1])

        # Compute dtype follows the infeed: a bf16 image (the TPU wrapper's
        # train_in_bfloat16 policy) makes every conv/dense MXU op compute in
        # bf16 with f32 master params; f32 inputs keep the full-precision
        # path. BatchNorm always promotes to f32 (see _ConvBNRelu).
        dtype = jnp.bfloat16 if images.dtype == jnp.bfloat16 else None

        # BN computes in the network dtype (stats stay f32 inside flax;
        # see _ConvBNRelu) so no f32 copy of a full activation reaches
        # HBM — bn1's output is the largest activation in the network
        # ([B, 236, 236, 64] at 472px) and the round-3 profile showed its
        # f32 spill dominating the stem's bandwidth.
        bn_kwargs = dict(
            use_running_average=not is_training,
            momentum=self.batch_norm_momentum,
            epsilon=self.batch_norm_epsilon,
            dtype=dtype,
        )

        # Stem: conv without norm/activation, then a standalone unscaled BN
        # (reference keeps scale=False on the standalone BNs, :444-458).
        # The stem can lower via space-to-depth (layers/s2d_conv.py) — an
        # exact reformulation that fills the MXU's reduction lanes; both
        # lowerings share the checkpoint layout and the "conv1_1" name.
        if stem_s2d_enabled():
            net = SpaceToDepthConv(
                self.width, (6, 6), strides=(2, 2),
                kernel_init=_CONV_INIT, name="conv1_1", dtype=dtype,
            )(images)
        else:
            net = nn.Conv(
                self.width, (6, 6), strides=(2, 2), padding="SAME",
                use_bias=False, kernel_init=_CONV_INIT, name="conv1_1",
                dtype=dtype,
            )(images)
        net = BatchNorm(use_scale=False, name="bn1", **bn_kwargs)(net)
        net = nn.relu(net)
        # Non-overlapping pools dispatch the backward on the backend:
        # SelectAndScatter on TPU, scatter-free elsewhere (ops/pooling.py;
        # on-chip A/B in DIAG_STEP_r05.json). Forward is bit-identical to
        # nn.max_pool either way.
        net = pooling.max_pool(net, (3, 3))

        for i in range(self.num_convs[0]):
            net = _ConvBNRelu(
                self.width, (5, 5),
                momentum=self.batch_norm_momentum,
                epsilon=self.batch_norm_epsilon,
                name=f"conv{2 + i}",
                dtype=dtype,
            )(net, is_training)
        net = pooling.max_pool(net, (3, 3))
        end_points["pool2"] = net

        # Grasp-param input head: one linear projection per named block,
        # summed (reference :470-502); unnamed params use a single block.
        if self.grasp_param_blocks is None:
            blocks = {"fcgrasp": (0, grasp_params.shape[-1])}
        else:
            blocks = self.grasp_param_blocks
        fcgrasp = None
        for name in sorted(blocks):
            offset, size = blocks[name]
            piece = nn.Dense(256, kernel_init=_CONV_INIT, name=name, dtype=dtype)(
                grasp_params[:, offset : offset + size]
            )
            fcgrasp = piece if fcgrasp is None else fcgrasp + piece
        fcgrasp = BatchNorm(use_scale=False, name="bn_fcgrasp", **bn_kwargs)(
            fcgrasp
        )
        fcgrasp = nn.relu(fcgrasp)
        fcgrasp = nn.Dense(
            self.width, kernel_init=_CONV_INIT, name="fcgrasp2", dtype=dtype
        )(fcgrasp)
        fcgrasp = BatchNorm(name="bn_fcgrasp2", **bn_kwargs)(fcgrasp)
        fcgrasp = nn.relu(fcgrasp)
        end_points["fcgrasp"] = fcgrasp
        context = fcgrasp.reshape(-1, 1, 1, self.width)
        if dtype is not None:
            context = context.astype(dtype)

        if tile_batch:
            # Tile the *embedding* (not the raw image) to the megabatch:
            # [B, h, w, c] -> [B*N, h, w, c] with each state repeated N times.
            net = jnp.repeat(net, action_batch_size, axis=0)
        net = net + context
        end_points["vsum"] = net

        for i in range(self.num_convs[1]):
            net = _ConvBNRelu(
                self.width, (3, 3),
                momentum=self.batch_norm_momentum,
                epsilon=self.batch_norm_epsilon,
                name=f"conv{2 + self.num_convs[0] + i}",
                dtype=dtype,
            )(net, is_training)
        net = pooling.max_pool(net, (2, 2))
        for i in range(self.num_convs[2]):
            net = _ConvBNRelu(
                self.width, (3, 3), padding="VALID",
                momentum=self.batch_norm_momentum,
                epsilon=self.batch_norm_epsilon,
                name=f"conv{2 + sum(self.num_convs[:2]) + i}",
                dtype=dtype,
            )(net, is_training)
        end_points["final_conv"] = net

        if goal_spatial is not None:
            reps = net.shape[0] // goal_spatial.shape[0]
            net = jnp.concatenate(
                [net, jnp.tile(goal_spatial, (reps, 1, 1, 1))], axis=3
            )
        net = net.reshape(net.shape[0], -1)
        if goal_vector is not None:
            reps = net.shape[0] // goal_vector.shape[0]
            net = jnp.concatenate([net, jnp.tile(goal_vector, (reps, 1))], axis=1)

        for i in range(self.hid_layers):
            net = nn.Dense(64, kernel_init=_CONV_INIT, name=f"fc{i}", dtype=dtype)(
                net
            )
            net = BatchNorm(name=f"bn_fc{i}", **bn_kwargs)(net)
            net = nn.relu(net)

        # Logit head computes and emits float32: the loss-bearing scalar
        # (and the sigmoid CEM objective) should not quantize to bf16.
        logits = nn.Dense(
            self.num_classes, kernel_init=_CONV_INIT, name="logit"
        )(net.astype(jnp.float32))
        end_points["logits"] = logits
        predictions = (
            jax.nn.softmax(logits) if softmax else jax.nn.sigmoid(logits)
        )
        if tile_batch:
            if self.num_classes > 1:
                predictions = predictions.reshape(
                    -1, action_batch_size, self.num_classes
                )
            else:
                predictions = predictions.reshape(-1, action_batch_size)
        elif self.num_classes == 1:
            predictions = predictions.reshape(-1)
        end_points["predictions"] = predictions
        return logits, end_points


def concat_e2e_grasp_params(action: Dict[str, jax.Array]) -> jax.Array:
    """Packs the E2E action struct into the flat 10-dim grasp-params layout
    the block table indexes (reference create_grasp_params_input +
    grasp_param_sizes, networks.py:668-676)."""
    keys = (
        "world_vector",            # 3
        "vertical_rotation",       # 2
        "close_gripper",           # 1
        "open_gripper",            # 1
        "terminate_episode",       # 1
        "gripper_closed",          # 1
        "height_to_bottom",        # 1
    )
    return jnp.concatenate([action[k] for k in keys], axis=-1)
