"""QT-Opt optimizer construction over optax.

Behavioral reference: tensor2robot/research/qtopt/optimizer_builder.py:25-96
(`BuildOpt`): exponential-decay LR derived from examples_per_epoch /
num_epochs_per_decay, then momentum | rmsprop | adam. The reference's
MovingAverageOptimizer wrap is expressed TPU-natively as the trainer's EMA
param tree (`use_avg_model_params` on the model; see train/state.py) — optax
keeps the optimizer a pure gradient transformation.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import optax


@dataclasses.dataclass
class QtOptHParams:
    """The hyperparameter bundle `BuildOpt` consumed as tf.HParams."""

    batch_size: int = 32
    examples_per_epoch: int = 3_000_000
    learning_rate: float = 1e-4
    learning_rate_decay_factor: float = 0.999
    model_weights_averaging: float = 0.9999
    momentum: float = 0.9
    num_epochs_per_decay: float = 2.0
    optimizer: str = "momentum"
    rmsprop_decay: float = 0.9
    rmsprop_epsilon: float = 1.0
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    use_avg_model_params: bool = True


def build_learning_rate(hparams: QtOptHParams) -> optax.Schedule:
    """Staircase exponential decay stepped every
    examples_per_epoch / batch_size * num_epochs_per_decay steps
    (reference optimizer_builder.py:61-70)."""
    decay_steps = int(
        hparams.examples_per_epoch / hparams.batch_size
        * hparams.num_epochs_per_decay
    )
    return optax.exponential_decay(
        init_value=hparams.learning_rate,
        transition_steps=max(decay_steps, 1),
        decay_rate=hparams.learning_rate_decay_factor,
        staircase=True,
    )


def build_opt(hparams: Optional[QtOptHParams] = None) -> optax.GradientTransformation:
    """Constructs the QT-Opt optimizer (reference BuildOpt :25-96).

    The caller (GraspingModelWrapper) owns EMA/"swapping saver" semantics via
    `use_avg_model_params`; this function returns only the descent rule.
    """
    hparams = hparams or QtOptHParams()
    learning_rate = build_learning_rate(hparams)
    if hparams.optimizer == "momentum":
        return optax.sgd(learning_rate, momentum=hparams.momentum)
    if hparams.optimizer == "rmsprop":
        return optax.rmsprop(
            learning_rate,
            decay=hparams.rmsprop_decay,
            momentum=hparams.momentum,
            eps=hparams.rmsprop_epsilon,
        )
    if hparams.optimizer == "adam":
        return optax.adam(
            learning_rate,
            b1=hparams.momentum,
            b2=hparams.adam_beta2,
            eps=hparams.adam_epsilon,
        )
    raise ValueError(
        f"Unknown optimizer {hparams.optimizer!r}; expected one of "
        "'momentum', 'rmsprop', 'adam'."
    )
