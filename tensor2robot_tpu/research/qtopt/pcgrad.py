"""PCGrad — gradient surgery for multi-task learning, JAX-native.

Behavioral reference: tensor2robot/research/qtopt/pcgrad.py:30-245 (a
tf.train.Optimizer wrapper). Semantics: given per-task losses, each task
gradient is projected off every *conflicting* task gradient (negative inner
product) before the per-task results are summed; variables can be opted in or
out of surgery via fnmatch allow/deny lists; non-surgery variables receive
the plain sum of task gradients (Yu et al., arXiv:2001.06782).

TPU-first shape: instead of wrapping an optimizer object, PCGrad here is a
pure function from per-task gradient pytrees to one combined gradient pytree
— composable with `jax.grad`, `jax.vmap` over tasks, `optax` descent rules,
and `pjit` sharding (the projections are elementwise + reductions, so XLA
all-reduces sharded inner products for free). The reference's two variants
are both kept: per-variable projection (memory-lean, `per_variable=True`)
and whole-model flattened projection.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from tensor2robot_tpu.utils.keypath import path_string

PyTree = Any

_EPS = 1e-5


def make_surgery_mask(
    params: PyTree,
    allowlist: Optional[Sequence[str]] = None,
    denylist: Optional[Sequence[str]] = None,
) -> PyTree:
    """Boolean pytree: True where PCGrad applies. A leaf participates when
    its '/'-joined path matches an allowlist wildcard and no denylist
    wildcard (reference _create_pcgrad_var_list :73-88)."""
    allow = list(allowlist) if allowlist is not None else ["*"]
    deny = list(denylist) if denylist is not None else []

    def decide(path, _leaf):
        name = path_string(path)
        return any(fnmatch.fnmatchcase(name, w) for w in allow) and not any(
            fnmatch.fnmatchcase(name, w) for w in deny
        )

    return jax.tree_util.tree_map_with_path(decide, params)


def _project_stacked(stacked: jax.Array) -> jax.Array:
    """Core surgery on stacked per-task grads [T, D]: every task gradient is
    projected off each conflicting task gradient, results summed -> [D]."""
    num_tasks = stacked.shape[0]
    sq_norms = jnp.sum(stacked * stacked, axis=-1)  # [T]

    def project_one(g):
        def body(k, g):
            inner = jnp.sum(g * stacked[k])
            coeff = jnp.minimum(inner / (sq_norms[k] + _EPS), 0.0)
            return g - coeff * stacked[k]

        return jax.lax.fori_loop(0, num_tasks, body, g)

    return jnp.sum(jax.vmap(project_one)(stacked), axis=0)


def project_task_gradients(
    task_grads: Sequence[PyTree],
    mask: Optional[PyTree] = None,
    per_variable: bool = True,
) -> PyTree:
    """Combines per-task gradient pytrees into one PCGrad gradient pytree.

    Args:
      task_grads: one gradient pytree per task (all same structure).
      mask: optional boolean pytree from `make_surgery_mask`; unmasked
        leaves get the plain task-sum (reference's non-pcgrad vars).
      per_variable: if True, inner products are computed per variable
        (reference _compute_projected_grads_per_variable :123-151);
        otherwise all masked leaves are flattened into one vector first
        (reference _compute_projected_grads :153-206).
    """
    if len(task_grads) == 1:
        return task_grads[0]
    stacked_tree = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *task_grads
    )
    summed = jax.tree_util.tree_map(
        lambda s: jnp.sum(s, axis=0), stacked_tree
    )
    if per_variable:
        projected = jax.tree_util.tree_map(
            lambda s: _project_stacked(s.reshape(s.shape[0], -1)).reshape(
                s.shape[1:]
            ),
            stacked_tree,
        )
    else:
        leaves, treedef = jax.tree_util.tree_flatten(stacked_tree)
        mask_leaves = (
            jax.tree_util.tree_leaves(mask) if mask is not None
            else [True] * len(leaves)
        )
        picked = [
            l.reshape(l.shape[0], -1)
            for l, m in zip(leaves, mask_leaves) if m
        ]
        if not picked:
            return summed
        flat = jnp.concatenate(picked, axis=1)
        proj = _project_stacked(flat)
        out_leaves, start = [], 0
        for leaf, m in zip(leaves, mask_leaves):
            if not m:
                out_leaves.append(jnp.sum(leaf, axis=0))
                continue
            size = int(jnp.size(leaf[0]))
            out_leaves.append(
                proj[start : start + size].reshape(leaf.shape[1:])
            )
            start += size
        return jax.tree_util.tree_unflatten(treedef, out_leaves)
    if mask is None:
        return projected
    return jax.tree_util.tree_map(
        lambda m, p, s: p if m else s, mask, projected, summed
    )


def pcgrad_gradients(
    task_loss_fns: Sequence[Callable[[PyTree], jax.Array]],
    params: PyTree,
    allowlist: Optional[Sequence[str]] = None,
    denylist: Optional[Sequence[str]] = None,
    per_variable: bool = True,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, PyTree]:
    """End-to-end helper: per-task `jax.grad`, optional task-order shuffle
    (the reference shuffles losses each apply, pcgrad.py:98), projection,
    combination. Returns (total_loss, combined_grads)."""
    losses_grads: List[Tuple[jax.Array, PyTree]] = [
        jax.value_and_grad(fn)(params) for fn in task_loss_fns
    ]
    losses = [lg[0] for lg in losses_grads]
    grads = [lg[1] for lg in losses_grads]
    if rng is not None and len(grads) > 1:
        # Permute task order (projection is order-dependent for >2 tasks);
        # traced gather keeps this jit-safe.
        perm = jax.random.permutation(rng, len(grads))
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves)[perm], *grads
        )
        grads = [
            jax.tree_util.tree_map(lambda s, i=i: s[i], stacked)
            for i in range(len(grads))
        ]
    mask = (
        make_surgery_mask(params, allowlist, denylist)
        if (allowlist is not None or denylist is not None)
        else None
    )
    combined = project_task_gradients(grads, mask, per_variable=per_variable)
    return jnp.sum(jnp.stack(losses)), combined
