"""QT-Opt T2R models: the Grasping44 critic family + its preprocessor.

Behavioral reference: tensor2robot/research/qtopt/t2r_models.py
(`LegacyGraspingModelWrapper` :60-238, `DefaultGrasping44ImagePreprocessor`
:241-307, `Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom`
:310-420). The wrapper adapts the Grasping44 Q-tower to the CriticModel
contract: split state/action specs, `q_predicted` output, log-loss against
`grasp_success` rewards, CEM action tiling in PREDICT, momentum optimizer
with staircase LR decay and EMA ("moving average + swapping saver") params.

TPU-first notes: the 512x640 jpeg decode stays on the host (data layer); the
crop + photometric distortion run *on device* inside the jitted step with
explicit rng so the infeed carries uint8; training math is bf16-friendly
via the trainer dtype policy; EMA params are a first-class part of
TrainState, exports select them (reference swapping-saver semantics).
"""

from __future__ import annotations

import abc
from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.models.abstract_model import MODE_PREDICT, MODE_TRAIN
from tensor2robot_tpu.models.base_models import CriticModel
from tensor2robot_tpu.preprocessors import image_transformations
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    SpecTransformationPreprocessor,
)
from tensor2robot_tpu.research.qtopt import optimizer_builder
from tensor2robot_tpu.research.qtopt.networks import (
    E2E_GRASP_PARAM_BLOCKS,
    Grasping44,
    concat_e2e_grasp_params,
)
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct

INPUT_SHAPE = (512, 640, 3)
TARGET_SHAPE = (472, 472)


class DefaultGrasping44ImagePreprocessor(SpecTransformationPreprocessor):
    """512x640x3 uint8 jpeg source -> 472x472 crop (random for train, center
    otherwise) -> float [0,1] -> train-only photometric distortion
    (reference t2r_models.py:241-307). For models configured with a smaller
    `image_size`, the source keeps the reference's crop slack (+40 rows,
    +168 cols).

    The crop is also published as a decode-time ROI (`get_decode_rois`):
    a ROI-enabled RecordDataset then decodes ONLY the crop window on the
    host (identical pixels — data/roi.py) and this preprocessor skips its
    own crop, keeping float conversion + distortion on device. Crop
    randomness moves with the crop: decode-time random offsets come from
    the dataset's seeded numpy RNG instead of this step's `rng` key."""

    def _target_shape(self):
        model_image = self._model.get_feature_specification(
            MODE_TRAIN
        )["state/image"]
        return tuple(model_image.shape[:2])

    def _source_shape(self):
        target = self._target_shape()
        return (target[0] + 40, target[1] + 168, 3)

    def _transform_in_feature_specification(self, spec, mode):
        self.update_spec(
            spec,
            "state/image",
            shape=self._source_shape(),
            dtype=np.uint8,
            data_format="jpeg",
        )
        return spec

    def get_decode_rois(self, mode):
        from tensor2robot_tpu.data.roi import DecodeROI

        th, tw = self._target_shape()
        return {
            "state/image": DecodeROI(
                th, tw, mode="random" if mode == MODE_TRAIN else "center"
            )
        }

    def _preprocess_fn(self, features, labels, mode, rng):
        image = features.state.image
        target_shape = self._target_shape()
        # Decode-time ROI (get_decode_rois) may have cropped already — the
        # image then arrives at the target shape and the crop here must
        # not re-crop. Static shape check, so jit traces the right branch.
        # NOTE: for pre-cropped inputs the crop offsets (random in train)
        # were drawn by the DATASET's seeded numpy RNG at decode time, so
        # the None-rng convention below governs only the photometric
        # distortion — a train batch from a ROI dataset is random-cropped
        # even when rng is None. Feed source-shaped images (or set
        # T2R_DECODE_ROI=0) where the deterministic center crop matters.
        cropped = tuple(image.shape[-3:-1]) == tuple(target_shape)
        # No rng = no stochastic augmentation (deterministic center crop
        # when cropping here), matching the framework-wide None-rng
        # convention; silently reusing a fixed key would repeat identical
        # distortions every batch.
        if mode == MODE_TRAIN and rng is not None:
            rng_crop, rng_distort = jax.random.split(rng)
            if not cropped:
                image = image_transformations.random_crop_image_batch(
                    rng_crop, image, target_shape
                )
            image = image.astype(jnp.float32) / 255.0
            image = image_transformations.apply_photometric_image_distortions(
                rng_distort, image
            )
        else:
            if not cropped:
                image = image_transformations.center_crop_image_batch(
                    image, target_shape
                )
            image = image.astype(jnp.float32) / 255.0
        features.state.image = image
        return features, labels


class _Grasping44Net(nn.Module):
    """Adapts the Grasping44 tower to the T2R network calling convention
    `__call__(packed_features, mode) -> outputs struct`."""

    grasp_param_blocks: Optional[Dict[str, Tuple[int, int]]] = None
    num_convs: Tuple[int, int, int] = (6, 6, 3)
    batch_norm_momentum: float = 0.9997
    width: int = 64

    @nn.compact
    def __call__(self, features, mode):
        action = {
            key: jnp.asarray(value) for key, value in features.action.items()
        }
        grasp_params = concat_e2e_grasp_params(action)
        logits, end_points = Grasping44(
            grasp_param_blocks=self.grasp_param_blocks,
            num_convs=self.num_convs,
            batch_norm_momentum=self.batch_norm_momentum,
            width=self.width,
            name="grasping44",
        )(
            features.state.image,
            grasp_params,
            is_training=mode == MODE_TRAIN,
        )
        # q_predicted carries logits (loss-stable); predictions carries the
        # sigmoid the reference exposed as q_predicted — CEM argmax is
        # invariant to the monotone map, training uses the logits.
        tiled = grasp_params.ndim == 3
        q_logits = (
            logits.reshape(end_points["predictions"].shape)
            if tiled
            else logits.reshape(-1)
        )
        return {
            "q_predicted": q_logits,
            "q_probability": end_points["predictions"],
        }


class GraspingModelWrapper(CriticModel):
    """CriticModel over the Grasping44 tower (reference
    LegacyGraspingModelWrapper :60-238). Momentum/rmsprop/adam optimizer
    with staircase exponential decay; EMA params when use_avg_model_params."""

    def __init__(
        self,
        learning_rate: float = 1e-4,
        model_weights_averaging: float = 0.9999,
        momentum: float = 0.9,
        export_batch_size: int = 1,
        use_avg_model_params: bool = True,
        learning_rate_decay_factor: float = 0.999,
        optimizer: str = "momentum",
        batch_size: int = 32,
        examples_per_epoch: int = 3_000_000,
        action_batch_size: Optional[int] = None,
        **kwargs,
    ):
        self.hparams = optimizer_builder.QtOptHParams(
            batch_size=batch_size,
            examples_per_epoch=examples_per_epoch,
            learning_rate=learning_rate,
            learning_rate_decay_factor=learning_rate_decay_factor,
            model_weights_averaging=model_weights_averaging,
            momentum=momentum,
            optimizer=optimizer,
            use_avg_model_params=use_avg_model_params,
        )
        self._export_batch_size = export_batch_size
        kwargs.setdefault(
            "preprocessor_cls", DefaultGrasping44ImagePreprocessor
        )
        super().__init__(
            action_batch_size=action_batch_size,
            create_optimizer_fn=lambda: optimizer_builder.build_opt(
                self.hparams
            ),
            use_avg_model_params=use_avg_model_params,
            avg_model_params_decay=model_weights_averaging,
            **kwargs,
        )

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        spec = TensorSpecStruct()
        spec["reward"] = ExtendedTensorSpec(
            shape=(1,), dtype=np.float32, name="grasp_success"
        )
        return spec


class Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
    GraspingModelWrapper
):
    """The e2e open/close/terminate/gripper-status/height-to-bottom critic
    (reference t2r_models.py:310-420): 472x472 image state + 10-dim action
    in 7 named blocks. `image_size` shrinks the state for debugging/dry
    runs (the Grasping44 tail needs >= ~220px)."""

    def __init__(
        self,
        image_size: Tuple[int, int] = (472, 472),
        num_convs: Tuple[int, int, int] = (6, 6, 3),
        batch_norm_momentum: float = 0.9997,
        width: int = 64,
        **kwargs,
    ):
        self._image_size = tuple(image_size)
        self._num_convs = tuple(num_convs)
        # Tower channel count: 64 is the reference; 128 is the round-5
        # MXU-alignment twin (networks.Grasping44.width).
        self._width = width
        # Reference batch_norm_decay=0.9997 (research/qtopt/networks.py:45
        # slim arg_scope); exposed because short trainings (tests, the AUC
        # bench) need running stats that adapt within a few hundred steps
        # to produce meaningful eval-mode inference.
        self._batch_norm_momentum = batch_norm_momentum
        super().__init__(**kwargs)

    def get_state_specification(self) -> TensorSpecStruct:
        return TensorSpecStruct(
            image=ExtendedTensorSpec(
                shape=self._image_size + (3,),
                dtype=np.float32,
                name="image_1",
            )
        )

    def get_action_specification(self) -> TensorSpecStruct:
        def action_spec(name, size=1):
            return ExtendedTensorSpec(
                shape=(size,), dtype=np.float32, name=name
            )

        return TensorSpecStruct(
            world_vector=action_spec("world_vector", 3),
            vertical_rotation=action_spec("vertical_rotation", 2),
            close_gripper=action_spec("close_gripper"),
            open_gripper=action_spec("open_gripper"),
            terminate_episode=action_spec("terminate_episode"),
            gripper_closed=action_spec("gripper_closed"),
            height_to_bottom=action_spec("height_to_bottom"),
        )

    def create_network(self) -> nn.Module:
        return _Grasping44Net(
            grasp_param_blocks=E2E_GRASP_PARAM_BLOCKS,
            num_convs=self._num_convs,
            batch_norm_momentum=self._batch_norm_momentum,
            width=self._width,
        )
