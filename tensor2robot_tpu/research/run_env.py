"""Episode runner: drives a gym-style env with a policy, writes transitions.

The collect/eval workhorse (reference research/dql_grasping_lib/run_env.py:
78-236): explore-probability schedule, episode -> transitions conversion,
replay-writer sink, per-episode reward accounting. Environments are any
object with `reset() -> obs` and `step(action) -> (obs, reward, done, info)`
(old-gym protocol; 5-tuple new-gym returns are also accepted).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu.utils import writer as writer_lib


@dataclasses.dataclass
class Transition:
    obs: Any
    action: np.ndarray
    reward: float
    new_obs: Any
    done: bool
    debug: Optional[dict] = None

    def __iter__(self):
        # Tuple-unpacking compatibility with the reference's
        # (obs, action, rew, new_obs, done, debug) episode tuples.
        return iter(
            (self.obs, self.action, self.reward, self.new_obs, self.done,
             self.debug)
        )


def episode_to_transitions_identity(episode: List[Transition]) -> List[Transition]:
    return episode


def _step_env(env, action) -> Tuple[Any, float, bool, dict]:
    result = env.step(action)
    if len(result) == 5:  # new-gym: obs, reward, terminated, truncated, info
        obs, reward, terminated, truncated, info = result
        return obs, float(reward), bool(terminated or truncated), info
    obs, reward, done, info = result
    return obs, float(reward), bool(done), info


class _TFAgentsEnvAdapter:
    """Adapts a TF-Agents-style environment (reset/step return TimeSteps
    with .observation/.reward/.is_last()) to the gym-tuple protocol the core
    loop drives (reference run_tfagents_env, run_env.py:106-130)."""

    def __init__(self, tfagents_env):
        self._env = tfagents_env

    def reset(self):
        timestep = self._env.reset()
        return timestep.observation

    def step(self, action):
        timestep = self._env.step(action)
        reward = timestep.reward
        return (
            timestep.observation,
            float(0.0 if reward is None else np.asarray(reward)),
            bool(timestep.is_last()),
            {},
        )

    def __getattr__(self, name):
        return getattr(self._env, name)


def run_tfagents_env(tfagents_env, policy, **kwargs) -> List[float]:
    """run_env over a TF-Agents-style environment (reference
    run_tfagents_env, research/dql_grasping_lib/run_env.py:106): same
    episode loop, TimeStep protocol adapted at the boundary."""
    return run_env(_TFAgentsEnvAdapter(tfagents_env), policy, **kwargs)


def run_env(
    env,
    policy,
    num_episodes: int = 1,
    max_episode_steps: Optional[int] = None,
    explore_schedule: Optional[Callable[[int], float]] = None,
    global_step: int = 0,
    episode_to_transitions_fn: Optional[Callable] = None,
    transition_to_record_fn: Optional[Callable] = None,
    replay_writer=None,
    replay_path: Optional[str] = None,
    output_dir: Optional[str] = None,
    on_episode_end: Optional[Callable[[int, List[Transition]], None]] = None,
) -> List[float]:
    """Runs episodes; returns per-episode total rewards
    (reference _run_env, run_env.py:133-236).

    Args:
      env: gym-style environment.
      policy: a policies.Policy (sample_action interface).
      num_episodes: episodes to run.
      max_episode_steps: per-episode step cap (None = env decides).
      explore_schedule: global_step -> explore probability fed to
        policy.sample_action (None = greedy).
      global_step: the learner step these episodes are attributed to.
      episode_to_transitions_fn: [Transition] -> transitions converter
        (n-step returns, reward relabeling, proto assembly, ...).
      transition_to_record_fn: transition -> serialized bytes for the
        replay writer. With a replay_writer, supply either this OR an
        episode_to_transitions_fn whose outputs are protos/bytes.
      replay_writer: utils.writer.ReplayWriter episode sink.
      replay_path: shard path prefix passed to replay_writer.open; derived
        from `output_dir` + global_step when omitted.
      on_episode_end: callback(episode_index, transitions).
    """
    explore_prob = (
        explore_schedule(global_step) if explore_schedule is not None else 0.0
    )
    if replay_writer is not None:
        if replay_path is None and output_dir is not None:
            replay_path = writer_lib.timestamped_record_path(
                output_dir, global_step
            )
        if replay_path is None:
            raise ValueError(
                "replay_writer requires replay_path or output_dir."
            )
        if transition_to_record_fn is None and episode_to_transitions_fn is None:
            raise ValueError(
                "replay_writer requires transition_to_record_fn or an "
                "episode_to_transitions_fn producing serializable protos."
            )
        replay_writer.open(replay_path)
    episode_rewards: List[float] = []
    try:
        for episode_index in range(num_episodes):
            obs = env.reset()
            if isinstance(obs, tuple) and len(obs) == 2:  # new-gym (obs, info)
                obs = obs[0]
            if hasattr(policy, "reset"):
                policy.reset()
            episode: List[Transition] = []
            total_reward, step, done = 0.0, 0, False
            while not done:
                action, _ = policy.sample_action(obs, explore_prob)
                new_obs, reward, done, env_debug = _step_env(env, action)
                episode.append(
                    Transition(obs, action, reward, new_obs, done, env_debug)
                )
                total_reward += reward
                obs = new_obs
                step += 1
                if max_episode_steps is not None and step >= max_episode_steps:
                    break
            transitions = (
                episode_to_transitions_fn(episode)
                if episode_to_transitions_fn is not None
                else episode
            )
            if replay_writer is not None:
                if transition_to_record_fn is not None:
                    records = [transition_to_record_fn(t) for t in transitions]
                else:
                    records = transitions
                replay_writer.write(
                    writer_lib.serialize_transition_records(records)
                )
            if on_episode_end is not None:
                on_episode_end(episode_index, transitions)
            episode_rewards.append(total_reward)
            logging.info(
                "episode %d/%d: reward=%.3f steps=%d explore=%.3f",
                episode_index + 1, num_episodes, total_reward, step, explore_prob,
            )
    finally:
        if replay_writer is not None:
            replay_writer.close()
    return episode_rewards
