from tensor2robot_tpu.research.vrgripper import episode_to_transitions
from tensor2robot_tpu.research.vrgripper.decoders import (
    DiscreteDecoder,
    MADE,
    MAFDecoder,
    MDNDecoder,
    MSEDecoder,
    get_discrete_action_loss,
    get_discrete_actions,
    get_discrete_bins,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_env_meta_models import (
    VRGripperEnvRegressionModelMAML,
    VRGripperEnvTecModel,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_env_models import (
    DefaultVRGripperPreprocessor,
    VRGripperDomainAdaptiveModel,
    VRGripperRegressionModel,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_env_wtl_models import (
    VRGripperEnvSimpleTrialModel,
    pack_wtl_meta_features,
)
