"""Action decoders for VRGripper behavioral cloning.

Behavioral references: tensor2robot/research/vrgripper/mse_decoder.py:26,
maf.py:68, discrete.py:31-138, plus layers/mdn.py for the MDN head.

Decoder contract (stateless, unlike the reference's cached `self._maf`):
`decoder(params, output_size, labels=None) -> (action, aux)` where `aux`
carries 'nll' (the decoder's negative log-likelihood / loss on `labels`)
when labels are provided — models surface it as an output tensor so
`model_train_fn` can consume it without re-entering the network.

The MAF decoder is a from-scratch masked autoregressive flow (MADE
conditioners) — there is no TFP on the TPU path. Density evaluation is the
single-pass direction (one MADE call per flow); sampling inverts the flow
autoregressively, unrolled over the (small) action dimension — all static
shapes, XLA-friendly.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import mdn as mdn_lib


class MSEDecoder(nn.Module):
    """Plain linear head + mean-squared-error loss (reference
    mse_decoder.py:26-36)."""

    @nn.compact
    def __call__(
        self,
        params: jax.Array,
        output_size: int,
        labels: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, dict]:
        action = nn.Dense(output_size, name="pose")(params)
        aux = {}
        if labels is not None:
            aux["nll"] = jnp.mean(jnp.square(action - labels))
        return action, aux


class MDNDecoder(nn.Module):
    """Gaussian-mixture head; action = approximate mode, loss = mixture NLL
    (reference layers/mdn.py MDNDecoder :128-167)."""

    num_mixture_components: int = 1
    condition_sigmas: bool = False

    @nn.compact
    def __call__(
        self,
        params: jax.Array,
        output_size: int,
        labels: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, dict]:
        dist_params = mdn_lib.MDNParams(
            num_alphas=self.num_mixture_components,
            sample_size=output_size,
            condition_sigmas=self.condition_sigmas,
        )(params)
        gm = mdn_lib.get_mixture_distribution(
            dist_params, self.num_mixture_components, output_size
        )
        aux = {"dist_params": dist_params}
        if labels is not None:
            aux["nll"] = mdn_lib.mdn_loss(gm, labels)
        return gm.approximate_mode(), aux


class MaskedDense(nn.Module):
    """Dense layer with a fixed binary connectivity mask (the MADE
    building block, Germain et al. arXiv:1502.03509)."""

    features: int
    mask: np.ndarray  # [in_features, features], 0/1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel",
            nn.initializers.glorot_uniform(),
            (x.shape[-1], self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        mask = jnp.asarray(self.mask, kernel.dtype)
        return x @ (kernel * mask) + bias


def _made_masks(
    event_size: int, hidden_layers: Sequence[int]
) -> Tuple[list, np.ndarray]:
    """Builds MADE degree masks: hidden unit degrees cycle 1..D-1; the
    output mask enforces strict autoregressive order (output i depends on
    inputs < i)."""
    degrees = [np.arange(1, event_size + 1)]
    for width in hidden_layers:
        degrees.append((np.arange(width) % max(1, event_size - 1)) + 1)
    masks = []
    for previous, current in zip(degrees[:-1], degrees[1:]):
        masks.append((previous[:, None] <= current[None, :]).astype(np.float32))
    out_mask = (degrees[-1][:, None] < degrees[0][None, :]).astype(np.float32)
    return masks, out_mask


class MADE(nn.Module):
    """Masked autoregressive conditioner: x -> (shift, log_scale), each
    output dim depending only on strictly-preceding input dims."""

    event_size: int
    hidden_layers: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        masks, out_mask = _made_masks(self.event_size, self.hidden_layers)
        net = x
        for i, (width, mask) in enumerate(zip(self.hidden_layers, masks)):
            net = MaskedDense(width, mask, name=f"masked{i}")(net)
            net = nn.relu(net)
        # Two heads off the shared trunk, both strictly autoregressive.
        double_mask = np.concatenate([out_mask, out_mask], axis=1)
        out = MaskedDense(
            2 * self.event_size, double_mask, name="masked_out"
        )(net)
        shift, log_scale = jnp.split(out, 2, axis=-1)
        # Bound the scale for stability (tanh soft clamp to [-5, 5]).
        log_scale = 5.0 * jnp.tanh(log_scale / 5.0)
        return shift, log_scale


class MAFDecoder(nn.Module):
    """Masked autoregressive flow over a conditioned isotropic base
    (reference maf.py:68-99): base = N(mu(params), 1), flows chained with
    fixed permutations between them. Loss = mean NLL of labels; the action
    output inverts the flow from the base mean (deterministic) or from a
    base sample when a 'sample' rng stream is available."""

    num_flows: int = 1
    hidden_layers: Sequence[int] = (64, 64)
    permutation_seed: int = 42

    def _permutations(self, event_size: int) -> list:
        rng = np.random.RandomState(self.permutation_seed)
        return [
            rng.permutation(event_size) for _ in range(self.num_flows - 1)
        ]

    def _flows(self, event_size: int) -> list:
        return [
            MADE(event_size, self.hidden_layers, name=f"made{i}")
            for i in range(self.num_flows)
        ]

    def _log_prob(self, flows, perms, x, mus):
        """Density direction: one MADE pass per flow (fast)."""
        event_size = x.shape[-1]
        log_det = jnp.zeros(x.shape[:-1])
        for i in reversed(range(self.num_flows)):
            shift, log_scale = flows[i](x)
            x = (x - shift) * jnp.exp(-log_scale)
            log_det = log_det - jnp.sum(log_scale, axis=-1)
            if i > 0:
                inverse_perm = np.argsort(perms[i - 1])
                x = x[..., inverse_perm]
        base_log_prob = -0.5 * jnp.sum(
            jnp.square(x - mus) + np.log(2.0 * np.pi), axis=-1
        )
        return base_log_prob + log_det

    def _forward(self, flows, perms, u):
        """Sampling direction: autoregressive inversion, unrolled over the
        event dim (small for actions)."""
        event_size = u.shape[-1]
        x = u
        for i in range(self.num_flows):
            if i > 0:
                x = x[..., perms[i - 1]]
            y = jnp.zeros_like(x)
            for d in range(event_size):
                shift, log_scale = flows[i](y)
                y = y.at[..., d].set(
                    x[..., d] * jnp.exp(log_scale[..., d]) + shift[..., d]
                )
            x = y
        return x

    @nn.compact
    def __call__(
        self,
        params: jax.Array,
        output_size: int,
        labels: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, dict]:
        if any(output_size > width for width in self.hidden_layers):
            raise ValueError(
                "MAF hidden layers have to be at least as wide as event size."
            )
        mus = nn.Dense(output_size, name="maf_mus")(params)
        flows = self._flows(output_size)
        perms = self._permutations(output_size)

        if self.has_rng("sample"):
            base = mus + jax.random.normal(
                self.make_rng("sample"), mus.shape, mus.dtype
            )
        else:
            base = mus
        action = self._forward(flows, perms, base)

        aux = {}
        if labels is not None:
            aux["nll"] = -jnp.mean(self._log_prob(flows, perms, labels, mus))
        return action, aux


def get_discrete_bins(
    num_bins: int, output_min: np.ndarray, output_max: np.ndarray
) -> np.ndarray:
    """Bin centers discretizing [output_min, output_max] per action dim ->
    [num_bins, action_dim] (reference discrete.py:31-47)."""
    action_range = np.asarray(output_max) - np.asarray(output_min)
    bin_sizes = action_range / float(num_bins)
    return np.array(
        [
            np.asarray(output_min) + bin_sizes * (bin_i + 0.5)
            for bin_i in range(num_bins)
        ]
    )


def get_discrete_actions(
    logits: jax.Array,
    action_size: int,
    num_bins: int,
    bin_centers: np.ndarray,
) -> jax.Array:
    """Mode of the per-dim categorical -> continuous bin-center actions
    (reference discrete.py:50-78)."""
    probabilities = jax.nn.softmax(
        logits.reshape(-1, action_size, num_bins), axis=-1
    )
    one_hot = jax.nn.one_hot(jnp.argmax(probabilities, axis=-1), num_bins)
    centers = jnp.asarray(bin_centers.T, jnp.float32)  # [action, bins]
    actions = jnp.sum(one_hot * centers, axis=-1)
    return actions.reshape(logits.shape[:-1] + (action_size,))


def get_discrete_action_loss(
    logits: jax.Array,
    action_labels: jax.Array,
    bin_centers: np.ndarray,
    num_bins: int,
) -> jax.Array:
    """Nearest-bin one-hot labels -> softmax cross entropy
    (reference discrete.py:81-110)."""
    centers = jnp.asarray(bin_centers, jnp.float32)  # [bins, action]
    distance = jnp.square(
        action_labels[..., None, :] - centers
    )  # [..., bins, action]
    discrete_labels = jnp.argmin(distance, axis=-2)  # [..., action]
    one_hot = jax.nn.one_hot(discrete_labels, num_bins).reshape(-1, num_bins)
    flat_logits = logits.reshape(-1, num_bins)
    log_probs = jax.nn.log_softmax(flat_logits, axis=-1)
    return -jnp.mean(jnp.sum(one_hot * log_probs, axis=-1))


class DiscreteDecoder(nn.Module):
    """Per-dim categorical head over discretized action bins
    (reference discrete.py:108-138)."""

    num_bins: int = 11
    action_low: float = -1.0
    action_high: float = 1.0

    @nn.compact
    def __call__(
        self,
        params: jax.Array,
        output_size: int,
        labels: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, dict]:
        logits = nn.Dense(output_size * self.num_bins, name="bin_logits")(
            params
        )
        bin_centers = get_discrete_bins(
            self.num_bins,
            np.full((output_size,), self.action_low),
            np.full((output_size,), self.action_high),
        )
        action = get_discrete_actions(
            logits, output_size, self.num_bins, bin_centers
        )
        aux = {"bin_logits": logits}
        if labels is not None:
            aux["nll"] = get_discrete_action_loss(
                logits.reshape(labels.shape[:-1] + (output_size * self.num_bins,)),
                labels,
                bin_centers,
                self.num_bins,
            )
        return action, aux
