"""Episode -> transition-proto converters for replay writing.

Behavioral reference:
tensor2robot/research/vrgripper/episode_to_transitions.py:41-132.
Transitions are (obs, action, reward, next_obs, done, debug) tuples; the
converters emit Example / SequenceExample protos in the layouts the
corresponding input pipelines parse.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.proto import example_pb2


def _float_feature(feature, values) -> None:
    feature.float_list.value.extend(
        np.asarray(values, np.float32).reshape(-1).tolist()
    )


def _int64_feature(feature, values) -> None:
    feature.int64_list.value.extend(
        np.asarray(values, np.int64).reshape(-1).tolist()
    )


@configurable("make_fixed_length")
def make_fixed_length(
    input_list: Sequence,
    fixed_length: int,
    always_include_endpoints: bool = True,
    randomized: bool = True,
    rng: Optional[np.random.RandomState] = None,
) -> Optional[List]:
    """Fixed-length subsample of a list; keeps endpoints by default
    (reference make_fixed_length :41-80). Returns None for lists of
    length <= 2, like the reference."""
    original_length = len(input_list)
    if original_length <= 2:
        return None
    if not randomized:
        indices = np.sort(np.mod(np.arange(fixed_length), original_length))
        return [input_list[i] for i in indices]
    rng = rng or np.random
    if always_include_endpoints:
        endpoint_indices = np.array([0, original_length - 1])
        other_indices = 1 + rng.choice(
            original_length - 2, fixed_length - 2, replace=True
        )
        indices = np.concatenate((endpoint_indices, other_indices), axis=0)
    else:
        indices = rng.choice(original_length, fixed_length, replace=True)
    indices = np.sort(indices)
    return [input_list[i] for i in indices]


@configurable("episode_to_transitions_reacher")
def episode_to_transitions_reacher(episode_data, is_demo: bool = False):
    """One Example per transition: pose_t/pose_tp1/action/reward/done/is_demo
    (reference :84-103)."""
    transitions = []
    for transition in episode_data:
        obs_t, action, reward, obs_tp1, done, _ = transition
        example = example_pb2.Example()
        feature = example.features.feature
        _float_feature(feature["pose_t"], obs_t)
        _float_feature(feature["pose_tp1"], obs_tp1)
        _float_feature(feature["action"], action)
        _float_feature(feature["reward"], [reward])
        _int64_feature(feature["done"], [int(done)])
        _int64_feature(feature["is_demo"], [int(is_demo)])
        transitions.append(example)
    return transitions


@configurable("episode_to_transitions_metareacher")
def episode_to_transitions_metareacher(episode_data):
    """One SequenceExample per episode: is_demo/target_idx context +
    per-step feature lists (reference :106-132)."""
    example = example_pb2.SequenceExample()
    context = example.context.feature
    _int64_feature(
        context["is_demo"], [int(episode_data[0][-1]["is_demo"])]
    )
    _int64_feature(
        context["target_idx"], [episode_data[0][-1]["target_idx"]]
    )
    lists = example.feature_lists.feature_list
    for transition in episode_data:
        obs_t, action, reward, obs_tp1, done, _ = transition
        _float_feature(lists["pose_t"].feature.add(), obs_t)
        _float_feature(lists["pose_tp1"].feature.add(), obs_tp1)
        _float_feature(lists["action"].feature.add(), action)
        _float_feature(lists["reward"].feature.add(), [reward])
        _int64_feature(lists["done"].feature.add(), [int(done)])
    return [example]
