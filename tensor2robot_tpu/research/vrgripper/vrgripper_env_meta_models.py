"""VRGripper meta-learning models: MAML variant and Task-Embedded Control.

Behavioral reference:
tensor2robot/research/vrgripper/vrgripper_env_meta_models.py
(`VRGripperEnvRegressionModelMAML` :118-134, `VRGripperEnvTecModel`
:138-415). TEC (arXiv:1810.03237): embed the condition episode(s) into a
task vector, concatenate it (tiled over time) with per-step state features,
decode actions with a pluggable density head; train with BC NLL + optional
contrastive embedding loss between condition and inference embeddings.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Type

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import tec as tec_lib
from tensor2robot_tpu.layers.vision_layers import (
    FilmParams,
    ImageFeaturesToPoseNet,
    ImagesToFeaturesNet,
)
from tensor2robot_tpu.meta_learning import meta_tfdata, preprocessors
from tensor2robot_tpu.meta_learning.maml_model import MAMLModel
from tensor2robot_tpu.models.abstract_model import (
    MODE_PREDICT,
    MODE_TRAIN,
    FlaxT2RModel,
)
from tensor2robot_tpu.research.vrgripper import decoders
from tensor2robot_tpu.research.vrgripper.vrgripper_env_models import (
    DefaultVRGripperPreprocessor,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    copy_tensorspec,
)


class VRGripperEnvRegressionModelMAML(MAMLModel):
    """MAML-wrapped VRGripperRegressionModel (reference :118-134)."""

    def _select_inference_output(self, predictions: TensorSpecStruct):
        predictions["condition_output"] = predictions[
            "full_condition_output/inference_output"
        ]
        predictions["inference_output"] = predictions[
            "full_inference_output/inference_output"
        ]
        return predictions


class _TecNet(nn.Module):
    """TEC forward (reference VRGripperEnvTecModel.inference_network_fn
    :245-311). Features are meta-shaped: condition/inference subtrees with
    [B, num_episodes, T, ...] leaves."""

    action_size: int
    num_waypoints: int
    episode_length: int
    fc_embed_size: int
    ignore_embedding: bool
    use_film: bool
    predict_end_weight: float
    action_decoder: Callable[[], nn.Module]

    @staticmethod
    def _embed_episode(embedder, reducer, episode_features, train: bool):
        """[B, E, T, H, W, C] images -> l2-normalized [B, E, embed]
        (reference _embed_episode :235-245). `embedder`/`reducer` are
        created once by the caller so condition and inference episodes
        share weights (the reference's AUTO_REUSE scopes)."""
        image = episode_features["features/image"]
        image_embedding = meta_tfdata.multi_batch_apply(
            lambda im: embedder(im, train), 3, image
        )
        embedding = meta_tfdata.multi_batch_apply(reducer, 2, image_embedding)
        return embedding / jnp.maximum(
            jnp.linalg.norm(embedding, axis=-1, keepdims=True), 1e-12
        )

    @nn.compact
    def __call__(self, features, mode, labels=None):
        train = mode == MODE_TRAIN
        embedder = tec_lib.EmbedConditionImages(name="image_embedding")
        reducer = tec_lib.ReduceTemporalEmbeddings(
            self.fc_embed_size,
            # Static kernel from the episode-length config (checkpoint-safe);
            # reference fixes 10 for T=40 episodes.
            conv1d_kernel=min(10, self.episode_length),
            name="fc_reduce",
        )
        condition_embedding = self._embed_episode(
            embedder, reducer, features.condition, train
        )
        gripper_pose = features.inference.features["gripper_pose"]
        num_inference_episodes = gripper_pose.shape[1]
        # Reduce the condition episodes to ONE task embedding (mean over the
        # episode axis), then broadcast it across inference episodes and
        # time — supports num_condition_samples_per_task != num inference
        # episodes; the per-episode embeddings still feed the contrastive
        # loss untouched.
        task_embedding = jnp.mean(condition_embedding, axis=1, keepdims=True)

        film_params = None
        if self.use_film:
            film_generator = FilmParams(
                film_output_size=2 * 5 * 32, name="film_params"
            )
            film_params = meta_tfdata.multi_batch_apply(
                film_generator, 2, task_embedding
            )
            # Stretch to [B, E_inf, T, film]: identical across episodes/time.
            film_params = jnp.tile(
                film_params[:, :, None, :],
                (1, num_inference_episodes, self.episode_length, 1),
            )

        fc_embedding = jnp.tile(
            task_embedding[..., : self.fc_embed_size][:, :, None, :],
            (1, num_inference_episodes, self.episode_length, 1),
        )
        tower = ImagesToFeaturesNet(
            normalizer="layer_norm", name="state_features"
        )
        if film_params is not None:
            state_features, _ = meta_tfdata.multi_batch_apply(
                lambda im, fp: tower(im, train, film_output_params=fp),
                3,
                features.inference.features["image"],
                film_params,
            )
        else:
            state_features, _ = meta_tfdata.multi_batch_apply(
                lambda im: tower(im, train),
                3,
                features.inference.features["image"],
            )
        if self.ignore_embedding:
            fc_inputs = jnp.concatenate([state_features, gripper_pose], -1)
        else:
            fc_inputs = jnp.concatenate(
                [state_features, gripper_pose, fc_embedding], -1
            )

        outputs = TensorSpecStruct()
        aux_output_dim = 1 if self.predict_end_weight > 0 else 0
        action_params, end_token = meta_tfdata.multi_batch_apply(
            lambda x: ImageFeaturesToPoseNet(
                num_outputs=None,
                aux_output_dim=aux_output_dim,
                name="a_func",
            )(x),
            3,
            fc_inputs,
        )
        action_labels = None
        if labels is not None and "action" in labels.keys():
            action_labels = labels["action"]
        action, decoder_aux = self.action_decoder(
            action_params,
            self.num_waypoints * self.action_size,
            labels=action_labels,
        )

        outputs["inference_output"] = action
        outputs["condition_embedding"] = condition_embedding
        for key, value in decoder_aux.items():
            outputs[f"decoder/{key}"] = value

        if self.predict_end_weight > 0:
            outputs["end_token_logits"] = end_token
            outputs["end_token"] = jax.nn.sigmoid(end_token)
            outputs["inference_output"] = jnp.concatenate(
                [outputs["inference_output"], outputs["end_token"]], -1
            )

        if mode != MODE_PREDICT:
            outputs["inference_embedding"] = self._embed_episode(
                embedder, reducer, features.inference, train
            )
        return outputs


class VRGripperEnvTecModel(FlaxT2RModel):
    """Task-Embedded Control Network (reference :138-415)."""

    _NETWORK_TAKES_LABELS = True

    def __init__(
        self,
        action_size: int = 7,
        gripper_pose_size: int = 14,
        num_waypoints: int = 1,
        episode_length: int = 40,
        embed_loss_weight: float = 0.0,
        fc_embed_size: int = 32,
        ignore_embedding: bool = False,
        action_decoder_cls: Type[nn.Module] = decoders.MDNDecoder,
        predict_end_weight: float = 0.0,
        use_film: bool = False,
        num_condition_samples_per_task: int = 1,
        image_size: Tuple[int, int] = (100, 100),
        **kwargs,
    ):
        kwargs.setdefault("preprocessor_cls", None)
        super().__init__(**kwargs)
        self._action_size = action_size
        self._gripper_pose_size = gripper_pose_size
        self._num_waypoints = num_waypoints
        self._episode_length = episode_length
        self._embed_loss_weight = embed_loss_weight
        self._fc_embed_size = fc_embed_size
        self._ignore_embedding = ignore_embedding
        self._action_decoder_cls = action_decoder_cls
        self._predict_end_weight = predict_end_weight
        self._use_film = use_film
        self._num_condition_samples_per_task = num_condition_samples_per_task
        self._image_size = tuple(image_size)

    def _episode_feature_specification(self, mode: str) -> TensorSpecStruct:
        """Per-episode feature spec (reference :86-100)."""
        del mode
        spec = TensorSpecStruct(
            image=ExtendedTensorSpec(
                shape=self._image_size + (3,),
                dtype=np.float32,
                name="image0",
                data_format="jpeg",
            ),
            gripper_pose=ExtendedTensorSpec(
                shape=(self._gripper_pose_size,),
                dtype=np.float32,
                name="world_pose_gripper",
            ),
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    def _episode_label_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            action=ExtendedTensorSpec(
                shape=(self._action_size,),
                dtype=np.float32,
                name="action_world",
            )
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    @property
    def preprocessor(self):
        base = DefaultVRGripperPreprocessor(
            _EpisodeSpecAdapter(self)
        )
        return preprocessors.FixedLenMetaExamplePreprocessor(
            base_preprocessor=base,
            num_condition_samples_per_task=(
                self._num_condition_samples_per_task
            ),
        )

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        return preprocessors.create_maml_feature_spec(
            self._episode_feature_specification(mode),
            self._episode_label_specification(mode),
        )

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        return preprocessors.create_maml_label_spec(
            self._episode_label_specification(mode)
        )

    def create_network(self) -> nn.Module:
        return _TecNet(
            action_size=self._action_size,
            num_waypoints=self._num_waypoints,
            episode_length=self._episode_length,
            fc_embed_size=self._fc_embed_size,
            ignore_embedding=self._ignore_embedding,
            use_film=self._use_film,
            predict_end_weight=self._predict_end_weight,
            action_decoder=self._action_decoder_cls(),
        )

    def model_train_fn(self, features, labels, inference_outputs, mode):
        """BC NLL + optional end-token loss + optional contrastive embedding
        loss (reference model_train_fn :330-376)."""
        bc_loss = inference_outputs["decoder/nll"]
        metrics = {"loss/bc_nll": bc_loss}
        loss = bc_loss

        if self._predict_end_weight > 0:
            logits = inference_outputs["end_token_logits"]
            # Last two steps are end states (reference _compute_end_loss).
            end_labels = jnp.concatenate(
                [
                    jnp.zeros_like(logits[:, :, :-2, :]),
                    jnp.ones_like(logits[:, :, -2:, :]),
                ],
                axis=2,
            )
            import optax

            end_loss = jnp.mean(
                optax.sigmoid_binary_cross_entropy(logits, end_labels)
            )
            metrics["loss/end_token"] = end_loss
            loss = loss + self._predict_end_weight * end_loss

        if self._embed_loss_weight > 0:
            embed_loss = tec_lib.compute_embedding_contrastive_loss(
                inference_outputs["inference_embedding"],
                inference_outputs["condition_embedding"],
            )
            metrics["loss/embed"] = embed_loss
            loss = loss + self._embed_loss_weight * embed_loss
        metrics["loss/total"] = loss
        return loss, metrics


class _EpisodeSpecAdapter:
    """Presents a TEC model's per-episode specs as a model contract for the
    base preprocessor (the reference passed spec fns directly,
    :190-199)."""

    def __init__(self, model: VRGripperEnvTecModel):
        self._model = model

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        return self._model._episode_feature_specification(mode)

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        return self._model._episode_label_specification(mode)
