"""VRGripper behavioral-cloning models (Watch-Try-Learn lineage).

Behavioral reference: tensor2robot/research/vrgripper/vrgripper_env_models.py
(`DefaultVRGripperPreprocessor` :40-135, `VRGripperRegressionModel` :139-324,
`VRGripperDomainAdaptiveModel` :326-442). Episode-batched BC: every feature
carries an explicit [episode_length] dim inside the per-example spec, so
batches are [B, T, ...]; image towers run over the merged [B*T] batch
(meta_tfdata.multi_batch_apply) — one large MXU-friendly conv batch instead
of a scan over time.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import mdn as mdn_lib
from tensor2robot_tpu.layers.vision_layers import (
    ImageFeaturesToPoseNet,
    ImagesToFeaturesNet,
)
from tensor2robot_tpu.meta_learning import meta_tfdata
from tensor2robot_tpu.models.abstract_model import (
    MODE_PREDICT,
    MODE_TRAIN,
    FlaxT2RModel,
)
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.preprocessors import image_transformations
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    copy_tensorspec,
    flatten_spec_structure,
)

FLOAT_DTYPES = (jnp.bfloat16, jnp.float32, jnp.float64)


class DefaultVRGripperPreprocessor(AbstractPreprocessor):
    """Crop/resize/convert uint8 episode images; optional Mixup
    (reference :40-135).

    The on-disk image is `src_img_res` uint8; preprocessing takes a
    `crop_size` crop (random at train, center otherwise), converts to
    float [0, 1], and resizes to the model's declared image shape. With
    `mixup_alpha > 0`, features and labels are Mixup-blended along the
    batch dim at train time.
    """

    def __init__(
        self,
        model_spec_provider,
        src_img_res: Tuple[int, int] = (220, 300),
        crop_size: Tuple[int, int] = (200, 280),
        mixup_alpha: float = 0.0,
    ):
        super().__init__(model_spec_provider)
        self._src_img_res = tuple(src_img_res)
        self._crop_size = tuple(crop_size)
        self._mixup_alpha = mixup_alpha

    def get_in_feature_specification(self, mode) -> TensorSpecStruct:
        feature_spec = self._model.get_feature_specification(mode).copy()
        if mode != MODE_PREDICT and "original_image" in feature_spec.keys():
            del feature_spec["original_image"]
        if "image" in feature_spec.keys():
            true_shape = list(feature_spec["image"].shape)
            true_shape[-3:-1] = self._src_img_res
            feature_spec["image"] = ExtendedTensorSpec.from_spec(
                feature_spec["image"], shape=tuple(true_shape), dtype=np.uint8
            )
        return flatten_spec_structure(feature_spec)

    def get_in_label_specification(self, mode) -> TensorSpecStruct:
        return flatten_spec_structure(
            self._model.get_label_specification(mode)
        )

    def get_out_feature_specification(self, mode) -> TensorSpecStruct:
        return flatten_spec_structure(
            self._model.get_feature_specification(mode)
        )

    def get_out_label_specification(self, mode) -> TensorSpecStruct:
        return flatten_spec_structure(
            self._model.get_label_specification(mode)
        )

    def _preprocess_fn(self, features, labels, mode, rng):
        if "image" in features.keys():
            image = features["image"]
            leading = image.shape[:-3]  # [B] or [B, T]
            flat = image.reshape((-1,) + image.shape[-3:])
            if mode == MODE_TRAIN and rng is not None:
                rng, rng_crop = jax.random.split(rng)
                flat = image_transformations.random_crop_image_batch(
                    rng_crop, flat, self._crop_size
                )
            else:
                flat = image_transformations.center_crop_image_batch(
                    flat, self._crop_size
                )
            flat = flat.astype(jnp.float32) / 255.0
            out_spec = self.get_out_feature_specification(mode)
            target_hw = tuple(out_spec["image"].shape[-3:-1])
            if target_hw != self._crop_size:
                flat = jax.image.resize(
                    flat,
                    (flat.shape[0],) + target_hw + (flat.shape[-1],),
                    method="bilinear",
                )
            features["original_image"] = features["image"]
            features["image"] = flat.reshape(
                leading + flat.shape[1:]
            )

        if (
            self._mixup_alpha > 0.0
            and labels is not None
            and mode == MODE_TRAIN
            and rng is not None
        ):
            # Beta(a, a) sample via two gammas.
            rng, rng_g1, rng_g2 = jax.random.split(rng, 3)
            g1 = jax.random.gamma(rng_g1, self._mixup_alpha)
            g2 = jax.random.gamma(rng_g2, self._mixup_alpha)
            lmbda = g1 / (g1 + g2)

            def mix(struct):
                for key, x in struct.items():
                    if hasattr(x, "dtype") and x.dtype in FLOAT_DTYPES:
                        struct[key] = lmbda * x + (1 - lmbda) * jnp.flip(
                            x, axis=0
                        )

            mix(features)
            mix(labels)
        return features, labels


class _VRGripperRegressionNet(nn.Module):
    """State -> action over [B, T] batches (reference _single_batch_a_func
    :229-270 under multi_batch_apply :272-307)."""

    action_size: int
    use_gripper_input: bool
    num_mixture_components: int
    condition_mixture_stddev: bool
    output_mixture_sample: bool
    normalize_outputs: bool
    output_mean: Optional[np.ndarray]
    output_stddev: Optional[np.ndarray]

    @nn.compact
    def __call__(self, features, mode, labels=None):
        train = mode == MODE_TRAIN

        def single_batch(image, gripper_pose, action_label):
            feature_points, end_points = ImagesToFeaturesNet(
                normalizer="layer_norm", name="state_features"
            )(image, train)
            if self.use_gripper_input:
                fc_input = jnp.concatenate(
                    [feature_points, gripper_pose], axis=-1
                )
            else:
                fc_input = feature_points
            outputs = {}
            if self.num_mixture_components > 1:
                dist_params = mdn_lib.MDNParams(
                    num_alphas=self.num_mixture_components,
                    sample_size=self.action_size,
                    condition_sigmas=self.condition_mixture_stddev,
                    name="mdn",
                )(fc_input)
                gm = mdn_lib.get_mixture_distribution(
                    dist_params,
                    self.num_mixture_components,
                    self.action_size,
                    jnp.asarray(self.output_mean)
                    if (self.normalize_outputs and self.output_mean is not None)
                    else None,
                )
                if self.output_mixture_sample and self.has_rng("sample"):
                    action = gm.sample(self.make_rng("sample"))
                else:
                    action = gm.approximate_mode()
                outputs["dist_params"] = dist_params
                if action_label is not None:
                    outputs["nll"] = mdn_lib.mdn_loss(gm, action_label)
            else:
                action, _ = ImageFeaturesToPoseNet(
                    num_outputs=self.action_size, name="pose_net"
                )(fc_input)
                if self.output_mean is not None:
                    action = (
                        jnp.asarray(self.output_mean)
                        + jnp.asarray(self.output_stddev) * action
                    )
            outputs.update(
                {
                    "inference_output": action,
                    "feature_points": feature_points,
                    "softmax": end_points.get("softmax"),
                }
            )
            return outputs

        action_label = labels["action"] if labels is not None else None
        # Merge [B, T] into one conv megabatch (reference a_func's
        # multi_batch_apply over 2 batch dims).
        outputs = meta_tfdata.multi_batch_apply(
            single_batch,
            2,
            features["image"],
            features["gripper_pose"],
            action_label,
        )
        out = TensorSpecStruct()
        for key, value in outputs.items():
            if value is not None:
                out[key] = value
        return out


class VRGripperRegressionModel(FlaxT2RModel):
    """Continuous-action BC regression for the VRGripper env
    (reference :139-324)."""

    _NETWORK_TAKES_LABELS = True

    def __init__(
        self,
        action_size: int = 7,
        use_gripper_input: bool = True,
        normalize_outputs: bool = False,
        output_mean: Optional[Sequence[float]] = None,
        output_stddev: Optional[Sequence[float]] = None,
        outer_loss_multiplier: float = 1.0,
        num_mixture_components: int = 1,
        output_mixture_sample: bool = False,
        condition_mixture_stddev: bool = False,
        episode_length: int = 40,
        image_size: Tuple[int, int] = (100, 100),
        **kwargs,
    ):
        kwargs.setdefault("preprocessor_cls", DefaultVRGripperPreprocessor)
        super().__init__(**kwargs)
        self._action_size = action_size
        self._use_gripper_input = use_gripper_input
        self._normalize_outputs = normalize_outputs
        self._outer_loss_multiplier = outer_loss_multiplier
        self._num_mixture_components = num_mixture_components
        self._output_mixture_sample = output_mixture_sample
        self._condition_mixture_stddev = condition_mixture_stddev
        self._episode_length = episode_length
        self._image_size = tuple(image_size)
        self._output_mean = None
        self._output_stddev = None
        if output_mean and output_stddev:
            if not len(output_mean) == len(output_stddev) == action_size:
                raise ValueError(
                    f"Output mean and stddev have lengths {len(output_mean)} "
                    f"and {len(output_stddev)}."
                )
            self._output_mean = np.array([output_mean], np.float32)
            self._output_stddev = np.array([output_stddev], np.float32)

    @property
    def action_size(self) -> int:
        return self._action_size

    @property
    def episode_length(self) -> int:
        return self._episode_length

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            image=ExtendedTensorSpec(
                shape=self._image_size + (3,),
                dtype=np.float32,
                name="image0",
                data_format="jpeg",
            ),
            gripper_pose=ExtendedTensorSpec(
                shape=(14,), dtype=np.float32, name="world_pose_gripper"
            ),
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            action=ExtendedTensorSpec(
                shape=(self._action_size,),
                dtype=np.float32,
                name="action_world",
            )
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    def create_network(self) -> nn.Module:
        return _VRGripperRegressionNet(
            action_size=self._action_size,
            use_gripper_input=self._use_gripper_input,
            num_mixture_components=self._num_mixture_components,
            condition_mixture_stddev=self._condition_mixture_stddev,
            output_mixture_sample=self._output_mixture_sample,
            normalize_outputs=self._normalize_outputs,
            output_mean=self._output_mean,
            output_stddev=self._output_stddev,
        )

    def model_train_fn(self, features, labels, inference_outputs, mode):
        if self._num_mixture_components > 1:
            loss = inference_outputs["nll"]
            return loss, {"loss/mdn_nll": loss}
        loss = self._outer_loss_multiplier * jnp.mean(
            jnp.square(
                inference_outputs["inference_output"] - labels["action"]
            )
        )
        return loss, {"loss/mse": loss}


class _DomainAdaptiveNet(nn.Module):
    """Video-only inner loop with a learned loss (reference
    VRGripperDomainAdaptiveModel :326-442). In the inner loop the gripper
    pose is withheld (zeros or predicted from image features)."""

    action_size: int
    predict_con_gripper_pose: bool
    output_mean: Optional[np.ndarray]
    output_stddev: Optional[np.ndarray]
    learned_loss_conv1d_layers: Optional[Tuple[int, ...]] = (10, 10, 6)

    @nn.compact
    def __call__(self, features, mode, labels=None, is_inner_loop=False):
        train = mode == MODE_TRAIN

        def single_batch(image, gripper_pose):
            feature_points, end_points = ImagesToFeaturesNet(
                normalizer="layer_norm", name="state_features"
            )(image, train)
            if is_inner_loop:
                if self.predict_con_gripper_pose:
                    out = nn.Dense(40, use_bias=False, name="pose_pred_fc")(
                        feature_points
                    )
                    out = nn.relu(nn.LayerNorm(name="pose_pred_ln")(out))
                    pose = nn.Dense(14, name="pose_pred_out")(out)
                else:
                    pose = jnp.zeros_like(gripper_pose)
            else:
                pose = gripper_pose
            action, _ = ImageFeaturesToPoseNet(
                num_outputs=self.action_size, name="pose_net"
            )(feature_points, aux_input=pose)
            if self.output_mean is not None:
                action = (
                    jnp.asarray(self.output_mean)
                    + jnp.asarray(self.output_stddev) * action
                )
            return {
                "inference_output": action,
                "feature_points": feature_points,
                "softmax": end_points.get("softmax"),
            }

        outputs = meta_tfdata.multi_batch_apply(
            single_batch, 2, features["image"], features["gripper_pose"]
        )

        # Learned loss head (reference model_train_fn :404-442): a conv1d
        # critic over [predicted_action, feature_points, inference_output].
        feature_points = outputs["feature_points"]
        predicted_action, _ = meta_tfdata.multi_batch_apply(
            lambda fp: ImageFeaturesToPoseNet(
                num_outputs=self.action_size, name="learned_loss_pose"
            )(fp),
            2,
            feature_points,
        )
        if self.learned_loss_conv1d_layers is None:
            learned_loss = jnp.mean(
                jnp.square(predicted_action - outputs["inference_output"])
            )
        else:
            net = jnp.concatenate(
                [
                    predicted_action,
                    feature_points,
                    outputs["inference_output"],
                ],
                axis=-1,
            )
            for i, num_filters in enumerate(
                self.learned_loss_conv1d_layers[:-1]
            ):
                net = nn.Conv(
                    num_filters, (10,), use_bias=False, padding="SAME",
                    name=f"ll_conv{i}",
                )(net)
                net = nn.relu(nn.LayerNorm(name=f"ll_ln{i}")(net))
            net = nn.Conv(
                self.learned_loss_conv1d_layers[-1], (1,), name="ll_conv_out"
            )(net)
            learned_loss = jnp.mean(jnp.sum(jnp.square(net), axis=(1, 2)))
        outputs["learned_loss"] = learned_loss

        out = TensorSpecStruct()
        for key, value in outputs.items():
            if value is not None:
                out[key] = value
        return out


class VRGripperDomainAdaptiveModel(VRGripperRegressionModel):
    """Domain-adaptive imitation with a learned inner loss
    (reference :326-442). Intended as the base model of a MAMLModel: the
    inner loop minimizes the learned loss (no labels needed — adapts from
    video alone); the outer loop behavior-clones."""

    def __init__(
        self,
        predict_con_gripper_pose: bool = False,
        learned_loss_conv1d_layers: Tuple[int, ...] = (10, 10, 6),
        **kwargs,
    ):
        super().__init__(**kwargs)
        self._predict_con_gripper_pose = predict_con_gripper_pose
        self._learned_loss_conv1d_layers = learned_loss_conv1d_layers
        self._is_inner_loop = False

    def create_network(self) -> nn.Module:
        return _DomainAdaptiveNet(
            action_size=self._action_size,
            predict_con_gripper_pose=self._predict_con_gripper_pose,
            output_mean=self._output_mean,
            output_stddev=self._output_stddev,
            learned_loss_conv1d_layers=self._learned_loss_conv1d_layers,
        )

    def inner_inference_network_fn(
        self, variables, features, mode, rng=None, labels=None
    ):
        """Inner-loop forward: gripper pose withheld (zeros or predicted
        from image features) — adaptation from video alone (reference
        single_batch_a_func's is_inner_loop branch :359-368)."""
        outputs = self.network.apply(
            variables, features, mode, labels, is_inner_loop=True
        )
        return outputs, {}

    def model_inner_loop_fn(self, features, labels, inference_outputs, mode):
        """Inner-loop adaptation signal: the learned loss (reference
        model_train_fn's non-outer branch :404-442)."""
        loss = inference_outputs["learned_loss"]
        return loss, {"loss/learned": loss}

    def model_train_fn(self, features, labels, inference_outputs, mode):
        """Outer loop: behavior cloning (reference :415-419)."""
        loss = self._outer_loss_multiplier * jnp.mean(
            jnp.square(
                inference_outputs["inference_output"] - labels["action"]
            )
        )
        return loss, {"loss/bc_mse": loss}
