"""Watch-Try-Learn trial/retrial models (arXiv:1906.03352).

Behavioral reference:
tensor2robot/research/vrgripper/vrgripper_env_wtl_models.py
(`pack_wtl_meta_features` :43-134, `VRGripperEnvSimpleTrialModel` :136-355).
The trial model conditions on a demo episode (and, for retrial, on a first
trial episode plus its success flag) via temporal embeddings of full-state
observations; the policy head maps [state, embedding(s)] to actions over
the fixed-length episode. Data arrives as MetaExamples.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import tec as tec_lib
from tensor2robot_tpu.layers.vision_layers import ImageFeaturesToPoseNet
from tensor2robot_tpu.layers import mdn as mdn_lib
from tensor2robot_tpu.meta_learning import meta_tfdata, preprocessors
from tensor2robot_tpu.models.abstract_model import MODE_TRAIN, FlaxT2RModel
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    NoOpPreprocessor,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    copy_tensorspec,
)


def pack_wtl_meta_features(
    state: np.ndarray,
    prev_episode_data,
    timestep: int,
    episode_length: int,
    num_condition_samples_per_task: int,
    action_size: int = 7,
) -> dict:
    """Packs a live observation + conditioning episodes into the trial
    model's meta feature layout (reference pack_wtl_meta_features :43-134).

    Returns flat numpy features with [1, num_episodes, T, ...] dims.
    """
    obs_size = np.asarray(state).shape[-1]

    def episode_to_array(episode_data):
        observations = [np.asarray(t[0]) for t in episode_data]
        while len(observations) < episode_length:
            observations.append(observations[-1])
        return np.stack(observations[:episode_length], axis=0)

    condition = []
    success = []
    for episode_data in (prev_episode_data or [])[
        :num_condition_samples_per_task
    ]:
        condition.append(episode_to_array(episode_data))
        episode_reward = float(
            np.sum([t[2] for t in episode_data])
        )
        success.append(
            np.full((episode_length, 1), float(episode_reward > 0), np.float32)
        )
    while len(condition) < num_condition_samples_per_task:
        condition.append(np.zeros((episode_length, obs_size), np.float32))
        success.append(np.zeros((episode_length, 1), np.float32))

    inference = np.tile(
        np.asarray(state, np.float32)[None, :], (episode_length, 1)
    )
    return {
        "condition/features/full_state_pose": np.stack(condition)[None, ...],
        "condition/labels/action": np.zeros(
            (1, num_condition_samples_per_task, episode_length, action_size),
            np.float32,
        ),
        "condition/labels/success": np.stack(success)[None, ...],
        "inference/features/full_state_pose": inference[None, None, ...],
    }


class _WtlTrialNet(nn.Module):
    """Trial/retrial policy head (reference inference_network_fn
    :213-291)."""

    action_size: int
    episode_length: int
    fc_embed_size: int
    ignore_embedding: bool
    num_mixture_components: int
    retrial: bool
    embed_type: str  # 'temporal' | 'mean'

    @nn.compact
    def __call__(self, features, mode, labels=None):
        inf_pose = features.inference.features["full_state_pose"]
        con_pose = features.condition.features["full_state_pose"]
        # Map success labels [0, 1] -> [-1, 1].
        con_success = 2.0 * features.condition.labels["success"] - 1.0

        conv1d_kernel = min(10, self.episode_length)
        if self.embed_type == "temporal":
            fc_embedding = meta_tfdata.multi_batch_apply(
                tec_lib.ReduceTemporalEmbeddings(
                    self.fc_embed_size,
                    conv1d_kernel=conv1d_kernel,
                    name="demo_embedding",
                ),
                2,
                con_pose[:, 0:1, :, :],
            )[:, :, None, :]
        elif self.embed_type == "mean":
            fc_embedding = con_pose[:, 0:1, -1:, :]
        else:
            raise ValueError(f"Invalid embed_type: {self.embed_type}.")
        fc_embedding = jnp.tile(
            fc_embedding, (1, 1, self.episode_length, 1)
        )

        if self.retrial:
            # Condition episode 1 is the first trial; embed it with its
            # success channel (reference :240-258).
            con_input = jnp.concatenate(
                [
                    con_pose[:, 1:2, :, :],
                    con_success[:, 1:2, :, :],
                    fc_embedding,
                ],
                axis=-1,
            )
            if self.embed_type == "mean":
                trial_embedding = meta_tfdata.multi_batch_apply(
                    tec_lib.EmbedFullstate(
                        self.fc_embed_size, name="trial_embedding"
                    ),
                    3,
                    con_input,
                )
                trial_embedding = jnp.mean(trial_embedding, axis=-2)
            else:
                trial_embedding = meta_tfdata.multi_batch_apply(
                    tec_lib.ReduceTemporalEmbeddings(
                        self.fc_embed_size,
                        conv1d_kernel=conv1d_kernel,
                        name="trial_embedding",
                    ),
                    2,
                    con_input,
                )
            trial_embedding = jnp.tile(
                trial_embedding[:, :, None, :],
                (1, 1, self.episode_length, 1),
            )
            fc_embedding = jnp.concatenate(
                [fc_embedding, trial_embedding], axis=-1
            )

        if self.ignore_embedding:
            fc_inputs = inf_pose
        else:
            pieces = [inf_pose, fc_embedding]
            if self.retrial:
                pieces.append(con_success[:, 1:2, :, :])
            fc_inputs = jnp.concatenate(pieces, axis=-1)

        outputs = TensorSpecStruct()
        action_labels = None
        if labels is not None and "action" in labels.keys():
            action_labels = labels["action"]
        if self.num_mixture_components > 1:
            hidden, _ = meta_tfdata.multi_batch_apply(
                lambda x: ImageFeaturesToPoseNet(
                    num_outputs=None, name="a_func"
                )(x),
                3,
                fc_inputs,
            )
            dist_params = meta_tfdata.multi_batch_apply(
                mdn_lib.MDNParams(
                    num_alphas=self.num_mixture_components,
                    sample_size=self.action_size,
                    name="mdn",
                ),
                3,
                hidden,
            )
            gm = mdn_lib.get_mixture_distribution(
                dist_params, self.num_mixture_components, self.action_size
            )
            action = gm.approximate_mode()
            outputs["dist_params"] = dist_params
            if action_labels is not None:
                outputs["nll"] = mdn_lib.mdn_loss(gm, action_labels)
        else:
            action, _ = meta_tfdata.multi_batch_apply(
                lambda x: ImageFeaturesToPoseNet(
                    num_outputs=self.action_size, name="a_func"
                )(x),
                3,
                fc_inputs,
            )
            if action_labels is not None:
                outputs["nll"] = jnp.mean(
                    jnp.square(action - action_labels)
                )
        outputs["inference_output"] = action
        return outputs


class VRGripperEnvSimpleTrialModel(FlaxT2RModel):
    """WTL trial model conditioning on the demo's full-state trajectory
    (reference VRGripperEnvSimpleTrialModel :136-355); `retrial=True` adds
    the first-trial episode + success flag (the retrial policy)."""

    _NETWORK_TAKES_LABELS = True

    def __init__(
        self,
        action_size: int = 7,
        episode_length: int = 40,
        fc_embed_size: int = 32,
        ignore_embedding: bool = False,
        num_mixture_components: int = 1,
        num_condition_samples_per_task: int = 1,
        retrial: bool = False,
        embed_type: str = "temporal",
        obs_size: int = 32,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self._action_size = action_size
        self._episode_length = episode_length
        self._fc_embed_size = fc_embed_size
        self._ignore_embedding = ignore_embedding
        self._num_mixture_components = num_mixture_components
        self._num_condition_samples_per_task = num_condition_samples_per_task
        self._retrial = retrial
        self._embed_type = embed_type
        self._obs_size = obs_size
        if retrial and num_condition_samples_per_task != 2:
            raise ValueError(
                "Retrial models need exactly 2 condition episodes "
                "(demo + first trial)."
            )

    @property
    def episode_length(self) -> int:
        return self._episode_length

    def _episode_feature_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            full_state_pose=ExtendedTensorSpec(
                shape=(self._obs_size,),
                dtype=np.float32,
                name="full_state_pose",
            )
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    def _episode_label_specification(self, mode: str) -> TensorSpecStruct:
        del mode
        spec = TensorSpecStruct(
            action=ExtendedTensorSpec(
                shape=(self._action_size,),
                dtype=np.float32,
                name="action_world",
            ),
            success=ExtendedTensorSpec(
                shape=(1,), dtype=np.float32, name="success"
            ),
        )
        return copy_tensorspec(spec, batch_size=self._episode_length)

    @property
    def preprocessor(self):
        base = NoOpPreprocessor(_WtlEpisodeSpecAdapter(self))
        return preprocessors.FixedLenMetaExamplePreprocessor(
            base_preprocessor=base,
            num_condition_samples_per_task=(
                self._num_condition_samples_per_task
            ),
        )

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        return preprocessors.create_maml_feature_spec(
            self._episode_feature_specification(mode),
            self._episode_label_specification(mode),
        )

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        return preprocessors.create_maml_label_spec(
            self._episode_label_specification(mode)
        )

    def create_network(self) -> nn.Module:
        return _WtlTrialNet(
            action_size=self._action_size,
            episode_length=self._episode_length,
            fc_embed_size=self._fc_embed_size,
            ignore_embedding=self._ignore_embedding,
            num_mixture_components=self._num_mixture_components,
            retrial=self._retrial,
            embed_type=self._embed_type,
        )

    def model_train_fn(self, features, labels, inference_outputs, mode):
        loss = inference_outputs["nll"]
        return loss, {"loss/bc": loss}

    def pack_features(self, state, prev_episode_data, timestep) -> dict:
        return pack_wtl_meta_features(
            state,
            prev_episode_data,
            timestep,
            self._episode_length,
            self._num_condition_samples_per_task,
            action_size=self._action_size,
        )


class _WtlEpisodeSpecAdapter:
    def __init__(self, model: VRGripperEnvSimpleTrialModel):
        self._model = model

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        return self._model._episode_feature_specification(mode)

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        return self._model._episode_label_specification(mode)
