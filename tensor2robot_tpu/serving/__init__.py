"""Fleet serving: dynamic micro-batching policy server (docs/SERVING.md).

The host-side traffic layer over AbstractPredictor: bounded queue with
deadlines and backpressure, bucket-padded micro-batches (ladder = the
exporter's warmup_batch_sizes, so every served shape is pre-compiled),
zero-downtime hot-swap, structured observability snapshots.
"""

from tensor2robot_tpu.serving.buckets import (
    buckets_from_metadata,
    pick_bucket,
    resolve_buckets,
)
from tensor2robot_tpu.serving.metrics import RequestSpan, ServerMetrics
from tensor2robot_tpu.serving.server import (
    DeadlineExceeded,
    PolicyServer,
    RequestRejected,
    RequestShed,
    ServeError,
    ServeFuture,
    ServeResponse,
    ServerClosed,
)

__all__ = [
    "PolicyServer",
    "ServeFuture",
    "ServeResponse",
    "ServeError",
    "RequestRejected",
    "RequestShed",
    "DeadlineExceeded",
    "ServerClosed",
    "RequestSpan",
    "ServerMetrics",
    "resolve_buckets",
    "buckets_from_metadata",
    "pick_bucket",
]
