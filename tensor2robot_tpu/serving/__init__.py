"""Fleet serving: micro-batching policy server + multi-replica router
(docs/SERVING.md, docs/RESILIENCE.md).

The host-side traffic layer over AbstractPredictor: bounded queue with
deadlines and backpressure, bucket-padded micro-batches (ladder = the
exporter's warmup_batch_sizes, so every served shape is pre-compiled),
zero-downtime hot-swap, structured observability snapshots — one level
up, a FleetRouter dispatching over a pool of policy-server replica
*processes* with deadline-aware least-loaded routing, retries, hedging,
health eviction, and rolling deploys — and, at the top, the
multi-tenant Gateway (per-tenant quotas, priority tiers, coalescing,
per-tenant circuit breaking) with a load-driven Autoscaler spawning and
draining replicas off the router's own load counters.

Exports resolve lazily (PEP 562): replica worker processes import this
package on spawn, and the replica entry path must not drag the full
server/specs/jax stack into a child that may only ever run the
lightweight mock backend. `from tensor2robot_tpu.serving import X`
works exactly as before; `import tensor2robot_tpu.serving` alone now
costs microseconds.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    # server.py — the single-process micro-batching policy server.
    "PolicyServer": "server",
    "ServeFuture": "server",
    "ServeResponse": "server",
    "ServeError": "server",
    "RequestRejected": "server",
    "RequestShed": "server",
    "DeadlineExceeded": "server",
    "ServerClosed": "server",
    "PredictFailed": "server",
    "PredictTimeout": "server",
    # metrics.py
    "RequestSpan": "metrics",
    "ServerMetrics": "metrics",
    # buckets.py
    "resolve_buckets": "buckets",
    "buckets_from_metadata": "buckets",
    "pick_bucket": "buckets",
    # router.py — the multi-replica fleet layer.
    "FleetRouter": "router",
    "FleetResponse": "router",
    "RouterFuture": "router",
    "FleetError": "router",
    "FleetSaturated": "router",
    "ReplicaUnavailable": "router",
    "RequestAbandoned": "router",
    "RouterClosed": "router",
    # replica.py — process entry + backends.
    "ReplicaSpec": "replica",
    "policy_server_factory": "replica",
    "mock_server_factory": "replica",
    "multi_policy_mock_factory": "replica",
    "multi_policy_store_factory": "replica",
    # policies.py — the multi-policy resident set behind one replica.
    "MultiPolicyServer": "policies",
    "PolicyError": "policies",
    "PolicyUnknown": "policies",
    "PolicyEvicted": "policies",
    "PolicyLoadFailed": "policies",
    # compile_cache.py — persistent XLA compile cache for replicas.
    "enable_compile_cache": "compile_cache",
    "enable_compile_cache_for": "compile_cache",
    # gateway.py — the multi-tenant front door over router pools.
    "Gateway": "gateway",
    "TenantBinding": "gateway",
    "GateFuture": "gateway",
    "GateResponse": "gateway",
    "GateError": "gateway",
    "UnknownTenant": "gateway",
    "TenantThrottled": "gateway",
    "TenantSuspended": "gateway",
    "TierShed": "gateway",
    "GateDeadline": "gateway",
    "GatewayClosed": "gateway",
    "TIERS": "gateway",
    "observation_digest": "gateway",
    # autoscaler.py — load-driven replica count over a router pool.
    "Autoscaler": "autoscaler",
    # pool.py — socket-fabric replica processes (cross-host transport).
    "RemoteReplicaPool": "pool",
    "ReplicaLink": "pool",
    # fabric.py — zone-aware dispatch + cross-host stores + host AOT.
    "ZoneRouter": "fabric",
    "StoreServer": "fabric",
    "mirror_policy": "fabric",
    "remote_store_factory": "fabric",
    "host_aot_report": "fabric",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(
            f"module 'tensor2robot_tpu.serving' has no attribute {name!r}"
        )
    import importlib

    module = importlib.import_module(f"{__name__}.{module_name}")
    value = getattr(module, name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


if TYPE_CHECKING:  # pragma: no cover — static analyzers only
    from tensor2robot_tpu.serving.autoscaler import Autoscaler  # noqa: F401
    from tensor2robot_tpu.serving.fabric import (  # noqa: F401
        StoreServer,
        ZoneRouter,
        host_aot_report,
        mirror_policy,
        remote_store_factory,
    )
    from tensor2robot_tpu.serving.pool import (  # noqa: F401
        RemoteReplicaPool,
        ReplicaLink,
    )
    from tensor2robot_tpu.serving.compile_cache import (  # noqa: F401
        enable_compile_cache,
        enable_compile_cache_for,
    )
    from tensor2robot_tpu.serving.gateway import (  # noqa: F401
        TIERS,
        GateDeadline,
        GateError,
        GateFuture,
        GateResponse,
        Gateway,
        GatewayClosed,
        TenantBinding,
        TenantSuspended,
        TenantThrottled,
        TierShed,
        UnknownTenant,
        observation_digest,
    )
    from tensor2robot_tpu.serving.buckets import (  # noqa: F401
        buckets_from_metadata,
        pick_bucket,
        resolve_buckets,
    )
    from tensor2robot_tpu.serving.metrics import (  # noqa: F401
        RequestSpan,
        ServerMetrics,
    )
    from tensor2robot_tpu.serving.policies import (  # noqa: F401
        MultiPolicyServer,
        PolicyError,
        PolicyEvicted,
        PolicyLoadFailed,
        PolicyUnknown,
    )
    from tensor2robot_tpu.serving.replica import (  # noqa: F401
        ReplicaSpec,
        mock_server_factory,
        multi_policy_mock_factory,
        multi_policy_store_factory,
        policy_server_factory,
    )
    from tensor2robot_tpu.serving.router import (  # noqa: F401
        FleetError,
        FleetResponse,
        FleetRouter,
        FleetSaturated,
        ReplicaUnavailable,
        RequestAbandoned,
        RouterClosed,
        RouterFuture,
    )
    from tensor2robot_tpu.serving.server import (  # noqa: F401
        DeadlineExceeded,
        PolicyServer,
        PredictFailed,
        PredictTimeout,
        RequestRejected,
        RequestShed,
        ServeError,
        ServeFuture,
        ServeResponse,
        ServerClosed,
    )
