"""Autoscaler: load-driven replica count over a FleetRouter pool.

The router made replica failure a typed, routed-around event; the
gateway made tenant overload a typed, shed event. What neither does is
change CAPACITY: a flash crowd against a fixed pool can only shed, and
a quiet pool burns accelerators serving nothing. This module closes the
loop off the router's own load counters (`FleetRouter.load()`):

  * **Watermarks + hysteresis.** Utilization (in-flight work over
    routable capacity) above `high_watermark` for `scale_up_ticks`
    CONSECUTIVE ticks spawns one replica (`router.add_replica`); below
    `low_watermark` for `scale_down_ticks` consecutive ticks retires
    one (`router.retire_replica`). One step per decision — capacity
    moves like a thermostat, not a step function, and a single noisy
    tick moves nothing.
  * **Cooloff via the shared backoff schedule.** After every action the
    scaler goes quiet for a seeded `utils/backoff.py` delay that grows
    with the length of the same-direction streak — the anti-flap
    discipline: a scaler oscillating around a watermark pays an
    increasing price for each reversal-free repeat, and a fixed seed
    replays the exact pacing under a fixed load trace.
  * **Scale-down never kills work.** Retirement drains through the
    router's `draining` state (unrouted, in-flight completes, then
    stop) — the rolling-swap discipline applied to capacity. A drain
    that cannot empty aborts and restores the replica.
  * **Bounds.** Replica count stays in [min_replicas, max_replicas];
    pending (starting) replicas count toward the ceiling so a slow boot
    cannot stack spawns.

Chaos: the `scale` site (testing/chaos.py) fires on every scaling
action; a `drop` clause skips that action (a scaler whose actuator
misses a beat), `delay` stalls it, `raise` fails the tick — each a
real control-plane failure mode the bench leg can inject
deterministically.

`tick()` is the whole control law and is directly callable (tests,
bench); `start()` runs it on a daemon thread at `tick_interval_s`.
"""

from __future__ import annotations

import logging
import threading

from tensor2robot_tpu.testing import locksmith
import time
from typing import Dict, List, Optional

from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.backoff import Backoff, poll_loop

_log = logging.getLogger(__name__)

__all__ = ["Autoscaler"]


class Autoscaler:
    """Thermostat over one FleetRouter: utilization in, replica count out."""

    def __init__(
        self,
        router,
        *,
        min_replicas: int = 1,
        max_replicas: int = 8,
        high_watermark: float = 0.75,
        low_watermark: float = 0.25,
        scale_up_ticks: int = 2,
        scale_down_ticks: int = 4,
        cooloff_base_ms: float = 500.0,
        cooloff_cap_ms: float = 5000.0,
        tick_interval_s: float = 0.25,
        drain_timeout_s: float = 30.0,
        seed: int = 0,
    ):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas "
                f"({min_replicas})"
            )
        if not 0.0 <= low_watermark < high_watermark <= 1.0:
            raise ValueError(
                f"need 0 <= low ({low_watermark}) < high ({high_watermark}) "
                "<= 1"
            )
        self._router = router
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.high_watermark = high_watermark
        self.low_watermark = low_watermark
        self.scale_up_ticks = scale_up_ticks
        self.scale_down_ticks = scale_down_ticks
        self._tick_interval_s = tick_interval_s
        self._drain_timeout_s = drain_timeout_s
        # Cooloff grows with the same-direction streak and resets on a
        # reversal: repeated one-way moves are cheap (a real ramp),
        # repeated moves AFTER a reversal (flapping) are not.
        self._cooloff = Backoff(
            base_ms=cooloff_base_ms, cap_ms=cooloff_cap_ms, seed=seed
        )
        self._lock = locksmith.make_lock("Autoscaler._lock")
        self._above = 0  # consecutive ticks above high watermark
        self._below = 0  # consecutive ticks below low watermark
        self._quiet_until = 0.0
        self._last_direction: Optional[str] = None
        self._streak = 0
        self._counters: Dict[str, int] = {}
        self._actions: List[Dict] = []
        self._peak_up = 0
        self._thread: Optional[threading.Thread] = None
        self._drain_thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()

    # -- the control law ------------------------------------------------------

    def tick(self) -> Optional[str]:
        """One control step: read load, update hysteresis, maybe act.
        Returns 'up'/'down' when a scaling action landed, None
        otherwise. Thread-safe but intended to be driven by ONE clock
        (the background loop or a test)."""
        load = self._router.load()
        now = time.monotonic()
        with self._lock:
            self._count("ticks")
            self._peak_up = max(self._peak_up, load["replicas_up"])
            if load["utilization"] >= self.high_watermark:
                self._above += 1
                self._below = 0
            elif load["utilization"] <= self.low_watermark:
                self._below += 1
                self._above = 0
            else:
                self._above = 0
                self._below = 0
            if now < self._quiet_until:
                self._count("cooloff_skips")
                return None
            direction: Optional[str] = None
            if self._above >= self.scale_up_ticks:
                # Pending replicas count toward the ceiling: a slow boot
                # must not stack spawns.
                effective = load["replicas_up"] + load["replicas_pending"]
                if effective < self.max_replicas:
                    direction = "up"
                self._above = 0
            elif self._below >= self.scale_down_ticks:
                # One drain at a time: a second retirement while one is
                # still emptying would double-count capacity leaving.
                drain_busy = (
                    self._drain_thread is not None
                    and self._drain_thread.is_alive()
                )
                if load["replicas_up"] > self.min_replicas and not drain_busy:
                    direction = "down"
                self._below = 0
            if direction is None:
                return None
        return self._act(direction, load)

    def _act(self, direction: str, load: Dict) -> Optional[str]:
        fault = chaos.maybe_fire("scale")
        if fault is not None and fault.action in ("drop", "corrupt"):
            with self._lock:
                self._count("chaos_skipped")
            return None
        if direction == "up":
            index = self._router.add_replica()
            ok = True
        else:
            index = self._pick_drain_target()
            ok = index is not None
            if ok:
                # The drain blocks until the replica's in-flight work
                # empties (or aborts) — run it OFF the control thread,
                # or a stalled drain would park the tick loop through
                # exactly the overload it exists to absorb. The target
                # leaves routing the moment retire_replica marks it
                # draining; tick() refuses a second drain while this
                # one runs.
                drain = threading.Thread(
                    target=self._finish_drain,
                    args=(index,),
                    name="t2r-autoscaler-drain",
                    daemon=True,
                )
                with self._lock:
                    self._drain_thread = drain
                drain.start()
        now = time.monotonic()
        with self._lock:
            if self._last_direction == direction:
                self._streak += 1
            else:
                self._streak = 1
                self._last_direction = direction
            cooloff_s = self._cooloff.delay_s(min(self._streak, 8))
            self._quiet_until = now + cooloff_s
            self._count(f"scale_{direction}" if ok else "scale_aborted")
            self._actions.append(
                {
                    "direction": direction,
                    "replica": index,
                    "ok": bool(ok),
                    "utilization": round(load["utilization"], 4),
                    "replicas_up": load["replicas_up"],
                    "cooloff_ms": round(cooloff_s * 1e3, 1),
                }
            )
            if len(self._actions) > 256:
                del self._actions[:-256]
        return direction if ok else None

    def _finish_drain(self, index: int) -> None:
        ok = self._router.retire_replica(
            index, drain_timeout_s=self._drain_timeout_s
        )
        with self._lock:
            self._count("drains_completed" if ok else "drain_aborted")

    def _pick_drain_target(self) -> Optional[int]:
        """Least-loaded `up` replica — the cheapest drain."""
        snap = self._router.snapshot()
        up = [r for r in snap["replicas"] if r["state"] == "up"]
        if not up:
            return None
        return min(up, key=lambda r: r["inflight"])["index"]

    def _count(self, name: str, n: int = 1) -> None:
        self._counters[name] = self._counters.get(name, 0) + n

    # -- background clock -----------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            raise RuntimeError("Autoscaler.start() called twice")
        self._thread = threading.Thread(
            target=self._run, name="t2r-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    @poll_loop
    def _run(self) -> None:
        while not self._stop_event.wait(self._tick_interval_s):
            try:
                self.tick()
            except chaos.ChaosFault:
                with self._lock:
                    self._count("chaos_faults")
            except Exception:
                # A broken tick (router mid-stop, transient state) must
                # not kill the control loop; the next tick re-reads.
                _log.exception("autoscaler tick failed")
                with self._lock:
                    self._count("tick_errors")

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self) -> "Autoscaler":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> Dict:
        with self._lock:
            actions = list(self._actions)
        # Placement surface for multi-policy fleets: which policies each
        # replica holds resident and its eviction/cold-load counters,
        # read off the router's health-derived replica snapshots (the
        # prewarm_source discipline — backend-independent; entries are
        # omitted entirely on single-policy fleets). A capacity decision
        # that ignores residency scales up a replica that must cold-load
        # the hot policy before it helps.
        policies = []
        try:
            for r in self._router.snapshot()["replicas"]:
                if r.get("resident_policies") is None:
                    continue
                policies.append(
                    {
                        "replica": r["index"],
                        "resident_policies": r["resident_policies"],
                        "policy_evictions": r.get("policy_evictions"),
                        "policy_cold_loads": r.get("policy_cold_loads"),
                    }
                )
        except Exception:  # router mid-stop; placement view is advisory
            policies = []
        # Scale-up latency attribution: for every replica this scaler
        # spawned, the router-measured boot duration and the restore
        # tier each bucket booted from — the record that says whether a
        # flash-crowd scale-up paid deserialize-time (AOT) or
        # compile-time, per replica.
        spawned = {
            a["replica"] for a in actions if a["direction"] == "up" and a["ok"]
        }
        boots = []
        if spawned:
            try:
                replicas = self._router.snapshot()["replicas"]
            except Exception:  # router mid-stop; attribution is advisory
                replicas = []
            boots = [
                {
                    "replica": r["index"],
                    "boot_ms": r.get("boot_ms"),
                    "prewarm_source": r.get("prewarm_source"),
                }
                for r in replicas
                if r["index"] in spawned
            ]
        with self._lock:
            return {
                "counters": dict(self._counters),
                "actions": actions,
                "scale_up_boots": boots,
                "policies": policies,
                "peak_replicas_up": self._peak_up,
                "policy": {
                    "min_replicas": self.min_replicas,
                    "max_replicas": self.max_replicas,
                    "high_watermark": self.high_watermark,
                    "low_watermark": self.low_watermark,
                    "scale_up_ticks": self.scale_up_ticks,
                    "scale_down_ticks": self.scale_down_ticks,
                    "tick_interval_ms": self._tick_interval_s * 1e3,
                },
            }
