"""Batch-size bucket discipline for the policy server.

The exporter pre-warms a small set of batch sizes (`warmup_batch_sizes`,
published in `t2r_metadata.json` and materialized as
`warmup/warmup_requests.tfrecord`). The server must only ever hand the
predictor batches at EXACTLY those sizes: the StableHLO artifact is
batch-polymorphic, but each concrete batch size is a separate XLA
compile, and a fresh compile in the serve path is a multi-second latency
cliff under load. Padding every dispatch up to a bucket keeps the served
shape set closed over what warmup already compiled.

Resolution order for the ladder: explicit constructor argument >
`T2R_SERVE_BUCKETS` > the loaded export's `warmup_batch_sizes` metadata
> `(1,)` (the degenerate no-batching ladder).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu import flags as t2r_flags

__all__ = [
    "resolve_buckets",
    "pick_bucket",
    "pad_feature_batch",
    "load_warmup_batches",
]


def _normalize(sizes: Sequence[int], source: str) -> Tuple[int, ...]:
    out = sorted({int(s) for s in sizes})
    if not out or any(s < 1 for s in out):
        raise ValueError(
            f"bucket ladder from {source} must be positive ints, got {sizes!r}"
        )
    return tuple(out)


def _flag_buckets() -> Optional[Tuple[int, ...]]:
    raw = t2r_flags.get_str("T2R_SERVE_BUCKETS")
    if raw is None or not raw.strip():
        return None
    try:
        sizes = [int(part) for part in raw.split(",") if part.strip()]
    except ValueError as err:
        raise ValueError(
            f"T2R_SERVE_BUCKETS must be comma-separated ints, got {raw!r}"
        ) from err
    return _normalize(sizes, "T2R_SERVE_BUCKETS")


def buckets_from_metadata(metadata: Mapping) -> Optional[Tuple[int, ...]]:
    """The exporter-published ladder (t2r_metadata.json
    `warmup_batch_sizes`), or None when the export predates it / was
    written without warmup."""
    sizes = metadata.get("warmup_batch_sizes") if metadata else None
    if not sizes:
        return None
    return _normalize(sizes, "t2r_metadata.json warmup_batch_sizes")


def resolve_buckets(
    explicit: Optional[Sequence[int]],
    metadata: Optional[Mapping],
) -> Tuple[int, ...]:
    if explicit is not None:
        return _normalize(explicit, "batch_buckets argument")
    from_flag = _flag_buckets()
    if from_flag is not None:
        return from_flag
    from_meta = buckets_from_metadata(metadata or {})
    if from_meta is not None:
        return from_meta
    return (1,)


def pick_bucket(buckets: Tuple[int, ...], n: int) -> int:
    """Smallest bucket that fits n requests; n above the ladder means the
    caller must split the batch at the max bucket first."""
    for bucket in buckets:
        if bucket >= n:
            return bucket
    raise ValueError(
        f"batch of {n} exceeds the max bucket {buckets[-1]}; dispatch at "
        "most max-bucket requests per batch"
    )


def pad_feature_batch(
    rows: List[Mapping[str, np.ndarray]], bucket: int
) -> Dict[str, np.ndarray]:
    """Stacks per-request flat feature rows into one batch padded to
    `bucket` by repeating the last real row. Padding rows are pure
    compute filler: the dispatcher never returns their outputs."""
    if not rows:
        raise ValueError("cannot pad an empty batch")
    if len(rows) > bucket:
        raise ValueError(f"{len(rows)} rows do not fit bucket {bucket}")
    pad = bucket - len(rows)
    out: Dict[str, np.ndarray] = {}
    for key in rows[0]:
        values = [np.asarray(row[key]) for row in rows]
        values.extend([values[-1]] * pad)
        out[key] = np.stack(values)
    return out


def load_warmup_batches(
    export_dir: str, feature_spec, metadata: Mapping
) -> Dict[int, Dict[str, np.ndarray]]:
    """Parses `warmup/warmup_requests.tfrecord` back into per-bucket
    batches — the exact spec-conforming payloads the exporter compiled
    against, re-chunked by the published `warmup_batch_sizes` (rows are
    written in ladder order). Missing warmup artifacts return {} and the
    server synthesizes random batches instead."""
    import os

    from tensor2robot_tpu.data.parser import SpecParser
    from tensor2robot_tpu.data.tfrecord import read_tfrecords
    from tensor2robot_tpu.export.export_generators import (
        WARMUP_DIR,
        WARMUP_FILENAME,
    )
    from tensor2robot_tpu.specs import flatten_spec_structure

    path = os.path.join(export_dir, WARMUP_DIR, WARMUP_FILENAME)
    sizes = metadata.get("warmup_batch_sizes") if metadata else None
    if not sizes or not os.path.exists(path):
        return {}
    records = list(read_tfrecords(path))
    if len(records) != sum(sizes):
        return {}  # foreign layout; let the caller synthesize
    parser = SpecParser(feature_spec)
    batches: Dict[int, Dict[str, np.ndarray]] = {}
    offset = 0
    for size in sizes:
        batch = parser.parse_batch(records[offset : offset + size])
        batches[int(size)] = dict(flatten_spec_structure(batch).items())
        offset += size
    return batches
