"""JAX persistent compilation cache for serving processes.

Replica boot cost is dominated by per-bucket XLA compiles: a policy
server prewarms every warmup bucket before it reports started, and a
hot-swap prewarms them again on the incoming version. None of that work
changes between boots of the same artifact on the same topology — it is
exactly what jax's persistent compilation cache deduplicates. This
module is the serving-side switch for it, behind the central
`T2R_COMPILE_CACHE_DIR` flag: replica N's first boot pays the compiles
and writes the cache; every later boot (respawns after a chaos kill,
rolling-deploy restarts, fleet scale-ups on the same host image)
deserializes instead of compiling.

This is the down payment on the ROADMAP's AOT-serving item: same
outcome (compile once per artifact, not once per process), without yet
shipping serialized executables inside the export dir.
"""

from __future__ import annotations

from typing import Optional

from tensor2robot_tpu import flags as t2r_flags

__all__ = ["enable_compile_cache"]


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Points jax's persistent compilation cache at a directory.

    Resolution: explicit `cache_dir` argument > `T2R_COMPILE_CACHE_DIR`
    flag > disabled (returns None, no config touched — the bit-exact
    default path). Returns the directory in effect. Every compile is
    cacheable (min compile time 0): a replica fleet re-boots the same
    buckets, so even sub-second entries pay for themselves by the second
    process.
    """
    if cache_dir is None:
        cache_dir = t2r_flags.get_str("T2R_COMPILE_CACHE_DIR")
    if not cache_dir:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # jax memoizes the cache's enabled/disabled state at the FIRST
    # compile: a process that compiled anything before this call (model
    # init, an eager export) has latched "disabled" and would silently
    # ignore the config update. reset_cache() drops the memo so the next
    # compile re-reads the directory we just set.
    try:
        from jax._src import compilation_cache as _compilation_cache
    except ImportError:  # pragma: no cover - future jax relayout
        _compilation_cache = None
    reset = getattr(_compilation_cache, "reset_cache", None)
    if reset is not None:
        reset()
    return cache_dir
