"""JAX persistent compilation cache for serving processes.

Replica boot cost is dominated by per-bucket XLA compiles: a policy
server prewarms every warmup bucket before it reports started, and a
hot-swap prewarms them again on the incoming version. None of that work
changes between boots of the same artifact on the same topology — it is
exactly what jax's persistent compilation cache deduplicates. This
module is the serving-side switch for it, behind the central
`T2R_COMPILE_CACHE_DIR` flag: replica N's first boot pays the compiles
and writes the cache; every later boot (respawns after a chaos kill,
rolling-deploy restarts, fleet scale-ups on the same host image)
deserializes instead of compiling.

With serialized AOT executables in the artifact (export/aot.py) this
cache is the SECOND tier of the restore ladder: AOT executable ->
persistent compile cache -> fresh trace. `enable_compile_cache_for`
is the restore-time entry point: a version whose warmup ladder is
fully covered by deserialized executables will never compile, so the
cache round-trip (config update + latched-state reset) is skipped for
that swap — re-entering it per swap was pure overhead on AOT-hit
boots.
"""

from __future__ import annotations

from typing import Optional

from tensor2robot_tpu import flags as t2r_flags

__all__ = ["enable_compile_cache", "enable_compile_cache_for"]


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Points jax's persistent compilation cache at a directory.

    Resolution: explicit `cache_dir` argument > `T2R_COMPILE_CACHE_DIR`
    flag > disabled (returns None, no config touched — the bit-exact
    default path). Returns the directory in effect. Every compile is
    cacheable (min compile time 0): a replica fleet re-boots the same
    buckets, so even sub-second entries pay for themselves by the second
    process.
    """
    if cache_dir is None:
        cache_dir = t2r_flags.get_str("T2R_COMPILE_CACHE_DIR")
    if not cache_dir:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # jax memoizes the cache's enabled/disabled state at the FIRST
    # compile: a process that compiled anything before this call (model
    # init, an eager export) has latched "disabled" and would silently
    # ignore the config update. reset_cache() drops the memo so the next
    # compile re-reads the directory we just set.
    try:
        from jax._src import compilation_cache as _compilation_cache
    except ImportError:  # pragma: no cover - future jax relayout
        _compilation_cache = None
    reset = getattr(_compilation_cache, "reset_cache", None)
    if reset is not None:
        reset()
    return cache_dir


def enable_compile_cache_for(loaded) -> Optional[str]:
    """Restore-time cache engagement for one loaded export version.

    When the version will serve EVERY bucket of its resolved ladder
    (T2R_SERVE_BUCKETS override included, `serving/buckets.py`
    resolution) from deserialized AOT executables, no compile will
    happen for it — skip the cache round-trip entirely (returns None;
    an already-enabled cache is left as is, this only skips
    re-entering). Otherwise behaves exactly like
    `enable_compile_cache()`: a compile tier is live for this version
    and the cache must engage BEFORE its first compile (the prewarm
    that follows restore). A server constructed with an explicit
    `batch_buckets` ladder is invisible from here; `PolicyServer`
    re-engages at start() for any bucket outside the AOT table.
    """
    if loaded is not None and getattr(loaded, "aot_covered", False):
        from tensor2robot_tpu.serving import buckets as buckets_lib

        table = getattr(loaded, "aot_executables", None) or {}
        try:
            ladder = buckets_lib.resolve_buckets(
                None, getattr(loaded, "metadata", None) or {}
            )
        except ValueError:
            ladder = ()
        if ladder and all(bucket in table for bucket in ladder):
            return None
    return enable_compile_cache()
