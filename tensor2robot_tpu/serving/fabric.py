"""Cross-host serving fabric: socket replicas, zones, cross-host stores.

Four pieces turn the one-host fleet into a multi-host serving fabric,
all riding the shared CRC-framed wire (`net/frames.py`):

  * **The fabric replica entry** (`fabric_replica_main`, and the
    `python -m tensor2robot_tpu.serving.fabric` CLI the pool launches).
    One replica process = one `ReplicaCore` (serving/replica.py — the
    SAME message core the mp fabric runs) driven by a duplex
    `FrameServer` instead of an mp queue, publishing its
    incarnation-stamped address only after its server factory has
    succeeded, so "address published" ≈ "ready to serve".
  * **ZoneRouter** — zone-aware least-loaded dispatch over per-zone
    `FleetRouter`s with CROSS-ZONE hedging and retry: a hedge always
    goes to a different zone than every attempt already in flight, a
    failed attempt retries onto an untried zone first, and every future
    still resolves through the per-zone routers' deadline backstops.
    The surface duck-types FleetRouter (submit/call/load/snapshot/
    rolling_swap/stop), so the gateway can span ZoneRouters as pools.
  * **Cross-host artifact store** — `StoreServer` exports an
    `ArtifactStore` over the wire by content address; `mirror_policy`
    pulls a policy (manifest + every referenced blob + its transitive
    delta bases) into a local mirror, hash-verifying every blob on
    receipt, manifests landing last, bases before dependents; and
    `remote_store_factory` is the replica factory that cold-loads its
    policies from such a mirror — so a fresh host materializes exactly
    the bytes the publisher's store holds, by sha256, or refuses typed.
  * **Per-host AOT resolution** (`host_aot_report`) — each host checks
    the artifact's `aot/` executables against ITS OWN platform/topology
    key (header-only: integrity then key, the payload is never
    unpickled here). A matching host restores from the executables; a
    mismatched one gets a typed per-file reason (`topology`,
    `jax_version`, `corrupt`) and falls down the restore ladder — the
    per-host table a heterogeneous fleet needs so a transplanted
    topology is never silently served.

Chaos peers: fabric replicas scope as `z<zone>.r<i>` (serving/pool.py
`replica_scope`), so `net_send`/`net_recv` plans cut specific links and
`partition:z1.r0+z1.r1` cuts a whole zone, exactly as replay shard
plans cut `s<k>`.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import itertools
import json
import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.net import frames
from tensor2robot_tpu.serving import replica as replica_lib
from tensor2robot_tpu.serving.router import (
    FleetError,
    RequestAbandoned,
    RouterClosed,
    RouterFuture,
    _RouterMetrics,
)
from tensor2robot_tpu.testing import chaos, locksmith
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "ZoneRouter",
    "StoreServer",
    "fabric_replica_main",
    "host_aot_report",
    "mirror_policy",
    "remote_store_factory",
]


# -- fabric replica entry ------------------------------------------------------


class _PostBox:
    """Holds the CURRENT router connection's duplex send callable.

    The core's `post` is fixed at construction but the router reconnects
    (respawn re-resolution, torn frames, partitions heal); the postbox
    rebinds on every inbound message, so an async reply completing after
    a reconnect rides the NEW connection instead of dying with the old
    one. With no router connected, posts drop — the same best-effort
    contract as an mp replica whose response queue is gone."""

    def __init__(self):
        self._lock = locksmith.make_lock("_PostBox._lock")
        self._send: Optional[Callable[[Any], bool]] = None

    def bind(self, send: Callable[[Any], bool]) -> None:
        with self._lock:
            self._send = send

    def __call__(self, message: tuple) -> None:
        with self._lock:
            send = self._send
        if send is None:
            return
        send(message)


def fabric_replica_main(
    index: int,
    spec: "replica_lib.ReplicaSpec",
    root: str,
    incarnation: int,
    zone: Optional[str] = None,
) -> None:
    """Process entry for a socket-fabric replica.

    Boot order is the discovery contract: build the server (factory may
    be slow — restore, prewarm), THEN start the frame server, THEN
    publish the incarnation-stamped address. A router that can connect
    is talking to a replica whose factory already succeeded; a factory
    crash exits nonzero with nothing published, and the supervisor's
    boot timeout handles the silence."""
    from tensor2robot_tpu.serving.pool import replica_scope

    if spec.scope is None:
        spec = dataclasses.replace(
            spec, scope=replica_scope(index, spec, zone)
        )
    server = replica_lib.build_server(index, spec)
    postbox = _PostBox()
    core = replica_lib.ReplicaCore(index, server, postbox, free_q=None)
    stop_event = threading.Event()
    # One core, many possible connections (a reconnecting router, a
    # probing sibling): core.handle is not reentrant, so every
    # connection thread serializes through this lock. Idle ticks take
    # it non-blocking — a tick skipped under traffic costs nothing,
    # the next message's own tick covers it.
    core_lock = locksmith.make_lock("fabric_replica.core_lock")

    def handler(message: tuple, send: Callable[[Any], bool]) -> None:
        postbox.bind(send)
        with core_lock:
            if not core.handle(message):
                stop_event.set()

    def idle_tick() -> None:
        if core_lock.acquire(blocking=False):
            try:
                core.tick(time.time())
            finally:
                core_lock.release()

    frame_server = frames.FrameServer(
        handler, duplex=True, idle_tick=idle_tick
    ).start()
    chaos.maybe_fire("boot")
    frames.publish_address(
        root, frame_server.port, incarnation=incarnation
    )
    try:
        stop_event.wait()
    finally:
        frame_server.stop()
        core.close()


def _cli_main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tensor2robot_tpu.serving.fabric",
        description="Fabric replica process entry (launched by "
        "serving/pool.py RemoteReplicaPool; not a user-facing tool).",
    )
    parser.add_argument("--replica", action="store_true", required=True)
    parser.add_argument("--index", type=int, required=True)
    parser.add_argument("--root", required=True)
    parser.add_argument("--incarnation", type=int, required=True)
    parser.add_argument("--spec", required=True)
    parser.add_argument("--zone", default=None)
    args = parser.parse_args(argv)
    with open(args.spec, "rb") as f:
        spec = pickle.load(f)
    fabric_replica_main(
        args.index, spec, args.root, args.incarnation, zone=args.zone
    )
    return 0


# -- zone-aware dispatch -------------------------------------------------------


class _ZoneRequest:
    __slots__ = (
        "future", "features", "deadline", "policy_id", "tried",
        "outstanding", "retries_left", "hedged", "last_error", "resolved",
        "t_submit",
    )

    def __init__(self, future, features, deadline, policy_id, retries):
        self.future = future
        self.features = features
        self.deadline = deadline  # monotonic
        self.policy_id = policy_id
        self.tried: List[str] = []  # zone names, placement order
        self.outstanding = 0
        self.retries_left = retries
        self.hedged = False
        self.last_error: Optional[BaseException] = None
        self.resolved = False
        self.t_submit = time.monotonic()


class ZoneRouter:
    """Least-loaded dispatch across availability zones, hedged ACROSS
    zones — the cross-host tail-amputation the one-pool hedge cannot
    give (a straggling zone hedges into a healthy one, and a partitioned
    zone's requests win from its sibling).

    `zones` maps zone name -> a started FleetRouter (typically one
    socket-transport router per host/zone). Dispatch picks the
    admissible zone with the lowest utilization (ties broken
    round-robin); a request still pending `T2R_FABRIC_HEDGE_MS` after
    placement is duplicated into a DIFFERENT zone (first reply wins); a
    failed attempt retries onto an untried zone while deadline and
    `zone_retries` budget remain. Every returned future resolves: inner
    futures carry their routers' deadline backstops, and a placement
    that fails synchronously resolves the wrapper typed.

    Duck-types the FleetRouter client surface (submit/call/load/
    snapshot/rolling_swap/stop), so a ZoneRouter can stand where a
    router stands — including as a Gateway pool."""

    def __init__(
        self,
        zones: Mapping[str, Any],
        hedge_ms: Optional[int] = None,
        zone_retries: int = 1,
        default_deadline_ms: Optional[int] = None,
    ):
        if not zones:
            raise ValueError("ZoneRouter needs at least one zone")
        self._zones: Dict[str, Any] = dict(zones)
        self._hedge_s = (
            hedge_ms if hedge_ms is not None
            else t2r_flags.get_int("T2R_FABRIC_HEDGE_MS")
        ) / 1e3
        self._zone_retries = int(zone_retries)
        self._default_deadline_s = (
            default_deadline_ms if default_deadline_ms is not None
            else t2r_flags.get_int("T2R_SERVE_DEADLINE_MS")
        ) / 1e3
        # Reentrant: an inner future that is ALREADY resolved when
        # _place registers its callback fires _on_inner_done
        # synchronously on the placing thread, which holds this lock.
        self._lock = locksmith.make_rlock("ZoneRouter._lock")
        self._metrics = _RouterMetrics()
        self._ids = itertools.count(1)
        self._rr = 0
        self._closed = False

    @property
    def zones(self) -> List[str]:
        return sorted(self._zones)

    # -- placement ------------------------------------------------------------

    def _pick_zone(self, exclude: Tuple[str, ...]) -> str:
        """Least-utilized zone with routable capacity, preferring zones
        not in `exclude` (the cross-zone discipline: a hedge/retry only
        falls back onto a tried zone when no other has capacity)."""
        loads = {}
        for name, router in self._zones.items():
            try:
                loads[name] = router.load()
            except Exception:  # a stopping/broken zone is unroutable
                continue
        candidates = [
            n for n, l in loads.items()
            if n not in exclude and l["replicas_up"] > 0
        ]
        if not candidates:
            candidates = [
                n for n, l in loads.items() if l["replicas_up"] > 0
            ]
        if not candidates:
            raise FleetError(
                "no zone has a healthy replica "
                f"({len(self._zones)} zones, all down or starting)"
            )
        best = min(loads[n]["utilization"] for n in candidates)
        tied = sorted(
            n for n in candidates if loads[n]["utilization"] == best
        )
        self._rr += 1
        return tied[self._rr % len(tied)]

    def _place(self, req: _ZoneRequest, exclude: Tuple[str, ...],
               is_hedge: bool) -> None:
        """Called under self._lock. Walks admissible zones least-loaded
        first: a zone whose submit refuses synchronously (closed,
        saturated, no healthy replica) is counted as a failed attempt
        and the NEXT zone is tried — so one dead zone costs a counter,
        not the request. Raises FleetError only when every zone has
        refused (caller decides whether that is fatal)."""
        remaining_s = req.deadline - time.monotonic()
        if remaining_s <= 0:
            raise RequestAbandoned(
                "request deadline passed before zone placement",
                reason="deadline",
            )
        tried_now = set(exclude)
        last_error: Optional[BaseException] = None
        while True:
            try:
                zone = self._pick_zone(tuple(tried_now))
            except FleetError as err:
                raise last_error if isinstance(
                    last_error, FleetError
                ) else err
            if zone in tried_now:
                # _pick_zone's capacity fallback reused an excluded
                # zone: no fresh zone remains for this attempt.
                raise last_error if isinstance(
                    last_error, FleetError
                ) else FleetError(
                    "every zone refused the attempt "
                    f"(last: {last_error})"
                )
            router = self._zones[zone]
            try:
                inner = router.submit(
                    req.features,
                    deadline_ms=remaining_s * 1e3,
                    policy_id=req.policy_id,
                )
            except Exception as err:
                last_error = err
                req.last_error = err
                tried_now.add(zone)
                self._metrics.count(f"zone_attempt_failed_{zone}")
                continue
            req.tried.append(zone)
            req.outstanding += 1
            self._metrics.count(f"zone_dispatch_{zone}")
            if is_hedge:
                self._metrics.count("zone_hedges")
            inner.add_done_callback(
                lambda f, zone=zone, hedge=is_hedge:
                self._on_inner_done(req, f, zone, hedge)
            )
            return

    def _on_inner_done(self, req: _ZoneRequest, inner, zone: str,
                       was_hedge: bool) -> None:
        fire = None
        with self._lock:
            req.outstanding -= 1
            if req.resolved:
                return
            err = inner.error()
            if err is None:
                req.resolved = True
                if was_hedge:
                    self._metrics.count("zone_hedge_wins")
                self._metrics.count(f"zone_win_{zone}")
                self._metrics.count("completed")
                fire = (inner.result(0), None)
            else:
                req.last_error = err
                self._metrics.count(f"zone_attempt_failed_{zone}")
                remaining = req.deadline - time.monotonic()
                placed = False
                if (
                    not self._closed
                    and remaining > 0
                    and req.retries_left > 0
                ):
                    req.retries_left -= 1
                    self._metrics.count("zone_retries")
                    try:
                        self._place(
                            req, exclude=tuple(req.tried), is_hedge=False
                        )
                        placed = True
                    except FleetError as place_err:
                        req.last_error = place_err
                if not placed and req.outstanding == 0:
                    req.resolved = True
                    self._metrics.count("failed")
                    fire = (None, req.last_error)
        if fire is not None:
            response, error = fire
            if error is None:
                self._metrics.observe_latency(
                    (time.monotonic() - req.t_submit) * 1e3
                )
            # The future fires OUTSIDE self._lock: user callbacks may
            # re-enter submit().
            req.future._set(response, error)

    def _maybe_hedge(self, req: _ZoneRequest) -> None:
        with self._lock:
            if (
                self._closed
                or req.resolved
                or req.hedged
                or len(self._zones) < 2
            ):
                return
            req.hedged = True
            try:
                # exclude=tried → the hedge lands in a DIFFERENT zone
                # than every attempt in flight; with no untried zone
                # left, _pick_zone's fallback would reuse one, so check.
                untried = [
                    z for z in self._zones if z not in req.tried
                ]
                if not untried:
                    req.hedged = False
                    return
                self._place(req, exclude=tuple(req.tried), is_hedge=True)
            except FleetError:
                req.hedged = False  # best-effort; original stands

    # -- client surface -------------------------------------------------------

    def submit(
        self,
        features: Mapping[str, Any],
        deadline_ms: Optional[float] = None,
        policy_id: Optional[str] = None,
    ) -> RouterFuture:
        with self._lock:
            if self._closed:
                raise RouterClosed("zone router is not running")
            deadline = time.monotonic() + (
                deadline_ms / 1e3 if deadline_ms is not None
                else self._default_deadline_s
            )
            req = _ZoneRequest(
                RouterFuture(next(self._ids)), features, deadline,
                policy_id, self._zone_retries,
            )
            self._metrics.count("submitted")
            self._place(req, exclude=(), is_hedge=False)
        if self._hedge_s > 0 and len(self._zones) > 1:
            timer = threading.Timer(
                self._hedge_s, self._maybe_hedge, args=(req,)
            )
            timer.daemon = True
            timer.start()
        return req.future

    def call(
        self,
        features: Mapping[str, Any],
        deadline_ms: Optional[float] = None,
        timeout: Optional[float] = None,
        policy_id: Optional[str] = None,
    ):
        future = self.submit(
            features, deadline_ms=deadline_ms, policy_id=policy_id
        )
        if timeout is None:
            timeout = (
                deadline_ms / 1e3 if deadline_ms is not None
                else self._default_deadline_s
            ) + 30.0
        return future.result(timeout)

    # -- fleet surface --------------------------------------------------------

    def load(self) -> Dict:
        """Aggregate capacity across zones, per-zone detail included —
        the shape autoscalers and the gateway's shed accounting read."""
        per_zone = {}
        for name, router in self._zones.items():
            try:
                per_zone[name] = router.load()
            except Exception:
                per_zone[name] = {
                    "replicas_up": 0, "inflight": 0, "capacity": 0,
                    "utilization": 1.0, "shed_saturated": 0,
                    "replicas_pending": 0, "replicas_draining": 0,
                }
        inflight = sum(l["inflight"] for l in per_zone.values())
        capacity = sum(l["capacity"] for l in per_zone.values())
        return {
            "replicas_up": sum(
                l["replicas_up"] for l in per_zone.values()
            ),
            "replicas_pending": sum(
                l.get("replicas_pending", 0) for l in per_zone.values()
            ),
            "replicas_draining": sum(
                l.get("replicas_draining", 0) for l in per_zone.values()
            ),
            "inflight": inflight,
            "capacity": capacity,
            "utilization": (inflight / capacity) if capacity else 1.0,
            "shed_saturated": sum(
                l.get("shed_saturated", 0) for l in per_zone.values()
            ),
            "zones": per_zone,
        }

    def snapshot(self) -> Dict:
        snap = self._metrics.snapshot()
        snap["zones"] = {
            name: router.snapshot()
            for name, router in self._zones.items()
        }
        # Flattened replica list with zone labels: the shape the gateway
        # reads model fingerprints and residency off, unchanged.
        replicas = []
        for name in sorted(self._zones):
            for rep in snap["zones"][name].get("replicas", ()):
                entry = dict(rep)
                entry["zone"] = name
                replicas.append(entry)
        snap["replicas"] = replicas
        # This process's wire accounting (every zone router here shares
        # one codec, pool, and stats surface): stage timings,
        # per-segment-class bytes, receive-pool allocation audit.
        snap["wire"] = frames.wire_snapshot()
        snap["policy"] = {
            "hedge_ms": self._hedge_s * 1e3,
            "zone_retries": self._zone_retries,
            "zones": self.zones,
        }
        return snap

    def rolling_swap(self, swap_timeout_s: float = 60.0,
                     policy_id: Optional[str] = None) -> Dict:
        """Zone by zone, replica by replica — one replica mid-swap
        fleet-wide, the rolling discipline applied across zones. A
        failed swap aborts the roll (remaining zones keep serving the
        old version)."""
        results: Dict[str, Any] = {"zones": {}, "failed": None}
        for name in sorted(self._zones):
            zone_result = self._zones[name].rolling_swap(
                swap_timeout_s=swap_timeout_s, policy_id=policy_id
            )
            results["zones"][name] = zone_result
            if zone_result.get("failed") is not None:
                results["failed"] = f"{name}:{zone_result['failed']}"
                break
        return results

    def stop(self, timeout_s: float = 10.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for router in self._zones.values():
            best_effort(router.stop, timeout_s)

    def __enter__(self) -> "ZoneRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# -- cross-host artifact store -------------------------------------------------


class StoreServer:
    """Serves an ArtifactStore over the frame wire, content-addressed.

    Protocol (request/reply shape; replies lead with the request's
    req_id, the SocketChannel correlation contract):

        ("manifest", req_id, policy_id) -> (req_id, "ok", manifest)
        ("blob", req_id, sha)           -> (req_id, "ok", bytes)
        ("list", req_id)                -> (req_id, "ok", [policy_id])
        any failure                     -> (req_id, "error", class, msg)

    Blob replies are raw stored bytes; the CLIENT re-hashes them against
    the sha it asked for (mirror_policy), so a corrupt wire or store
    surfaces as a typed refusal on the receiving host, never as a
    silently-wrong artifact. Publishes its address under
    `<store root>/serve/transport.json`."""

    def __init__(self, store, root: Optional[str] = None,
                 incarnation: int = 0):
        self._store = store
        self.root = root if root is not None else os.path.join(
            store.root, "serve"
        )
        os.makedirs(self.root, exist_ok=True)
        self._server = frames.FrameServer(self._handle)
        self._incarnation = int(incarnation)

    def start(self) -> "StoreServer":
        self._server.start()
        frames.publish_address(
            self.root, self._server.port, incarnation=self._incarnation
        )
        return self

    @property
    def port(self) -> int:
        return self._server.port

    def _handle(self, request: tuple):
        if not isinstance(request, tuple) or len(request) < 2:
            return None  # unfluent peer; no req_id to answer to
        kind, req_id = request[0], request[1]
        try:
            if kind == "manifest":
                return (req_id, "ok", self._store.manifest(request[2]))
            if kind == "blob":
                sha = request[2]
                return (
                    req_id, "ok",
                    self._store._read_blob(sha, f"remote fetch {sha[:12]}"),
                )
            if kind == "list":
                return (req_id, "ok", self._store.policies())
            return (req_id, "error", "BadRequest", f"unknown op {kind!r}")
        except Exception as err:
            return (req_id, "error", type(err).__name__, str(err))

    def stop(self) -> None:
        self._server.stop()


# Blob fetches kept in flight per mirror connection. Bounded so a
# mirror of a many-blob policy cannot hold an unbounded reply backlog
# in memory on either end.
MIRROR_WINDOW = 8


class _StoreClient:
    """Typed call helper over a PipelinedChannel to a StoreServer.

    `submit`/`result` expose the pipelining: several blob fetches ride
    one connection concurrently, correlated by req_id — `mirror_policy`
    keeps a window of them in flight instead of paying a full lockstep
    round trip per blob."""

    def __init__(self, service_root: str, timeout_s: float = 30.0):
        self._channel = frames.PipelinedChannel(service_root)
        self._timeout_s = timeout_s
        self._ids = itertools.count(1)

    def submit(self, op: str, *args):
        req_id = f"{op}-{next(self._ids)}"
        return self._channel.submit((op, req_id) + args, req_id)

    def result(self, pending):
        from tensor2robot_tpu.export import artifact_store as store_lib

        reply = self._channel.result(pending, timeout_s=self._timeout_s)
        if reply[1] == "ok":
            return reply[2]
        # Rehydrate the store's own error taxonomy: a server-side
        # ArtifactCorrupt / PolicyNotFound stays THAT type on this
        # host, so mirror callers branch on it exactly as local ones.
        error_cls = getattr(store_lib, reply[2], None)
        if not (
            isinstance(error_cls, type)
            and issubclass(error_cls, store_lib.ArtifactStoreError)
        ):
            error_cls = store_lib.ArtifactStoreError
        raise error_cls(
            f"remote store failed: {reply[2]}: {reply[3]}"
        )

    def call(self, op: str, *args):
        return self.result(self.submit(op, *args))

    def close(self) -> None:
        self._channel.close()


def mirror_policy(
    service_root: str,
    policy_id: str,
    dest_store,
    timeout_s: float = 30.0,
) -> Dict[str, Any]:
    """Pull one policy (and its transitive delta bases) from a remote
    StoreServer into `dest_store`, by content address.

    Every blob is fetched by sha256 and RE-HASHED on receipt (a wire or
    remote-disk corruption is a typed ArtifactCorrupt here, before any
    byte lands); already-present blobs are skipped (content-addressed
    dedup across mirrors). Manifests land LAST, bases before
    dependents, each atomically — so a partially-mirrored policy does
    not exist, and a concurrent reader sees either nothing or a policy
    whose every referenced blob is already on disk. Returns
    {policies, blobs_fetched, blobs_reused, bytes_fetched}."""
    from tensor2robot_tpu.export.artifact_store import ArtifactCorrupt

    client = _StoreClient(service_root, timeout_s=timeout_s)
    try:
        # Walk the delta-base chain: manifests base-first.
        chain: List[Tuple[str, Dict[str, Any]]] = []
        seen = set()
        cursor: Optional[str] = policy_id
        while cursor is not None:
            if cursor in seen:
                raise ArtifactCorrupt(
                    f"policy {policy_id!r}: delta base chain cycles "
                    f"at {cursor!r}"
                )
            seen.add(cursor)
            manifest = client.call("manifest", cursor)
            chain.append((cursor, manifest))
            cursor = manifest["payload"].get("base")
        chain.reverse()  # bases first

        fetched = reused = nbytes = 0
        # Want-list across the whole chain (dedup preserving order: a
        # base and its dependent may share blobs).
        want: List[Tuple[str, str]] = []
        want_seen = set()
        for pid, manifest in chain:
            shas = [
                entry["blob"] for entry in manifest["files"].values()
            ]
            payload_blob = manifest["payload"].get("blob")
            if payload_blob:
                shas.append(payload_blob)
            for sha in shas:
                if sha in want_seen:
                    continue
                want_seen.add(sha)
                if os.path.exists(dest_store._blob_path(sha)):
                    reused += 1
                else:
                    want.append((pid, sha))
        # Windowed pipeline: keep up to MIRROR_WINDOW blob requests in
        # flight on the one connection (the channel multiplexes them by
        # req_id), landing each oldest-first — a WAN round trip is paid
        # once per window, not once per blob. Each blob is still
        # sha256-re-hashed before it touches disk.
        window: List[Tuple[str, str, Any]] = []
        idx = 0
        while idx < len(want) or window:
            while idx < len(want) and len(window) < MIRROR_WINDOW:
                pid, sha = want[idx]
                window.append((pid, sha, client.submit("blob", sha)))
                idx += 1
            pid, sha, pending = window.pop(0)
            data = client.result(pending)
            if hashlib.sha256(data).hexdigest() != sha:
                raise ArtifactCorrupt(
                    f"mirror of {pid!r}: blob sha256-{sha[:12]}… "
                    "failed its content hash on receipt — refusing "
                    "the transfer"
                )
            dest_store._write_blob(data)
            fetched += 1
            nbytes += len(data)
        # Blobs are all down; NOW the manifests, bases first.
        for pid, manifest in chain:
            if dest_store.has(pid):
                continue
            path = dest_store._manifest_path(pid)
            data = json.dumps(manifest, sort_keys=True, indent=1).encode()
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(path), prefix=".tmp-"
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        return {
            "policies": [pid for pid, _ in chain],
            "blobs_fetched": fetched,
            "blobs_reused": reused,
            "bytes_fetched": nbytes,
        }
    finally:
        client.close()


def remote_store_factory(
    service_root: str,
    mirror_root: str,
    policy_ids=None,
    **kwargs,
):
    """Replica factory for a host that does NOT hold the artifact store:
    list (or take) the policy ids, mirror each — content-addressed,
    hash-verified, transitive bases included — into a LOCAL store under
    `mirror_root`, then serve from the mirror through the standard
    multi-policy store factory. Heavy work happens in the replica child,
    on purpose; a second replica on the same host reuses the mirror's
    blobs by content address."""
    from tensor2robot_tpu.export.artifact_store import ArtifactStore

    mirror = ArtifactStore(mirror_root)
    if policy_ids is None:
        client = _StoreClient(service_root)
        try:
            policy_ids = client.call("list")
        finally:
            client.close()
    for policy_id in policy_ids:
        mirror_policy(service_root, policy_id, mirror)
    return replica_lib.multi_policy_store_factory(
        mirror_root, policy_ids=list(policy_ids), **kwargs
    )


# -- per-host AOT resolution ---------------------------------------------------


def host_aot_report(
    export_root: str,
    topology: Optional[Mapping[str, Any]] = None,
) -> Dict[str, Any]:
    """How THIS host resolves the artifact's `aot/` executables.

    Header-only: each envelope is integrity-checked (magic/length/CRC)
    and its key compared against this host's platform/topology triple
    and jax version — the payload is NEVER unpickled here, so a
    transplanted or corrupt executable costs a typed row, not a crash.
    Per file: `status` is `aot` (this host restores from it),
    `topology` / `jax_version` / `key` (intact but keyed elsewhere —
    the restore ladder falls back, loudly), or `corrupt`. The summary
    is the per-host AOT key table docs/SERVING.md documents and the
    heterogeneity bench leg asserts."""
    from tensor2robot_tpu.export import aot as aot_lib

    if topology is None:
        topology = aot_lib.device_topology()
    topology = dict(topology)
    aot_dir = os.path.join(export_root, aot_lib.AOT_DIR)
    files: Dict[str, Dict[str, Any]] = {}
    counts = {"aot": 0, "topology": 0, "jax_version": 0, "key": 0,
              "corrupt": 0}
    names = []
    if os.path.isdir(aot_dir):
        names = sorted(
            n for n in os.listdir(aot_dir) if n.endswith(".bin")
        )
    for name in names:
        path = os.path.join(aot_dir, name)
        with open(path, "rb") as f:
            blob = f.read()
        entry: Dict[str, Any] = {}
        try:
            header, _payload = aot_lib._unpack(blob)
        except aot_lib.AOTCorrupt as err:
            entry = {"status": "corrupt", "detail": str(err)}
            files[name] = entry
            counts["corrupt"] += 1
            continue
        entry["header_topology"] = header.get("topology")
        import jax

        # Same check order as aot._check_key, so this report names the
        # SAME first reason the restore ladder's typed fallback will.
        if header.get("format_version") != aot_lib.AOT_FORMAT_VERSION:
            entry["status"] = "key"
            entry["detail"] = (
                f"format_version {header.get('format_version')} != "
                f"{aot_lib.AOT_FORMAT_VERSION}"
            )
        elif header.get("jax") != jax.__version__:
            entry["status"] = "jax_version"
            entry["detail"] = (
                f"serialized under jax {header.get('jax')}, host runs "
                f"{jax.__version__}"
            )
        elif dict(header.get("topology") or {}) != topology:
            entry["status"] = "topology"
            entry["detail"] = (
                f"lowered for {header.get('topology')}, this host is "
                f"{topology}"
            )
        else:
            entry["status"] = "aot"
        files[name] = entry
        counts[entry["status"]] += 1
    return {
        "host_topology": topology,
        "files": files,
        "counts": counts,
        # The one-line verdict placement logic keys on: does THIS host
        # restore every bucket from the executables, or none, or a mix
        # (a mix means a partially-regenerated aot/ dir — worth eyes).
        "all_aot": bool(names) and counts["aot"] == len(names),
    }


if __name__ == "__main__":  # pragma: no cover — subprocess entry
    raise SystemExit(_cli_main())
