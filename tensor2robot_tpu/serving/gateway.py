"""Gateway: the multi-tenant front door over FleetRouter pools.

Everything below the router is production-hardened (hedging, circuit
break, rolling swap, chaos), but a fleet serving millions of users is
not one client stream against one model — it is MANY tenants, each with
its own traffic shape, sharing replica pools that must stay saturated
without letting any one tenant brown out the rest. This module is that
control tier:

  * **Tenant bindings.** Each tenant binds to a pool (a `FleetRouter`
    fronting one artifact/quant-regime/bucket-ladder), a priority tier,
    and an admission quota. Many tenants share one pool; a gateway can
    front many pools.
  * **Admission quotas — token bucket per tenant.** Refill at
    `quota_rps` up to `burst` (`T2R_GATE_QUOTA_RPS`/`T2R_GATE_BURST`
    defaults); an over-quota submit fails synchronously with the typed
    `TenantThrottled` — cheap, counted, and BEFORE any queue or pool
    work, so a rogue tenant's excess costs the shared pool nothing.
  * **Priority tiers — strict-priority admission queue.** gold >
    silver > bronze. The dispatcher always serves the highest non-empty
    tier; when the bounded queue (`T2R_GATE_MAX_QUEUE`) overflows, the
    OLDEST entry of the LOWEST-priority tier is shed with the typed
    `TierShed` — bronze before gold; within a tier the oldest entry is
    shed so the freshest survive (the policy server's shed_oldest
    discipline generalized across tiers).
    Per-tier queue budgets bound how long a tier may wait before it is
    shed typed (`GateDeadline(reason='queue_budget')`): under overload
    bronze degrades into fast typed sheds instead of slow timeouts.
  * **Request coalescing.** Bitwise-identical observations against the
    same pool share ONE replica dispatch (`T2R_GATE_COALESCE`): the
    packed feature bytes are hashed (the exact-verified decode-cache
    discipline applied to inference), followers attach to the leader's
    future, and every rider receives the same outputs object —
    bitwise-equal responses by construction. A coalesce entry is never
    joinable across a model-version flip: `rolling_swap()` bumps the
    pool's swap epoch and entries from older epochs stop accepting
    riders, so no request is served by a dispatch from the wrong side
    of a publish.
  * **Deadline propagation.** The gateway deadline (submit override >
    binding default > `T2R_GATE_DEADLINE_MS`) is fixed at admission;
    the REMAINING budget rides into `FleetRouter.submit`, which ships
    the wall deadline to the replica, whose policy server drops
    expired entries at micro-batch formation. One deadline, enforced at
    every hop.
  * **Per-tenant circuit breaking.** A tenant whose ADMITTED requests
    keep failing (`T2R_GATE_CIRCUIT_THRESHOLD` consecutive — pool-side
    errors, queue sheds, and queue expiries all count; this is
    deliberate overload backpressure, converting a tenant's queue churn
    into cheap synchronous rejections) is suspended for a cooloff
    (`T2R_GATE_CIRCUIT_COOLOFF_MS`): admission rejects with the typed
    `TenantSuspended` instead of letting the tenant keep converting
    gateway and pool capacity into deadline misses. Throttles do not
    count — they are already free — and a coalesce RIDER's failure
    never counts against its tenant: only a leader's own traffic is
    evidence.

Chaos sites (testing/chaos.py): `admit` fires on every admission and
`coalesce` on every join attempt, both with the tenant's call-site
scope `t<i>` — so a plan can target ONE tenant inside the shared
gateway process (`t2/admit:3:raise`). A `drop` at `admit` sheds the
admission typed; a `drop` at `coalesce` bypasses the join (the request
dispatches individually). See docs/SERVING.md ("Multi-tenant gateway")
and docs/RESILIENCE.md (overload policy table).
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import logging
import threading

from tensor2robot_tpu.testing import locksmith
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.serving.metrics import percentile
from tensor2robot_tpu.serving.router import (
    FleetError,
    FleetRouter,
    ReplicaUnavailable,
    RequestAbandoned,
    RouterClosed,
)
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.backoff import Backoff

_log = logging.getLogger(__name__)

__all__ = [
    "Gateway",
    "TenantBinding",
    "GateFuture",
    "GateResponse",
    "GateError",
    "UnknownTenant",
    "TenantThrottled",
    "TenantSuspended",
    "TierShed",
    "GateDeadline",
    "GatewayClosed",
    "TIERS",
]

# Strict priority order: earlier tiers are served first and shed last.
TIERS: Tuple[str, ...] = ("gold", "silver", "bronze")
_TIER_RANK = {tier: rank for rank, tier in enumerate(TIERS)}


class GateError(RuntimeError):
    """Base class for gateway-level request failures. Deliberately not a
    FleetError subclass: admission failures never reached a pool, and
    the two layers' errors never mix in one except clause (pool-side
    failures resolve through the future carrying the router's own typed
    error)."""


class UnknownTenant(GateError):
    """No binding for this tenant name."""


class TenantThrottled(GateError):
    """The tenant's token bucket is empty: over-quota, shed at admission."""


class TenantSuspended(GateError):
    """The tenant's circuit is open after consecutive failures of its
    admitted requests (pool-side errors, queue sheds, queue expiries)."""


class TierShed(GateError):
    """Shed by the strict-priority overload policy (queue overflow or an
    injected admission drop). `tier` names the tier that was shed."""

    def __init__(self, message: str, tier: str):
        super().__init__(message)
        self.tier = tier


class GateDeadline(GateError):
    """The request expired while queued at the gateway. `reason` is
    'deadline' (end-to-end budget) or 'queue_budget' (per-tier bound)."""

    def __init__(self, message: str, reason: str):
        super().__init__(message)
        self.reason = reason


class GatewayClosed(GateError):
    """The gateway stopped before the request completed."""


@dataclasses.dataclass
class TenantBinding:
    """One tenant's contract with the gateway.

    `pool` keys into the gateway's router pools; `tier` is one of
    TIERS. `quota_rps`/`burst`/`deadline_ms` default (None) to the
    `T2R_GATE_*` flags. `scope` is the tenant's chaos call-site scope;
    unset, the gateway assigns `t<i>` by binding order.
    """

    tenant: str
    pool: str = "default"
    tier: str = "bronze"
    quota_rps: Optional[float] = None
    burst: Optional[int] = None
    deadline_ms: Optional[float] = None
    scope: Optional[str] = None


class GateResponse:
    """One request's outputs plus gateway-level provenance. Coalesced
    riders share the SAME `outputs` object as their leader — bitwise
    equality is structural, not re-verified."""

    __slots__ = (
        "outputs", "model_version", "spans", "tenant", "tier", "pool",
        "replica", "attempts", "hedged", "coalesced", "policy_id",
    )

    def __init__(self, outputs, model_version, spans, tenant, tier, pool,
                 replica, attempts, hedged, coalesced, policy_id=None):
        self.outputs = outputs
        self.model_version = model_version
        self.spans = spans
        self.tenant = tenant
        self.tier = tier
        self.pool = pool
        self.replica = replica
        self.attempts = attempts
        self.hedged = hedged
        self.coalesced = coalesced
        self.policy_id = policy_id


class GateFuture:
    """Completion handle for one gateway request; resolves exactly once,
    always (success, typed failure, or GatewayClosed at stop)."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._event = threading.Event()
        self._response: Optional[GateResponse] = None
        self._error: Optional[BaseException] = None
        self._callbacks: List = []
        self._cb_lock = locksmith.make_lock("GateFuture._cb_lock")

    def done(self) -> bool:
        return self._event.is_set()

    def error(self) -> Optional[BaseException]:
        return self._error if self._event.is_set() else None

    def result(self, timeout: Optional[float] = None) -> GateResponse:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"gateway request {self.request_id} still pending after "
                f"{timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._response

    def add_done_callback(self, fn) -> None:
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _set(self, response, error) -> None:
        with self._cb_lock:
            if self._event.is_set():
                return  # resolves exactly once; a loser cannot overwrite
            self._response, self._error = response, error
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(self)


class _GateRequest:
    __slots__ = (
        "id", "tenant", "features", "deadline", "queue_deadline", "future",
        "t_submit", "digest", "entry", "pool_retries", "policy_id",
    )

    def __init__(self, request_id, tenant, features, deadline,
                 queue_deadline, policy_id=None):
        self.id = request_id
        self.tenant = tenant
        self.features = features
        self.deadline = deadline  # monotonic, end-to-end
        self.queue_deadline = queue_deadline  # monotonic, tier budget
        self.future = GateFuture(request_id)
        self.t_submit = time.monotonic()
        self.digest: Optional[bytes] = None
        self.entry: Optional["_CoalesceEntry"] = None  # led by this request
        self.pool_retries = 0
        self.policy_id: Optional[str] = policy_id


class _Tenant:
    """Runtime state for one binding: token buckets + circuit + counters.

    Admission is keyed (tenant, policy_id): each policy stream a tenant
    names gets its OWN token bucket at the binding's rate/burst (key
    None is the unnamed/default stream, behaviorally identical to the
    pre-multi-policy gateway). One policy's burst therefore throttles
    that policy's stream, never the tenant's traffic to other policies.
    """

    __slots__ = (
        "binding", "scope", "tier", "burst", "rate", "buckets",
        "consecutive_failures", "suspended_until", "counters",
    )

    def __init__(self, binding: TenantBinding, scope: str, rate: float,
                 burst: float):
        self.binding = binding
        self.scope = scope
        self.tier = binding.tier
        self.rate = rate
        self.burst = burst
        # policy_id -> [tokens, last_refill]; fresh buckets may burst
        # immediately.
        self.buckets: Dict[Optional[str], List[float]] = {}
        self.consecutive_failures = 0
        self.suspended_until = 0.0
        self.counters: Dict[str, int] = {}

    def take_token(self, policy_id: Optional[str], now: float) -> bool:
        bucket = self.buckets.get(policy_id)
        if bucket is None:
            bucket = self.buckets[policy_id] = [self.burst, now]
        bucket[0] = min(
            self.burst, bucket[0] + (now - bucket[1]) * self.rate
        )
        bucket[1] = now
        if bucket[0] < 1.0:
            return False
        bucket[0] -= 1.0
        return True

    def tokens_now(self, policy_id: Optional[str], now: float) -> float:
        bucket = self.buckets.get(policy_id)
        if bucket is None:
            return self.burst
        return min(
            self.burst, bucket[0] + (now - bucket[1]) * self.rate
        )


class _CoalesceEntry:
    """One in-flight dispatch that identical observations may ride."""

    __slots__ = ("digest", "leader", "followers", "epoch", "resolved")

    def __init__(
        self, digest: bytes, leader: _GateRequest, epoch: Tuple[int, int]
    ):
        self.digest = digest
        self.leader = leader
        self.followers: List[_GateRequest] = []
        self.epoch = epoch
        self.resolved = False


class _Pool:
    """Per-pool dispatch state: strict-priority queues + coalesce map."""

    __slots__ = (
        "name", "router", "queues", "cond", "coalesce", "swap_epoch",
        "policy_epochs", "thread", "last_sweep", "model_fingerprint",
        "fingerprint_epoch", "counters",
    )

    def __init__(self, name: str, router: FleetRouter):
        self.name = name
        self.router = router
        # Per-pool admission/shed ledger (guarded by the gateway lock,
        # like the tenant ledgers): with pools standing for availability
        # ZONES, this is where "which zone shed how much, and where did
        # its load go" is answered — the global counters cannot.
        self.counters: Dict[str, int] = {}
        self.queues: Dict[str, deque] = {tier: deque() for tier in TIERS}
        self.cond = locksmith.make_condition("_Pool.cond")
        self.coalesce: Dict[bytes, _CoalesceEntry] = {}
        self.swap_epoch = 0
        # Per-policy publish epochs: rolling_swap(policy_id=...) bumps
        # ONE policy's epoch, fencing only that policy's coalesce
        # entries; the global swap_epoch fences everything.
        self.policy_epochs: Dict[str, int] = {}
        self.thread: Optional[threading.Thread] = None
        self.last_sweep = 0.0
        # Cached recorded artifact fingerprint (digest ingredient),
        # refreshed from the router snapshot when the swap epoch moves.
        self.model_fingerprint: Optional[str] = None
        self.fingerprint_epoch = -1

    def depth(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def epoch_key(self, policy_id: Optional[str]) -> Tuple[int, int]:
        """Called under self.cond: the fencing epoch a coalesce entry
        for `policy_id` is stamped with and compared against."""
        return (
            self.swap_epoch,
            self.policy_epochs.get(policy_id, 0) if policy_id else 0,
        )


def observation_digest(
    arrays: Mapping[str, np.ndarray],
    policy_id: Optional[str] = None,
    model_fingerprint: Optional[str] = None,
) -> bytes:
    """Content hash over the PACKED feature bytes (key, dtype, shape,
    buffer) PLUS the serving identity — two requests coalesce iff this
    matches, which is the bitwise-identical-observation contract.

    The identity fields are the fix for a real coalescing bug: hashing
    observations alone let two requests naming DIFFERENT policies (or
    arriving across an artifact republish with identical bytes) share
    one dispatch, silently serving tenant A's observation with tenant
    B's policy outputs. `policy_id` and `model_fingerprint` (the
    artifact's recorded AOT fingerprint, or the pool name when the
    backend records none) are domain-separated from the feature bytes
    so `{"a": 1}` under policy "x" can never collide with a crafted
    feature key."""
    h = hashlib.blake2b(digest_size=16)
    h.update(b"\x00policy\x00")
    h.update((policy_id or "").encode())
    h.update(b"\x00model\x00")
    h.update((model_fingerprint or "").encode())
    h.update(b"\x00features\x00")
    for key in sorted(arrays):
        value = arrays[key]
        h.update(key.encode())
        h.update(str(value.dtype).encode())
        h.update(str(value.shape).encode())
        h.update(np.ascontiguousarray(value).tobytes())
    return h.digest()


# Dispatcher tick: the upper bound on how stale an expiry sweep can be,
# and the idle wait quantum (a queued request never waits longer than
# this past its budget to resolve typed).
_SWEEP_INTERVAL_S = 0.025


class Gateway:
    """Multi-tenant admission control over one or more FleetRouter pools.

    `pools` is a mapping {name: started FleetRouter} (or one router,
    bound as "default"); the gateway does not own the routers unless
    stop(stop_pools=True). Constructor args override the `T2R_GATE_*`
    flag defaults (the PolicyServer convention). `tier_queue_budget_ms`
    bounds per-tier queue wait ({tier: ms}; absent/None = the request's
    own deadline). `seed` drives the saturation-backoff schedule —
    gateway pacing under a fixed fault plan is reproducible.
    """

    def __init__(
        self,
        pools,
        bindings: Sequence[TenantBinding],
        *,
        max_queue: Optional[int] = None,
        coalesce: Optional[bool] = None,
        default_deadline_ms: Optional[int] = None,
        quota_rps: Optional[float] = None,
        burst: Optional[int] = None,
        circuit_threshold: Optional[int] = None,
        circuit_cooloff_ms: Optional[float] = None,
        tier_queue_budget_ms: Optional[Mapping[str, float]] = None,
        dispatch_backoff_ms: float = 5.0,
        seed: int = 0,
    ):
        if isinstance(pools, FleetRouter):
            pools = {"default": pools}
        if not pools:
            raise ValueError("a gateway needs at least one pool")
        self._pools: Dict[str, _Pool] = {
            name: _Pool(name, router) for name, router in pools.items()
        }
        self._max_queue = (
            max_queue if max_queue is not None
            else t2r_flags.get_int("T2R_GATE_MAX_QUEUE")
        )
        self._coalesce_enabled = (
            coalesce if coalesce is not None
            else t2r_flags.get_bool("T2R_GATE_COALESCE")
        )
        self._default_deadline_s = (
            default_deadline_ms if default_deadline_ms is not None
            else t2r_flags.get_int("T2R_GATE_DEADLINE_MS")
        ) / 1e3
        default_rate = (
            quota_rps if quota_rps is not None
            else float(t2r_flags.get_int("T2R_GATE_QUOTA_RPS"))
        )
        default_burst = (
            burst if burst is not None
            else t2r_flags.get_int("T2R_GATE_BURST")
        )
        self._circuit_threshold = (
            circuit_threshold if circuit_threshold is not None
            else t2r_flags.get_int("T2R_GATE_CIRCUIT_THRESHOLD")
        )
        self._circuit_cooloff_s = (
            circuit_cooloff_ms if circuit_cooloff_ms is not None
            else t2r_flags.get_int("T2R_GATE_CIRCUIT_COOLOFF_MS")
        ) / 1e3
        self._tier_budget_s: Dict[str, Optional[float]] = {
            tier: None for tier in TIERS
        }
        for tier, budget_ms in (tier_queue_budget_ms or {}).items():
            if tier not in _TIER_RANK:
                raise ValueError(
                    f"unknown tier {tier!r} in tier_queue_budget_ms "
                    f"(tiers: {', '.join(TIERS)})"
                )
            self._tier_budget_s[tier] = (
                None if budget_ms is None else budget_ms / 1e3
            )
        self._dispatch_backoff_ms = dispatch_backoff_ms
        self._seed = seed

        # Reentrant: admission counts failures while holding the state
        # lock (the router's convention).
        self._lock = locksmith.make_rlock("Gateway._lock")
        self._tenants: Dict[str, _Tenant] = {}
        for i, binding in enumerate(bindings):
            if binding.tier not in _TIER_RANK:
                raise ValueError(
                    f"tenant {binding.tenant!r}: unknown tier "
                    f"{binding.tier!r} (tiers: {', '.join(TIERS)})"
                )
            if binding.pool not in self._pools:
                raise ValueError(
                    f"tenant {binding.tenant!r}: unknown pool "
                    f"{binding.pool!r} (pools: {', '.join(self._pools)})"
                )
            if binding.tenant in self._tenants:
                raise ValueError(
                    f"tenant {binding.tenant!r} bound twice"
                )
            self._tenants[binding.tenant] = _Tenant(
                binding,
                scope=binding.scope if binding.scope else f"t{i}",
                rate=(
                    binding.quota_rps if binding.quota_rps is not None
                    else default_rate
                ),
                burst=float(
                    binding.burst if binding.burst is not None
                    else default_burst
                ),
            )
        if not self._tenants:
            raise ValueError("a gateway needs at least one tenant binding")

        self._counters: Dict[str, int] = {}
        self._latencies: deque = deque(maxlen=4096)
        self._ids = itertools.count(1)
        self._started = False
        self._closed = False

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Gateway":
        if self._started:
            raise RuntimeError("Gateway.start() called twice")
        self._started = True
        for pool in self._pools.values():
            pool.thread = threading.Thread(
                target=self._dispatch_loop,
                args=(pool,),
                name=f"t2r-gate-dispatch-{pool.name}",
                daemon=True,
            )
            pool.thread.start()
        return self

    def stop(self, stop_pools: bool = False, timeout_s: float = 10.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for pool in self._pools.values():
            orphans: List[_GateRequest] = []
            with pool.cond:
                for q in pool.queues.values():
                    orphans.extend(q)
                    q.clear()
                # Riders of entries still in the map (their leaders may
                # be in flight); queued leaders' riders — including
                # riders of SHADOWED entries no longer in the map — are
                # taken via _take_fanout below, off request.entry.
                for entry in pool.coalesce.values():
                    if not entry.resolved:
                        orphans.extend(entry.followers)
                        entry.followers = []
                pool.coalesce.clear()
                pool.cond.notify_all()
            error = GatewayClosed("gateway stopped with request queued")
            for request in orphans:
                for member in [request] + self._take_fanout(pool, request):
                    member.future._set(None, error)
        for pool in self._pools.values():
            if pool.thread is not None:
                pool.thread.join(timeout=timeout_s)
        if stop_pools:
            for pool in self._pools.values():
                pool.router.stop()

    def __enter__(self) -> "Gateway":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission ------------------------------------------------------------

    def submit(
        self,
        tenant: str,
        features: Mapping[str, Any],
        deadline_ms: Optional[float] = None,
        policy_id: Optional[str] = None,
    ) -> GateFuture:
        """Admits one request for `tenant`. Typed admission failures
        (UnknownTenant / TenantSuspended / TenantThrottled / TierShed /
        GatewayClosed) raise synchronously; everything after admission
        resolves through the returned future, exactly once, always.
        `policy_id` names the policy on a multi-policy pool: admission
        meters the (tenant, policy) stream, the coalescing key folds the
        policy in (identical observations against different policies
        never share a dispatch), and the router places the request
        policy-aware."""
        if not self._started or self._closed:
            raise GatewayClosed("gateway is not running")
        state = self._tenants.get(tenant)
        if state is None:
            raise UnknownTenant(
                f"no binding for tenant {tenant!r} "
                f"(known: {sorted(self._tenants)})"
            )
        self._count("submitted")
        self._tcount(state, "submitted")
        # Chaos admission site, scoped to THIS tenant (t<i>): `raise`/
        # `flake` propagate as injected admission faults; `drop` sheds
        # the admission typed; `delay` models a slow front door.
        fault = chaos.maybe_fire("admit", scope=state.scope)
        if fault is not None and fault.action in ("drop", "corrupt"):
            self._count("chaos_admit_drops")
            self._tcount(state, "shed")
            raise TierShed(
                f"tenant {tenant!r} admission dropped by chaos plan "
                f"({fault.describe()})",
                tier=state.tier,
            )
        now = time.monotonic()
        with self._lock:
            if now < state.suspended_until:
                self._count("suspended")
                self._tcount(state, "suspended")
                raise TenantSuspended(
                    f"tenant {tenant!r} circuit open for another "
                    f"{(state.suspended_until - now) * 1e3:.0f}ms after "
                    f"{state.consecutive_failures} consecutive failures"
                )
            # Token bucket: continuous refill, one token per admission,
            # metered per (tenant, policy) stream.
            if not state.take_token(policy_id, now):
                self._count("throttled")
                self._tcount(state, "throttled")
                stream = f" (policy {policy_id!r})" if policy_id else ""
                raise TenantThrottled(
                    f"tenant {tenant!r}{stream} over quota "
                    f"({state.rate:g} req/s, burst {state.burst:g})"
                )
        arrays = {k: np.asarray(v) for k, v in features.items()}
        deadline = now + (
            deadline_ms / 1e3 if deadline_ms is not None
            else (
                state.binding.deadline_ms / 1e3
                if state.binding.deadline_ms is not None
                else self._default_deadline_s
            )
        )
        budget = self._tier_budget_s.get(state.tier)
        queue_deadline = deadline if budget is None else min(
            deadline, now + budget
        )
        request = _GateRequest(
            next(self._ids), state, arrays, deadline, queue_deadline,
            policy_id,
        )
        pool = self._pools[state.binding.pool]
        if self._coalesce_enabled:
            request.digest = observation_digest(
                arrays,
                policy_id=policy_id,
                model_fingerprint=self._pool_fingerprint(pool),
            )
            if self._try_join(pool, request):
                return request.future
        self._enqueue(pool, request)
        return request.future

    def call(
        self,
        tenant: str,
        features: Mapping[str, Any],
        deadline_ms: Optional[float] = None,
        timeout: Optional[float] = None,
        policy_id: Optional[str] = None,
    ) -> GateResponse:
        future = self.submit(
            tenant, features, deadline_ms=deadline_ms, policy_id=policy_id
        )
        if timeout is None:
            timeout = (
                deadline_ms / 1e3 if deadline_ms is not None
                else self._default_deadline_s
            ) + 30.0
        return future.result(timeout)

    def _pool_fingerprint(self, pool: _Pool) -> str:
        """Digest ingredient: the pool's recorded artifact fingerprint,
        cached per swap epoch (a publish may change the artifact, so the
        cache refreshes off the router snapshot after every epoch bump);
        the pool NAME is the fallback identity when no replica records a
        fingerprint (mock backends) — distinct pools still never share a
        coalescing keyspace."""
        with pool.cond:
            epoch = pool.swap_epoch
            if pool.fingerprint_epoch == epoch:
                return pool.model_fingerprint
        fingerprint = None
        try:
            for rep in pool.router.snapshot().get("replicas", ()):
                fingerprint = rep.get("model_fingerprint")
                if fingerprint:
                    break
        except Exception:
            fingerprint = None
        fingerprint = str(fingerprint) if fingerprint else f"pool:{pool.name}"
        with pool.cond:
            pool.model_fingerprint = fingerprint
            pool.fingerprint_epoch = epoch
        return fingerprint

    def _joinable(self, pool: _Pool, request: _GateRequest) -> bool:
        """Called under pool.cond. Joinable = same digest (which folds
        policy_id and artifact fingerprint), same epoch key — global
        swap epoch AND the policy's own publish epoch, so neither a
        fleet-wide publish nor this policy's rolling swap lets a rider
        cross a version flip — not yet resolved, AND the leader must not
        drag the rider down: a rider never joins a LOWER-priority leader
        (whose shed/starvation fate it would inherit — priority
        inversion), and never a leader whose deadline outlives its own
        (the dispatch carries the LEADER's budget, so the rider would be
        served past its deadline)."""
        entry = pool.coalesce.get(request.digest)
        return (
            entry is not None
            and not entry.resolved
            and entry.epoch == pool.epoch_key(request.policy_id)
            and _TIER_RANK[entry.leader.tenant.tier]
            <= _TIER_RANK[request.tenant.tier]
            and entry.leader.deadline <= request.deadline
        )

    def _try_join(self, pool: _Pool, request: _GateRequest) -> bool:
        """Rides an open coalesce entry for an identical observation
        (see _joinable for the exact contract)."""
        with pool.cond:
            joinable = self._joinable(pool, request)
        if not joinable:
            return False
        # The chaos hook may sleep; fire it outside the pool lock and
        # re-verify the entry afterwards (the leader may have resolved
        # or a swap may have flipped the epoch mid-hook).
        fault = chaos.maybe_fire("coalesce", scope=request.tenant.scope)
        if fault is not None and fault.action in ("drop", "corrupt"):
            self._count("chaos_coalesce_bypass")
            return False
        with pool.cond:
            if not self._joinable(pool, request):
                return False
            pool.coalesce[request.digest].followers.append(request)
        self._count("coalesced_joins")
        self._tcount(request.tenant, "coalesced")
        return True

    def _enqueue(self, pool: _Pool, request: _GateRequest) -> None:
        tier = request.tenant.tier
        victim: Optional[_GateRequest] = None
        with pool.cond:
            if pool.depth() >= self._max_queue:
                victim = self._pick_shed_victim(pool, tier)
                if victim is None:
                    # Every queued entry outranks the incoming tier:
                    # reject the newcomer, never a higher tier.
                    self._count("shed_queue")
                    self._count(f"shed_queue_{tier}")
                    self._tcount(request.tenant, "shed")
                    self._pcount(pool, "shed")
                    raise TierShed(
                        f"gateway queue full ({self._max_queue}) with no "
                        f"{tier}-or-lower entry to shed; request rejected",
                        tier=tier,
                    )
            if self._coalesce_enabled and request.digest is not None:
                request.entry = _CoalesceEntry(
                    request.digest, request,
                    pool.epoch_key(request.policy_id),
                )
                # May shadow a stale (older-epoch / chaos-bypassed)
                # entry; that entry stays reachable through ITS leader's
                # request.entry, so its riders still resolve with it.
                pool.coalesce[request.digest] = request.entry
            pool.queues[tier].append(request)
            self._count("admitted")
            self._tcount(request.tenant, "admitted")
            self._pcount(pool, "admitted")
            pool.cond.notify()
        if victim is not None:
            self._resolve_shed(pool, victim)

    def _pick_shed_victim(
        self, pool: _Pool, incoming_tier: str
    ) -> Optional[_GateRequest]:
        """Oldest entry of the lowest-priority non-empty tier, provided
        the incoming tier does not rank below it (called under the pool
        cond)."""
        for tier in reversed(TIERS):
            q = pool.queues[tier]
            if not q:
                continue
            if _TIER_RANK[incoming_tier] > _TIER_RANK[tier]:
                return None
            return q.popleft()
        return None

    def _resolve_shed(self, pool: _Pool, victim: _GateRequest) -> None:
        tier = victim.tenant.tier
        self._count("shed_queue")
        self._count(f"shed_queue_{tier}")
        self._tcount(victim.tenant, "shed")
        self._pcount(pool, "shed")
        error = TierShed(
            f"request {victim.id} ({tier}) shed by the strict-priority "
            "overload policy",
            tier=tier,
        )
        self._resolve_failure(pool, victim, error, count_circuit=True)

    # -- dispatch -------------------------------------------------------------

    def _dispatch_loop(self, pool: _Pool) -> None:
        """Pops the highest-priority live request and hands it to the
        pool's router with its REMAINING deadline. Saturation backs off
        on the seeded schedule (strict priority means nothing else
        could dispatch either); expired entries across all tiers are
        swept typed at least every _SWEEP_INTERVAL_S."""
        backoff = Backoff(
            base_ms=self._dispatch_backoff_ms, cap_ms=100.0,
            seed=self._seed ^ zlib.crc32(pool.name.encode()),
        )
        saturated_attempts = 0
        while True:
            expired: List[Tuple[_GateRequest, str]] = []
            request: Optional[_GateRequest] = None
            with pool.cond:
                while True:
                    if self._closed:
                        # A request this thread held in hand during
                        # stop() may have been requeued AFTER stop's
                        # drain; sweep the leftovers — fanning out each
                        # one's coalesce riders too — so every future
                        # still resolves (GateFuture is resolve-once,
                        # so double-draining is harmless).
                        leftovers = [
                            r for q in pool.queues.values() for r in q
                        ]
                        for q in pool.queues.values():
                            q.clear()
                        closed_err = GatewayClosed(
                            "gateway stopped with request queued"
                        )
                        for r in leftovers:
                            for member in [r] + self._take_fanout(pool, r):
                                member.future._set(None, closed_err)
                        return
                    now = time.monotonic()
                    if now - pool.last_sweep >= _SWEEP_INTERVAL_S:
                        pool.last_sweep = now
                        expired = self._sweep_expired_locked(pool, now)
                        if expired:
                            break
                    request = self._pop_live_locked(pool, now, expired)
                    if request is not None or expired:
                        break
                    pool.cond.wait(timeout=_SWEEP_INTERVAL_S)
            for victim, reason in expired:
                self._resolve_expired(pool, victim, reason)
            if request is None:
                continue
            remaining_ms = (request.deadline - time.monotonic()) * 1e3
            try:
                router_future = pool.router.submit(
                    request.features,
                    deadline_ms=remaining_ms,
                    policy_id=request.policy_id,
                )
            except RouterClosed:
                self._resolve_failure(
                    pool, request,
                    GatewayClosed(
                        f"pool {pool.name!r} router closed under request "
                        f"{request.id}"
                    ),
                    # A closing router is infrastructure, not tenant
                    # behavior: don't feed the circuit for it.
                    count_circuit=False,
                )
                continue
            except FleetError as err:
                # No replica at all is a ZONE verdict, not congestion:
                # when a fingerprint-equal sibling pool has capacity,
                # move the request there NOW (a partitioned/dead home
                # zone would otherwise spin it in place until its
                # deadline) — same interchangeability gate and counters
                # as the post-dispatch blip retry below.
                if (
                    isinstance(err, ReplicaUnavailable)
                    and not self._closed
                    and request.pool_retries < self._MAX_POOL_RETRIES
                    and time.monotonic()
                    < min(request.deadline, request.queue_deadline)
                ):
                    target = self._failover_pool(pool, request)
                    if target is not pool and self._requeue(
                        pool, target, request
                    ):
                        continue
                # Saturated / no replica anywhere: requeue at the FRONT
                # of its tier (order preserved) and back off on the
                # seeded schedule — strict priority means nothing else
                # queued could dispatch either. The sweep keeps
                # resolving expiries while we wait.
                saturated_attempts += 1
                self._count("dispatch_saturated")
                with pool.cond:
                    pool.queues[request.tenant.tier].appendleft(request)
                    delay = backoff.delay_s(min(saturated_attempts, 6))
                    pool.cond.wait(timeout=min(delay, _SWEEP_INTERVAL_S * 4))
                continue
            saturated_attempts = 0
            self._count("dispatched")
            self._pcount(pool, "dispatched")
            router_future.add_done_callback(
                lambda rf, pool=pool, request=request:
                self._on_pool_done(pool, request, rf)
            )

    def _pop_live_locked(
        self, pool: _Pool, now: float,
        expired: List[Tuple[_GateRequest, str]],
    ) -> Optional[_GateRequest]:
        """Highest-priority non-expired head (expired heads are shunted
        to the expiry list typed, never dispatched)."""
        for tier in TIERS:
            q = pool.queues[tier]
            while q:
                request = q.popleft()
                if now >= request.deadline:
                    expired.append((request, "deadline"))
                    continue
                if now >= request.queue_deadline:
                    expired.append((request, "queue_budget"))
                    continue
                return request
        return None

    def _sweep_expired_locked(
        self, pool: _Pool, now: float
    ) -> List[Tuple[_GateRequest, str]]:
        """Removes every expired entry from every tier queue (called
        under the pool cond; resolution happens outside it). Without
        this, a bronze request starved by strict priority would only
        resolve when popped — potentially never under sustained gold
        load."""
        expired: List[Tuple[_GateRequest, str]] = []
        for tier in TIERS:
            q = pool.queues[tier]
            if not q:
                continue
            survivors = deque()
            for request in q:
                if now >= request.deadline:
                    expired.append((request, "deadline"))
                elif now >= request.queue_deadline:
                    expired.append((request, "queue_budget"))
                else:
                    survivors.append(request)
            pool.queues[tier] = survivors
        return expired

    def _resolve_expired(
        self, pool: _Pool, request: _GateRequest, reason: str
    ) -> None:
        tier = request.tenant.tier
        self._count("expired_in_queue")
        self._count(f"expired_in_queue_{tier}")
        self._tcount(request.tenant, "shed")
        self._pcount(pool, "expired")
        waited_ms = (time.monotonic() - request.t_submit) * 1e3
        self._resolve_failure(
            pool, request,
            GateDeadline(
                f"request {request.id} ({tier}) expired in the gateway "
                f"queue after {waited_ms:.0f}ms ({reason})",
                reason=reason,
            ),
            count_circuit=True,
        )

    # -- completion -----------------------------------------------------------

    def _take_fanout(
        self, pool: _Pool, request: _GateRequest
    ) -> List[_GateRequest]:
        """Atomically closes the entry this request leads and returns
        its riders (empty for non-leaders). Works off request.entry, not
        the map alone: a shadowed (stale-epoch) entry must still fan its
        riders out when its own leader resolves."""
        entry = request.entry
        if entry is None:
            return []
        with pool.cond:
            entry.resolved = True
            if pool.coalesce.get(entry.digest) is entry:
                del pool.coalesce[entry.digest]
            followers, entry.followers = entry.followers, []
            return followers

    # A pool-side abandonment that is congestion, not a verdict: the
    # router exhausted ITS budget (retries against a dying/saturated
    # pool), but the request still holds end-to-end deadline — the
    # front door re-queues it (front of its tier) and lets capacity
    # recover (respawn, scale-up) instead of surfacing a kill-window
    # blip to a gold tenant. Bounded per request; 'deadline' reasons are
    # final.
    _MAX_POOL_RETRIES = 3

    def _retryable(self, request: _GateRequest, error: BaseException) -> bool:
        if self._closed or request.pool_retries >= self._MAX_POOL_RETRIES:
            return False
        if time.monotonic() >= min(request.deadline, request.queue_deadline):
            return False
        return (
            isinstance(error, RequestAbandoned)
            and error.reason != "deadline"
        )

    def _failover_pool(self, pool: _Pool, request: _GateRequest) -> _Pool:
        """Where a pool-side blip retry should land: a DIFFERENT pool
        serving the SAME recorded artifact (fingerprint equality is the
        interchangeability proof — zones of one deployment match, pools
        serving different models never do), least-utilized first. Falls
        back to the failed pool itself when no sibling qualifies — the
        single-pool behavior, unchanged."""
        if len(self._pools) < 2:
            return pool
        own = self._pool_fingerprint(pool)
        best, best_util = pool, None
        for other in self._pools.values():
            if other is pool or self._pool_fingerprint(other) != own:
                continue
            try:
                load = other.router.load()
            except Exception:
                continue
            if load["replicas_up"] < 1:
                continue
            if best_util is None or load["utilization"] < best_util:
                best, best_util = other, load["utilization"]
        return best

    def _requeue(self, pool: _Pool, target: _Pool,
                 request: _GateRequest) -> bool:
        """Requeues `request` at the front of its tier on `target`
        (possibly `pool` itself), counting the move. Returns False when
        the gateway closed first — nothing was queued."""
        if target is not pool and request.entry is not None:
            # Moving zones: seal this request's coalesce entry in the
            # OLD pool under the OLD pool's cond, so no new rider can
            # join after the move (its existing riders stay attached
            # through request.entry and fan out with the final
            # resolution, wherever it lands).
            with pool.cond:
                request.entry.resolved = True
                if pool.coalesce.get(
                    request.entry.digest
                ) is request.entry:
                    del pool.coalesce[request.entry.digest]
        # The closed re-check rides INSIDE the pool cond: stop() flips
        # _closed before it drains the queues under this same cond, so
        # a requeue that observed _closed False here is guaranteed to
        # be swept by stop's drain — it can never strand a future in a
        # queue nobody reads.
        requeued = False
        with target.cond:
            if not self._closed:
                request.pool_retries += 1
                target.queues[request.tenant.tier].appendleft(request)
                target.cond.notify()
                requeued = True
        if requeued:
            self._count("pool_retries")
            self._tcount(request.tenant, "pool_retries")
            if target is not pool:
                self._count("cross_pool_retries")
                self._pcount(pool, "retried_away")
                self._pcount(target, "retried_in")
        return requeued

    def _on_pool_done(self, pool: _Pool, request: _GateRequest, rf) -> None:
        error = rf.error()
        if error is not None:
            if self._retryable(request, error):
                target = self._failover_pool(pool, request)
                if self._requeue(pool, target, request):
                    return
            self._pcount(pool, "failed")
            self._resolve_failure(pool, request, error, count_circuit=True)
            return
        response = rf.result(0)
        self._pcount(pool, "completed")
        riders = self._take_fanout(pool, request)
        now = time.monotonic()
        for member, coalesced in [(request, False)] + [
            (r, True) for r in riders
        ]:
            state = member.tenant
            with self._lock:
                state.consecutive_failures = 0
                self._latencies.append((now - member.t_submit) * 1e3)
            self._count("completed")
            self._tcount(state, "completed")
            if coalesced:
                self._count("coalesced_served")
            spans = dict(response.spans)
            spans["gateway_ms"] = (now - member.t_submit) * 1e3
            member.future._set(
                GateResponse(
                    response.outputs, response.model_version, spans,
                    state.binding.tenant, state.tier, pool.name,
                    response.replica, response.attempts, response.hedged,
                    coalesced, member.policy_id,
                ),
                None,
            )

    def _resolve_failure(
        self, pool: _Pool, request: _GateRequest, error: BaseException,
        count_circuit: bool,
    ) -> None:
        """Fails a request (and any coalesce riders) typed. When
        `count_circuit`, the failure feeds the LEADER tenant's circuit
        breaker — every post-admission failure counts (pool-side error,
        queue shed, queue expiry): deliberate overload backpressure."""
        riders = self._take_fanout(pool, request)
        for member in [request] + riders:
            state = member.tenant
            self._count("failed")
            self._count(f"failed_{type(error).__name__}")
            self._tcount(state, "failed")
            # Only the LEADER's tenant feeds the circuit breaker: a
            # rider failing because of its leader's fate is not
            # evidence about the rider's own traffic.
            if count_circuit and member is request:
                self._note_tenant_failure(state)
            member.future._set(None, error)

    def _note_tenant_failure(self, state: _Tenant) -> None:
        with self._lock:
            state.consecutive_failures += 1
            if (
                state.consecutive_failures >= self._circuit_threshold
                and time.monotonic() >= state.suspended_until
            ):
                state.suspended_until = (
                    time.monotonic() + self._circuit_cooloff_s
                )
                state.consecutive_failures = 0
                self._count("circuit_opens")
                self._tcount(state, "circuit_opens")
                _log.warning(
                    "tenant %r circuit opened for %.0fms",
                    state.binding.tenant, self._circuit_cooloff_s * 1e3,
                )

    # -- fleet operations -----------------------------------------------------

    def rolling_swap(
        self,
        pool: str = "default",
        swap_timeout_s: float = 60.0,
        policy_id: Optional[str] = None,
    ) -> Dict:
        """Publishes the newest export through `pool` via the router's
        zero-downtime roll. The fencing epoch bumps FIRST, so no request
        admitted after the publish began can ride a dispatch from before
        it (the coalesce version-flip guard).

        With `policy_id`, the roll is scoped to ONE policy on a
        multi-policy pool: only that policy's publish epoch bumps (its
        coalesce entries are fenced; every other policy's entries keep
        accepting riders) and only that policy's server swaps per
        replica — one policy's publish never blips another policy's
        traffic."""
        state = self._pools[pool]
        with state.cond:
            if policy_id is None:
                state.swap_epoch += 1
            else:
                state.policy_epochs[policy_id] = (
                    state.policy_epochs.get(policy_id, 0) + 1
                )
        self._count("rolling_swaps")
        return state.router.rolling_swap(
            swap_timeout_s=swap_timeout_s, policy_id=policy_id
        )

    # -- introspection --------------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def _tcount(self, state: _Tenant, name: str, n: int = 1) -> None:
        with self._lock:
            state.counters[name] = state.counters.get(name, 0) + n

    def _pcount(self, pool: _Pool, name: str, n: int = 1) -> None:
        with self._lock:
            pool.counters[name] = pool.counters.get(name, 0) + n

    def tenant_scope(self, tenant: str) -> str:
        """The chaos call-site scope (`t<i>`) assigned to a tenant."""
        return self._tenants[tenant].scope

    def snapshot(self) -> Dict:
        now = time.monotonic()
        with self._lock:
            counters = dict(self._counters)
            latencies = sorted(self._latencies)
            tenants = {
                name: {
                    "tier": state.tier,
                    "scope": state.scope,
                    "quota_rps": state.rate,
                    "burst": state.burst,
                    # Effective tokens NOW (refill is lazy at admission;
                    # reporting the stored value would show a bucket
                    # frozen at its last submit). The unnamed key is the
                    # default stream — single-policy traffic reads as it
                    # always did.
                    "tokens": round(state.tokens_now(None, now), 3),
                    "policy_tokens": {
                        pid: round(state.tokens_now(pid, now), 3)
                        for pid in state.buckets
                        if pid is not None
                    },
                    "circuit_open": time.monotonic() < state.suspended_until,
                    "counters": dict(state.counters),
                }
                for name, state in self._tenants.items()
            }
        pools = {}
        for name, pool in self._pools.items():
            with pool.cond:
                pools[name] = {
                    "queue_depth": {
                        tier: len(q) for tier, q in pool.queues.items()
                    },
                    "coalesce_open": len(pool.coalesce),
                    "swap_epoch": pool.swap_epoch,
                    "policy_epochs": dict(pool.policy_epochs),
                    "model_fingerprint": pool.model_fingerprint,
                }
            with self._lock:
                # Per-pool (= per availability zone) admission ledger:
                # admitted/dispatched/completed/shed/expired/failed and
                # the retried_away/retried_in pair that shows where a
                # partitioned zone's load went.
                pools[name]["counters"] = dict(pool.counters)
        return {
            "counters": counters,
            "latency_ms": {
                "p50": round(percentile(latencies, 0.50), 3),
                "p99": round(percentile(latencies, 0.99), 3),
                "window": len(latencies),
            },
            "tenants": tenants,
            "pools": pools,
            "policy": {
                "max_queue": self._max_queue,
                "coalesce": self._coalesce_enabled,
                "default_deadline_ms": self._default_deadline_s * 1e3,
                "circuit_threshold": self._circuit_threshold,
                "circuit_cooloff_ms": self._circuit_cooloff_s * 1e3,
                "tier_queue_budget_ms": {
                    tier: (None if s is None else s * 1e3)
                    for tier, s in self._tier_budget_s.items()
                },
                "tiers": list(TIERS),
            },
        }
