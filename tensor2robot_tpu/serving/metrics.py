"""Per-request observability for the policy server.

Every request carries a span record through its lifecycle
(enqueue -> dispatch -> compute -> reply); the server aggregates them
into a structured snapshot cheap enough to poll at 1 Hz from a fleet
monitor: monotonic counters (admitted/completed/shed/rejected/
deadline-missed/hot-swaps), gauges (queue depth), latency percentiles
over a bounded ring of recent spans, and the batch-fill ratio — the
fraction of dispatched batch slots that carried real requests, THE
number that says whether micro-batching is earning its latency cost.
"""

from __future__ import annotations

import threading

from tensor2robot_tpu.testing import locksmith
from collections import deque
from typing import Dict, List, Optional

__all__ = ["RequestSpan", "ServerMetrics", "percentile"]


class RequestSpan:
    """Monotonic timestamps for one request's hops (seconds). Unset hops
    stay None (e.g. a shed request never dispatches)."""

    __slots__ = ("t_enqueue", "t_dispatch", "t_compute_done", "t_reply")

    def __init__(self, t_enqueue: float):
        self.t_enqueue = t_enqueue
        self.t_dispatch: Optional[float] = None
        self.t_compute_done: Optional[float] = None
        self.t_reply: Optional[float] = None

    def as_millis(self) -> Dict[str, float]:
        """queue/compute/reply/total durations in ms (None-safe)."""
        out: Dict[str, float] = {}
        if self.t_dispatch is not None:
            out["queue_ms"] = (self.t_dispatch - self.t_enqueue) * 1e3
        if self.t_compute_done is not None and self.t_dispatch is not None:
            out["compute_ms"] = (self.t_compute_done - self.t_dispatch) * 1e3
        if self.t_reply is not None and self.t_compute_done is not None:
            out["reply_ms"] = (self.t_reply - self.t_compute_done) * 1e3
        if self.t_reply is not None:
            out["total_ms"] = (self.t_reply - self.t_enqueue) * 1e3
        return out


def percentile(sorted_values: List[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list (0 when empty).
    The single definition for both the server snapshot and the bench
    legs, so their numbers are computed identically."""
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1, int(round(q * (len(sorted_values) - 1))))
    return sorted_values[idx]


class ServerMetrics:
    """Thread-safe aggregate; all mutators are O(1)."""

    def __init__(self, span_window: int = 2048):
        self._lock = locksmith.make_lock("ServerMetrics._lock")
        self._spans: deque = deque(maxlen=span_window)
        self._counters = {
            "admitted": 0,
            "completed": 0,
            "failed": 0,
            "shed": 0,
            "rejected": 0,
            "deadline_missed": 0,
            "deadline_dropped": 0,
            "hot_swaps": 0,
            "batches": 0,
            # AOT restore accounting: buckets served from a deserialized
            # executable vs buckets that fell back to a compile tier
            # while AOT was requested — accumulated across the boot and
            # every hot-swap, so a fleet can see a deploy that silently
            # started paying compiles again.
            "aot_hits": 0,
            "aot_misses": 0,
        }
        self._batch_slots = 0
        self._batch_real = 0
        self._per_bucket: Dict[int, int] = {}
        self._failed_by_class: Dict[str, int] = {}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def count_failure(self, failure_class: str, n: int = 1) -> None:
        """Increments the failed counter AND its per-class attribution in
        one lock acquisition. The class breakdown is what tells an
        operator whether a red `failed` counter is a predictor crash, a
        compute watchdog, or a structural dispatch bug — aggregated
        `failed` alone cannot distinguish an outage from an overload."""
        with self._lock:
            self._counters["failed"] += n
            self._failed_by_class[failure_class] = (
                self._failed_by_class.get(failure_class, 0) + n
            )

    def observe_batch(self, bucket: int, real: int) -> None:
        with self._lock:
            self._counters["batches"] += 1
            self._batch_slots += bucket
            self._batch_real += real
            self._per_bucket[bucket] = self._per_bucket.get(bucket, 0) + 1

    def observe_replies(self, spans: List[Dict[str, float]]) -> None:
        """Records a served batch's reply spans AND its completed count
        in one lock acquisition — the only way replies are recorded, so
        the latency window and the completed counter cannot drift."""
        with self._lock:
            self._spans.extend(spans)
            self._counters["completed"] += len(spans)

    def snapshot(self, queue_depth: int = 0) -> Dict:
        with self._lock:
            counters = dict(self._counters)
            spans = list(self._spans)
            slots, real = self._batch_slots, self._batch_real
            per_bucket = dict(self._per_bucket)
            failed_by_class = dict(self._failed_by_class)
        totals = sorted(s["total_ms"] for s in spans)
        queues = sorted(s.get("queue_ms", 0.0) for s in spans)
        computes = sorted(s.get("compute_ms", 0.0) for s in spans)
        return {
            "counters": counters,
            "failed_by_class": failed_by_class,
            "queue_depth": queue_depth,
            "batch_fill_ratio": (real / slots) if slots else 0.0,
            "batches_by_bucket": {str(k): v for k, v in sorted(per_bucket.items())},
            "latency_ms": {
                "p50_total": round(percentile(totals, 0.50), 3),
                "p99_total": round(percentile(totals, 0.99), 3),
                "p50_queue": round(percentile(queues, 0.50), 3),
                "p99_queue": round(percentile(queues, 0.99), 3),
                "p50_compute": round(percentile(computes, 0.50), 3),
                "p99_compute": round(percentile(computes, 0.99), 3),
                "window": len(spans),
            },
        }
