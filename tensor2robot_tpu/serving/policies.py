"""Multi-policy replica backend: a resident set of policy servers.

One replica process, N policies (ROADMAP item 2): the replica hosts a
RESIDENT SET of started policy servers keyed by policy id — the base
artifact's payload is shared through the content-addressed store
(export/artifact_store.py) and each sibling materializes from its delta
payload on load. Requests name their policy (`submit(policy_id=...)`);
a miss takes the COLD-LOAD path (counted) or a typed refusal, and the
resident set stays under a MEMORY BUDGET by evicting the
least-recently-used idle policy (counted, typed `PolicyEvicted` on
later use when cold loads are off).

This module is deliberately jax-free (the replica.py discipline): the
heavy stack loads inside the `loader` callable, which is the backend
seam — the production loader materializes an export dir from the store
and boots a PolicyServer with the shared bucket ladder
(server.exported_policy_loader); the mock loader builds a
policy-parameterized `_MockServer` in microseconds.

Flags (flags.py): `T2R_POLICY_MEM_BUDGET` (MB, 0 = unbounded),
`T2R_POLICY_MAX_RESIDENT` (count, 0 = unbounded),
`T2R_POLICY_COLD_LOAD` (0 = misses refuse typed instead of loading).
"""

from __future__ import annotations

import collections
import threading

from tensor2robot_tpu.testing import locksmith
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from tensor2robot_tpu import flags
from tensor2robot_tpu.utils.errors import best_effort

__all__ = [
    "MultiPolicyServer",
    "PolicyError",
    "PolicyUnknown",
    "PolicyEvicted",
    "PolicyLoadFailed",
]


class PolicyError(RuntimeError):
    """Base class for multi-policy residency failures."""


class PolicyUnknown(PolicyError):
    """The policy id is not in this replica's catalog (or is not
    resident while cold loads are disabled and it was never evicted)."""


class PolicyEvicted(PolicyError):
    """The policy WAS resident, was evicted under the memory budget,
    and cold loads are disabled — the placement layer must route this
    request to a replica where the policy is still resident."""


class PolicyLoadFailed(PolicyError):
    """The backend loader raised: the policy exists in the catalog but
    could not be materialized/booted on this replica."""


class _Resident:
    __slots__ = ("server", "mem_bytes", "last_used", "active")

    def __init__(self, server: Any, mem_bytes: int):
        self.server = server
        self.mem_bytes = int(mem_bytes)
        self.last_used = time.monotonic()
        self.active = 0  # submits currently between acquire and enqueue


class MultiPolicyServer:
    """Resident set of policy servers behind one replica-facing surface.

    ``loader(policy_id)`` returns a STARTED server-like object
    (`submit(features, deadline_ms=...)`, `snapshot()`,
    `hot_swap(wait=...)`, `stop()`); its weight footprint comes from
    ``mem_bytes_fn(policy_id, server)`` (default: the server's
    ``mem_bytes`` attribute, else 0 — unbudgeted). Loads are
    single-flight per policy and happen OUTSIDE the resident-set lock;
    eviction picks the least-recently-used policy with no submit in
    flight (a drained victim completes its queued work in ``stop``).
    """

    multi_policy = True

    def __init__(
        self,
        loader: Callable[[str], Any],
        catalog: Iterable[str],
        default_policy: Optional[str] = None,
        *,
        mem_budget_mb: Optional[int] = None,
        max_resident: Optional[int] = None,
        cold_load: Optional[bool] = None,
        preload: Iterable[str] = (),
        mem_bytes_fn: Optional[Callable[[str, Any], int]] = None,
    ):
        self._loader = loader
        self._catalog = list(dict.fromkeys(catalog))
        if not self._catalog:
            raise ValueError("multi-policy server needs a non-empty catalog")
        self._catalog_set = set(self._catalog)
        self._default = default_policy or self._catalog[0]
        if self._default not in self._catalog_set:
            raise ValueError(
                f"default policy {self._default!r} is not in the catalog"
            )
        if mem_budget_mb is None:
            mem_budget_mb = flags.get_int("T2R_POLICY_MEM_BUDGET")
        if max_resident is None:
            max_resident = flags.get_int("T2R_POLICY_MAX_RESIDENT")
        if cold_load is None:
            cold_load = flags.get_bool("T2R_POLICY_COLD_LOAD")
        self._mem_budget = int(mem_budget_mb) << 20 if mem_budget_mb else 0
        self._max_resident = int(max_resident) if max_resident else 0
        self._cold_load = bool(cold_load)
        self._mem_bytes_fn = mem_bytes_fn or (
            lambda pid, server: int(getattr(server, "mem_bytes", 0))
        )
        self._resident: "collections.OrderedDict[str, _Resident]" = (
            collections.OrderedDict()
        )
        self._evicted: set = set()
        self._counters = {
            "policy_loads": 0,
            "policy_cold_loads": 0,
            "policy_evictions": 0,
        }
        self._lock = locksmith.make_rlock("MultiPolicyServer._lock")
        self._load_locks: Dict[str, threading.Lock] = {}
        self._closed = False
        for policy_id in preload:
            self._acquire(policy_id, cold=False)
            self._release(policy_id)

    # -- residency ---------------------------------------------------------

    def is_resident(self, policy_id: str) -> bool:
        with self._lock:
            return policy_id in self._resident

    def resident_policies(self) -> List[str]:
        """LRU order, least-recently-used first."""
        with self._lock:
            return list(self._resident)

    def policy_version(self, policy_id: str) -> int:
        with self._lock:
            res = self._resident.get(policy_id)
            server = res.server if res is not None else None
        if server is None:
            return -1
        version = getattr(server, "model_version", None)
        if version is not None:
            return int(version)
        try:
            return int(server.snapshot().get("model_version", -1))
        except Exception:
            return -1

    @property
    def model_version(self) -> int:
        return self.policy_version(self._default)

    def _acquire(self, policy_id: str, cold: bool) -> Any:
        """Resident server for `policy_id`, loading it if allowed; bumps
        the LRU clock and the active guard (pair with `_release`)."""
        if self._closed:
            raise PolicyError("multi-policy server is stopped")
        with self._lock:
            res = self._resident.get(policy_id)
            if res is not None:
                self._resident.move_to_end(policy_id)
                res.last_used = time.monotonic()
                res.active += 1
                return res.server
            if policy_id not in self._catalog_set:
                raise PolicyUnknown(
                    f"policy {policy_id!r} is not in this replica's "
                    f"catalog of {len(self._catalog)} policies"
                )
            if cold and not self._cold_load:
                if policy_id in self._evicted:
                    raise PolicyEvicted(
                        f"policy {policy_id!r} was evicted under the "
                        "memory budget and cold loads are disabled "
                        "(T2R_POLICY_COLD_LOAD=0) — route to a replica "
                        "where it is resident"
                    )
                raise PolicyUnknown(
                    f"policy {policy_id!r} is not resident and cold "
                    "loads are disabled (T2R_POLICY_COLD_LOAD=0)"
                )
            load_lock = self._load_locks.setdefault(
                policy_id,
                locksmith.make_lock(
                    f"MultiPolicyServer._load_locks[{policy_id}]",
                    budget_ms=0,  # brackets a whole model load by design
                ),
            )
        with load_lock:  # single-flight; the load runs OUTSIDE self._lock
            with self._lock:
                res = self._resident.get(policy_id)
                if res is not None:  # raced: another thread loaded it
                    self._resident.move_to_end(policy_id)
                    res.last_used = time.monotonic()
                    res.active += 1
                    return res.server
            try:
                server = self._loader(policy_id)
            except PolicyError:
                raise
            except Exception as err:
                raise PolicyLoadFailed(
                    f"loading policy {policy_id!r} failed: "
                    f"{type(err).__name__}: {err}"
                ) from err
            mem = int(self._mem_bytes_fn(policy_id, server))
            victims: List[Any] = []
            with self._lock:
                self._evict_for(mem, victims)
                res = _Resident(server, mem)
                res.active = 1
                self._resident[policy_id] = res
                self._evicted.discard(policy_id)
                self._counters["policy_loads"] += 1
                if cold:
                    self._counters["policy_cold_loads"] += 1
        for victim in victims:
            # An eviction victim failing to stop cleanly must not fail
            # the load that displaced it.
            best_effort(victim.stop)
        return server

    def _release(self, policy_id: str) -> None:
        with self._lock:
            res = self._resident.get(policy_id)
            if res is not None and res.active > 0:
                res.active -= 1

    def _evict_for(self, incoming_mem: int, victims: List[Any]) -> None:
        """Under self._lock: pop LRU idle policies until the incoming
        load fits the budget/count caps. A policy larger than the whole
        budget still loads once everything idle is out — the budget is
        eviction pressure, not an admission refusal."""

        def over() -> bool:
            if self._max_resident and (
                len(self._resident) + 1 > self._max_resident
            ):
                return True
            if self._mem_budget:
                total = sum(r.mem_bytes for r in self._resident.values())
                return total + incoming_mem > self._mem_budget
            return False

        while over():
            victim_id = None
            for pid, res in self._resident.items():  # LRU order
                if res.active == 0:
                    victim_id = pid
                    break
            if victim_id is None:
                return  # everything busy: admit over budget, retry later
            res = self._resident.pop(victim_id)
            self._evicted.add(victim_id)
            self._counters["policy_evictions"] += 1
            victims.append(res.server)

    # -- server surface ----------------------------------------------------

    def submit(
        self,
        features,
        deadline_ms: Optional[float] = None,
        policy_id: Optional[str] = None,
    ):
        policy_id = policy_id or self._default
        server = self._acquire(policy_id, cold=True)
        try:
            if deadline_ms is None:
                return server.submit(features)
            return server.submit(features, deadline_ms=deadline_ms)
        finally:
            self._release(policy_id)

    def hot_swap(
        self, wait: bool = False, policy_id: Optional[str] = None
    ) -> bool:
        """Swap ONE policy's server (default policy when unnamed). A
        non-resident policy swaps trivially: the next cold load
        materializes whatever the store now holds."""
        policy_id = policy_id or self._default
        with self._lock:
            res = self._resident.get(policy_id)
            server = res.server if res is not None else None
        if server is None:
            if policy_id not in self._catalog_set:
                raise PolicyUnknown(
                    f"cannot swap unknown policy {policy_id!r}"
                )
            return True
        return bool(server.hot_swap(wait=wait))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            resident = list(self._resident)
            counters = dict(self._counters)
            mem = {
                pid: res.mem_bytes
                for pid, res in self._resident.items()
            }
            default_res = self._resident.get(self._default)
            anchor = (
                default_res.server
                if default_res is not None
                else next(iter(self._resident.values())).server
                if self._resident
                else None
            )
        snap: Dict[str, Any] = {}
        if anchor is not None:
            try:
                snap = dict(anchor.snapshot())
            except Exception:
                snap = {}
        versions = {pid: self.policy_version(pid) for pid in resident}
        snap.update(
            {
                "multi_policy": True,
                "model_version": versions.get(self._default, -1),
                # Backend-independent placement surface (the
                # prewarm_source discipline): the router and autoscaler
                # read these off health snapshots without knowing which
                # backend produced them.
                "resident_policies": resident,
                "policy_loads": counters["policy_loads"],
                "policy_cold_loads": counters["policy_cold_loads"],
                "policy_evictions": counters["policy_evictions"],
                "policy_mem_bytes": mem,
                "policy_mem_budget_bytes": self._mem_budget,
                "policy_versions": versions,
                "default_policy": self._default,
                "catalog_size": len(self._catalog),
            }
        )
        return snap

    def stop(self) -> None:
        with self._lock:
            self._closed = True
            servers = [res.server for res in self._resident.values()]
            self._resident.clear()
        for server in servers:
            # Shutdown is best-effort per policy; one wedged backend
            # must not strand the rest.
            best_effort(server.stop)
