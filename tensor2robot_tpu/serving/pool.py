"""RemoteReplicaPool: serving replicas as independent processes on the wire.

The local fleet (serving/router.py) spawns replicas through a
multiprocessing context: mp queues, a shared shm free ring, one process
group. Those primitives are exactly what pins the fleet to one host.
This module provides the router's OTHER transport: each replica is an
independent OS process in its OWN session/process group (no inherited
mp primitives, no shared memory), started via
`python -m tensor2robot_tpu.serving.fabric`, speaking the shared
CRC-framed wire from `net/frames.py` — the same frame contract, address
discovery, and chaos sites the replay fabric runs on.

The integration point is deliberately narrow: the router's `_spawn`
asks the pool for a `(handle, link)` pair where

  * `handle` duck-types `multiprocessing.Process` (pid / is_alive /
    terminate / kill / join / exitcode) over a `subprocess.Popen`, and
  * `link` duck-types the replica request queue (`put(message)`) over a
    lazily-connected frame stream — so the router's dispatch, health
    probing, circuit breaking, rolling swap, retirement, and stop paths
    run UNCHANGED over either transport. A `put` that cannot reach the
    replica raises (the router already treats that as
    ReplicaUnavailable / a skipped probe); replies, health snapshots,
    and lifecycle messages stream back on the same connection into the
    router's response queue.

Respawn re-resolution is incarnation-stamped: every spawn of replica
index `i` gets the next incarnation number, the replica publishes
`{host, port, pid, incarnation}` under `<root>/r<i>/transport.json`
only once its server factory has succeeded, and the new link refuses
any address published by an older incarnation — the dead predecessor's
stale file reads as "not up yet" (retry), never as a connectable
address. The router's health probes double as the re-resolution loop:
each probe `put` retries the connect, and the first one to land after
the fresh publication triggers the `("hello",)` handshake whose
`("started", ...)` reply readmits the replica to routing.

Chaos: the link threads the replica's scope (`z<zone>.r<i>`, or
`r<i>` without a zone) as `peer` through BOTH directions — `net_send`
before every frame to the replica, `net_recv` after every frame heard
from it — so one `partition:z1.r0+z1.r1` clause cuts a zone's links
symmetrically, exactly as replay shard partitions behave.
"""

from __future__ import annotations

import logging
import os
import pickle
import queue
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tensor2robot_tpu.net import frames
from tensor2robot_tpu.serving.replica import ReplicaSpec
from tensor2robot_tpu.testing import chaos, locksmith
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "RemoteProcessHandle",
    "RemoteReplicaPool",
    "ReplicaLink",
    "ResponseQueue",
    "replica_root",
    "replica_scope",
]


def replica_scope(index: int, spec: ReplicaSpec,
                  zone: Optional[str] = None) -> str:
    """The chaos scope a fabric replica runs under — and therefore the
    peer name every chaos clause must use to target its link. One
    definition shared by the pool (link side) and the replica entry
    (receive side), so a partition plan always cuts both directions of
    the same link. Scope charset: no `:+;/` (the plan grammar's
    delimiters); dots are safe."""
    if spec.scope is not None:
        return spec.scope
    return f"z{zone}.r{index}" if zone else f"r{index}"


def replica_root(root: str, index: int) -> str:
    """Where replica `index` publishes its transport address."""
    return os.path.join(root, f"r{index}")


class ResponseQueue(queue.Queue):
    """Thread-queue stand-in for the router's mp response queue: same
    `put`/`get(timeout=)` surface, plus the no-op mp.Queue teardown
    methods the router's stop() calls unconditionally."""

    def close(self) -> None:
        pass

    def cancel_join_thread(self) -> None:
        pass


class RemoteProcessHandle:
    """`multiprocessing.Process` duck-type over a detached subprocess.

    The child runs in its own session (`start_new_session=True`), so it
    shares no process group, controlling terminal, or mp state with the
    router — the "independent processes" the cross-host model requires;
    signals here are explicit, never inherited."""

    def __init__(self, popen: "subprocess.Popen"):
        self._popen = popen

    @property
    def pid(self) -> int:
        return self._popen.pid

    @property
    def exitcode(self) -> Optional[int]:
        return self._popen.poll()

    def is_alive(self) -> bool:
        return self._popen.poll() is None

    def terminate(self) -> None:
        best_effort(self._popen.terminate)

    def kill(self) -> None:
        best_effort(self._popen.kill)

    def join(self, timeout: Optional[float] = None) -> None:
        try:
            self._popen.wait(timeout)
        except subprocess.TimeoutExpired:
            pass


class ReplicaLink:
    """Request-queue duck-type over one replica's frame stream.

    `put(message)` lazily (re)connects — resolving the replica's
    CURRENT published address, refusing stale incarnations — performs
    the `("hello",)` identity handshake on a fresh connection, and
    writes the message as one frame. Every frame the replica sends back
    (replies, health, started, swapped, stopped) is read by a
    per-connection reader thread and handed to `deliver` (the router's
    response queue). ANY wire failure tears the connection down and, on
    `put`, raises a typed TransportError the router's existing failure
    handling absorbs; the NEXT put starts clean. Lock order: the router
    calls `put` while holding its own lock, and this link's lock is
    always innermost (nothing here calls back into the router)."""

    def __init__(
        self,
        root: str,
        peer: str,
        deliver: Callable[[tuple], None],
        min_incarnation: int = 0,
        connect_timeout_s: float = 2.0,
    ):
        self.root = root
        self.peer = peer
        self.min_incarnation = int(min_incarnation)
        self._deliver = deliver
        self._connect_timeout_s = connect_timeout_s
        self._lock = locksmith.make_lock("ReplicaLink._lock")
        self._sock: Optional[socket.socket] = None
        self._closed = False

    def _teardown_locked(self) -> None:
        if self._sock is not None:
            best_effort(self._sock.close)
            self._sock = None

    def _ensure_connected_locked(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        info = frames.read_address_info(self.root)
        if info is None:
            raise frames.TransportError(
                f"no transport address published under {self.root} "
                "(replica not up yet, or respawning)"
            )
        if info["incarnation"] < self.min_incarnation:
            raise frames.TransportError(
                f"stale transport address under {self.root}: published by "
                f"incarnation {info['incarnation']}, expecting >= "
                f"{self.min_incarnation} (predecessor's file; the respawn "
                "has not published yet)"
            )
        address = (info["host"], info["port"])
        try:
            sock = socket.create_connection(
                address, timeout=self._connect_timeout_s
            )
        except OSError as err:
            raise frames.TransportError(
                f"connect to replica at {address} failed: {err}"
            ) from err
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        self._sock = sock
        reader = threading.Thread(
            target=self._read_loop, args=(sock,),
            name=f"t2r-link-{self.peer}", daemon=True,
        )
        # Identity handshake BEFORE the caller's message: the replica
        # answers ("started", index, version, pid), which is what
        # (re)admits it to routing — the socket fabric's equivalent of
        # the mp replica's proactive started post.
        try:
            frames.write_frame(sock, ("hello",), peer=self.peer)
        except frames.TransportError:
            self._teardown_locked()
            raise
        reader.start()
        return sock

    def _read_loop(self, sock: socket.socket) -> None:
        while True:
            try:
                message = frames.read_frame(sock)
            except frames.TransportError:
                break  # torn/closed/bad frame: the stream dies whole
            # Receive side of the partition model: frames FROM a
            # partitioned replica are dropped too, so a zone partition
            # is symmetric (in-flight replies do not leak out of it).
            hit = chaos.maybe_fire("net_recv", peer=self.peer)
            if hit is not None:
                if hit.action in ("drop", "partition"):
                    continue
                if hit.action == "corrupt":
                    break  # CRC-equivalent: tear the stream down
            try:
                self._deliver(message)
            except Exception:
                _log.exception("link %s: delivery failed", self.peer)
        with self._lock:
            if self._sock is sock:
                self._teardown_locked()
            else:
                best_effort(sock.close)

    def put(self, message: tuple) -> None:
        """Send one router->replica message; raises TransportError when
        the replica is unreachable (unpublished, stale incarnation,
        refused, or the write fails). A chaos drop/partition at
        `net_send` consumes the message silently — the wire accepted
        it, the packet died; deadlines and retries do their job."""
        with self._lock:
            if self._closed:
                raise frames.TransportError(
                    f"link to {self.peer} is closed"
                )
            sock = self._ensure_connected_locked()
            try:
                frames.write_frame(sock, message, peer=self.peer)
            except frames.TransportError:
                self._teardown_locked()
                raise

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._teardown_locked()

    def cancel_join_thread(self) -> None:
        pass  # mp.Queue teardown parity for the router's stop()


class RemoteReplicaPool:
    """Spawns and re-spawns fabric replicas, one incarnation at a time.

    Owns the per-index incarnation counters and the live links. `spawn`
    is the router `_spawn`'s delegate: it pickles the spec next to the
    replica's address directory, launches the interpreter entry in a
    fresh session, and returns the `(handle, link)` pair the router
    slots straight into `replica.proc` / `replica.request_q`."""

    def __init__(
        self,
        root: str,
        deliver: Callable[[tuple], None],
        zone: Optional[str] = None,
        connect_timeout_s: float = 2.0,
    ):
        self.root = root
        self.zone = zone
        self._deliver = deliver
        self._connect_timeout_s = connect_timeout_s
        self._lock = locksmith.make_lock("RemoteReplicaPool._lock")
        self._incarnations: Dict[int, int] = {}
        self._links: Dict[int, ReplicaLink] = {}
        self._procs: List[RemoteProcessHandle] = []
        # index -> (spec object, path): the ReplicaSpec is immutable
        # per index, so it is pickled ONCE and every respawn
        # incarnation reuses the path instead of re-serializing a
        # model-sized spec on the respawn hot path.
        self._specs: Dict[int, Tuple[ReplicaSpec, str]] = {}

    def spawn(
        self, index: int, spec: ReplicaSpec
    ) -> Tuple[RemoteProcessHandle, ReplicaLink]:
        with self._lock:
            incarnation = self._incarnations.get(index, 0) + 1
            self._incarnations[index] = incarnation
            stale = self._links.pop(index, None)
        if stale is not None:
            # The predecessor's link must die with it: a late frame off
            # the old stream is already handled as a late reply, but a
            # reconnect there could resurrect a retired address.
            stale.close()
        rdir = replica_root(self.root, index)
        os.makedirs(rdir, exist_ok=True)
        spec_path = os.path.join(rdir, "spec.pkl")
        with self._lock:
            cached = self._specs.get(index)
        if (
            cached is None
            or cached[0] is not spec
            or not os.path.exists(spec_path)
        ):
            # tmp+replace: a child booting off a prior incarnation's
            # path can never read a torn spec mid-write.
            tmp = f"{spec_path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(spec, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, spec_path)
            with self._lock:
                self._specs[index] = (spec, spec_path)
        args = [
            sys.executable, "-m", "tensor2robot_tpu.serving.fabric",
            "--replica",
            "--index", str(index),
            "--root", rdir,
            "--incarnation", str(incarnation),
            "--spec", spec_path,
        ]
        if self.zone is not None:
            args += ["--zone", str(self.zone)]
        popen = subprocess.Popen(args, start_new_session=True)
        handle = RemoteProcessHandle(popen)
        link = ReplicaLink(
            rdir,
            peer=replica_scope(index, spec, self.zone),
            deliver=self._deliver,
            min_incarnation=incarnation,
            connect_timeout_s=self._connect_timeout_s,
        )
        with self._lock:
            self._links[index] = link
            self._procs.append(handle)
        return handle, link

    def incarnation(self, index: int) -> int:
        with self._lock:
            return self._incarnations.get(index, 0)

    def close(self) -> None:
        with self._lock:
            links = list(self._links.values())
            self._links.clear()
        for link in links:
            link.close()
